"""Device-memory and program accounting for serving (PR 15 tentpole).

HBM residency became the scarce resource the platform optimizes — PR 14
packs weights to int4/int8, PR 12 pins bucketed KV/state lane buffers,
PR 11 parks an AOT executable per program — but nothing MEASURED what is
actually resident.  ``ResourceLedger`` decomposes a deployment's device
memory into its three structural components, each derived from the same
source of truth the optimizing PR introduced:

- **weights** — ``quantize.weight_bytes`` over the model's live params
  (+ state) tree: every leaf at its STORED dtype, so an int4-quantized
  deployment reads ~8x below its float twin (the PR 14 structural claim,
  now a live gauge instead of a bench printout).
- **kv_state** — the generation scheduler's committed lane buffers
  (``ContinuousBatcher.state_bytes()``): fixed ``(max_active, bucket)``
  buffers per lane, the exact allocation PR 12's bucket geometry pins.
- **executables** — AOT executable count + best-effort generated-code
  size from the PR 11 cache (``aot_stats`` / ``aot_memory_bytes``).

The ledger feeds three surfaces: ``serving_hbm_bytes{component=}``
gauges in the engine registry, the ``resources`` block of the health doc
(fleet-aggregated by ``serving/fleet.py``), and the per-program
execution counters keyed by warm-up-manifest entry — the input the
ROADMAP's multi-model serving needs before it can apportion HBM between
co-resident models.

Pure numpy + the quantize helpers: importable without touching a device.
"""

from __future__ import annotations

from typing import Dict, Optional


def _tree_bytes(tree) -> int:
    if not tree:
        return 0
    from analytics_zoo_tpu.inference.quantize import weight_bytes
    return int(weight_bytes(tree))


class ResourceLedger:
    """One deployment's device-memory decomposition.  ``doc()`` is cheap
    enough for every /healthz scrape: the weights component is cached per
    AOT epoch (the tree only changes when the program family does), the
    lane/executable reads are O(lanes + cached programs)."""

    COMPONENTS = ("weights", "kv_state", "executables")

    def __init__(self, model, batcher=None):
        self.model = model
        self.batcher = batcher
        self._weights_cache: Optional[tuple] = None   # (epoch, bytes)
        self._qbits_cache: Optional[tuple] = None     # (epoch, bits)
        # executables only change when a program compiles: key the
        # best-effort memory_analysis sweep by (epoch, cached count) so
        # a steady-state scrape never re-walks the backend per program
        self._code_cache: Optional[tuple] = None      # (epoch, n, bytes)

    # -- components ----------------------------------------------------------
    def weights_bytes(self) -> int:
        epoch = getattr(self.model, "_aot_epoch", None)
        if self._weights_cache is not None \
                and self._weights_cache[0] == epoch:
            return self._weights_cache[1]
        try:
            n = _tree_bytes(getattr(self.model, "_params", None)) \
                + _tree_bytes(getattr(self.model, "_state", None))
        except Exception:  # noqa: BLE001 — bridge models, exotic leaves
            n = 0
        self._weights_cache = (epoch, n)
        return n

    def kv_state_bytes(self) -> int:
        if self.batcher is None:
            return 0
        try:
            return int(self.batcher.state_bytes())
        except Exception:  # noqa: BLE001 — mid-construction race
            return 0

    def kv_state_doc(self) -> Optional[Dict]:
        """The kv_state decomposition (PR 18): ``{lanes, paged_pool,
        scales, aux, total}`` from the scheduler, or None for a batcher
        without the breakdown (or no batcher at all)."""
        fn = getattr(self.batcher, "state_bytes_doc", None)
        if not callable(fn):
            return None
        try:
            return dict(fn())
        except Exception:  # noqa: BLE001 — mid-construction race
            return None

    def executables(self) -> Dict:
        stats = {"count": 0, "code_bytes": None, "programs": {}}
        aot_stats = getattr(self.model, "aot_stats", None)
        if callable(aot_stats):
            try:
                s = aot_stats()
                stats["count"] = int(s.get("cached_programs", 0))
                stats["programs"] = dict(s.get("programs") or {})
            except Exception:  # noqa: BLE001
                pass
        mem = getattr(self.model, "aot_memory_bytes", None)
        if callable(mem):
            epoch = getattr(self.model, "_aot_epoch", None)
            key = (epoch, stats["count"])
            if self._code_cache is not None \
                    and self._code_cache[:2] == key:
                stats["code_bytes"] = self._code_cache[2]
            else:
                try:
                    stats["code_bytes"] = mem()
                except Exception:  # noqa: BLE001
                    stats["code_bytes"] = None
                self._code_cache = key + (stats["code_bytes"],)
        if self.batcher is not None:
            # the scheduler's compiled program set (prefill/insert/decode)
            # rides the same accounting, keyed by its own program names
            try:
                gs = self.batcher.program_stats()
                stats["count"] += int(gs.get("count", 0))
                stats["programs"].update(gs.get("programs") or {})
            except Exception:  # noqa: BLE001
                pass
        return stats

    # -- surfaces ------------------------------------------------------------
    def doc(self) -> Dict:
        """The health-doc ``resources`` block."""
        w = self.weights_bytes()
        kv = self.kv_state_bytes()
        exes = self.executables()
        code = exes.get("code_bytes")
        out = {
            "weights_bytes": w,
            "kv_state_bytes": kv,
            "executables": exes,
            "total_bytes": w + kv + (code or 0),
        }
        kvd = self.kv_state_doc()
        if kvd is not None:
            out["kv_state"] = kvd
        # cached per epoch like weights: quantized_bits flattens the
        # whole params tree, and this runs on every /healthz scrape
        epoch = getattr(self.model, "_aot_epoch", None)
        if self._qbits_cache is None or self._qbits_cache[0] != epoch:
            qbits = None
            try:
                from analytics_zoo_tpu.inference.quantize import (
                    quantized_bits)
                qbits = quantized_bits(getattr(self.model, "_params",
                                               None) or {})
            except Exception:  # noqa: BLE001
                pass
            self._qbits_cache = (epoch, qbits)
        if self._qbits_cache[1] is not None:
            out["quantized_bits"] = self._qbits_cache[1]
        return out

    def hbm_bytes(self, component: str) -> float:
        """Gauge provider for ``serving_hbm_bytes{component=}``."""
        if component == "weights":
            return float(self.weights_bytes())
        if component == "kv_state":
            return float(self.kv_state_bytes())
        if component == "executables":
            return float(self.executables().get("code_bytes") or 0)
        return 0.0
