"""mmap'd weight store — share one on-disk copy of the params across
replicas (PR 11 zero cold start).

The `.npz` weights file (utils/serialization.py) is a zip: every boot
re-reads and re-copies every byte into fresh heap arrays, once per replica.
This store lays the SAME flattened pytree out as one bare ``.npy`` file per
leaf plus a ``manifest.json``, so a replica boot restores leaves with
``np.load(mmap_mode="r")``:

- **no deserialization copy** — the mapping is established without touching
  the weight bytes; pages fault in lazily when `jax.device_put` DMAs them
  to the device;
- **one host copy per MACHINE, not per replica** — N replicas mapping the
  same files share page cache, so scaling out does not multiply host RSS
  by the checkpoint size;
- **idempotent export** — ``save_store`` fingerprints the leaf set
  (paths/shapes/dtypes + content sample) and skips the rewrite when the
  store already matches, so "persist once per deployment" is a cheap call
  every replica may race on.

Caveats (documented in the README): writes go through a temp dir + atomic
rename, but readers mapping a store must not have it rewritten under them
(the manager exports before replicas spawn); on NFS, mmap consistency is
the filesystem's weak spot — keep the store on a local disk.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
import time
from typing import Dict, Optional

import numpy as np

logger = logging.getLogger(__name__)

MANIFEST = "manifest.json"
_FORMAT = 1


def _flatten(tree) -> Dict[str, np.ndarray]:
    from analytics_zoo_tpu.utils.serialization import _flatten_with_paths
    return _flatten_with_paths(tree)


def _leaf_file(index: int) -> str:
    return f"leaf-{index:05d}.npy"


def _fingerprint(flat: Dict[str, np.ndarray]) -> str:
    """Content identity covering EVERY byte of every leaf: paths/shapes/
    dtypes hashed with sha256, contents folded in as a per-leaf crc32 —
    ~GB/s, so the idempotence check stays cheap on multi-GB checkpoints,
    while a weight change anywhere in a leaf (including mid-array, which
    a head+tail sample would miss) forces the re-export."""
    import zlib
    h = hashlib.sha256()
    for key in sorted(flat):
        # order="C" (not ascontiguousarray, which silently promotes 0-d
        # scalars to (1,)): quantized trees carry 0-d scale leaves whose
        # shape must round-trip exactly (PR 14)
        a = np.asarray(flat[key], order="C")
        h.update(key.encode())
        h.update(str(a.shape).encode())
        h.update(np.dtype(a.dtype).str.encode())
        crc = zlib.crc32(memoryview(a.reshape(-1).view(np.uint8)))
        h.update(crc.to_bytes(4, "little"))
    return h.hexdigest()


def read_manifest(store_dir: str) -> Optional[Dict]:
    try:
        with open(os.path.join(store_dir, MANIFEST)) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) and doc.get("leaves") else None
    except (OSError, ValueError):
        return None


def is_store(path: str) -> bool:
    return os.path.isdir(path) and read_manifest(path) is not None


def save_store(store_dir: str, tree) -> Dict:
    """Persist ``tree`` as the mmap'd store at ``store_dir``.  Returns the
    manifest.  Idempotent: a store whose fingerprint already matches is
    left untouched (``manifest["skipped"] = True`` on the return value),
    so every replica of a deployment can call this and only the first
    pays the write."""
    flat = _flatten(tree)
    fp = _fingerprint(flat)
    existing = read_manifest(store_dir)
    if existing and existing.get("fingerprint") == fp:
        existing["skipped"] = True
        return existing
    parent = os.path.dirname(os.path.abspath(store_dir)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".weightstore-", dir=parent)
    leaves = {}
    total = 0
    try:
        for i, key in enumerate(sorted(flat)):
            a = np.asarray(flat[key], order="C")   # preserves 0-d shapes
            np.save(os.path.join(tmp, _leaf_file(i)), a,
                    allow_pickle=False)
            leaves[key] = {"file": _leaf_file(i),
                           "shape": list(a.shape),
                           "dtype": np.dtype(a.dtype).str}
            total += a.nbytes
        manifest = {"format": _FORMAT, "fingerprint": fp,
                    "leaves": leaves, "total_bytes": total}
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1)
        if os.path.isdir(store_dir):
            # replace atomically-ish: rename the old store aside first so
            # a reader never sees a half-written directory
            old = store_dir.rstrip("/\\") + ".old"
            if os.path.isdir(old):
                import shutil
                shutil.rmtree(old, ignore_errors=True)
            os.replace(store_dir, old)
            os.replace(tmp, store_dir)
            import shutil
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.replace(tmp, store_dir)
    except BaseException:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    logger.info("weightstore: persisted %d leaf file(s), %.1f MiB at %s",
                len(leaves), total / 1048576.0, store_dir)
    return manifest


def load_flat(store_dir: str, mmap: bool = True) -> Dict[str, np.ndarray]:
    """The store's leaves as a ``{path: array}`` dict; with ``mmap`` each
    array is a read-only ``np.memmap`` view (zero bytes read until pages
    fault in, page cache shared across processes).

    Readers can race :func:`save_store`'s atomic dir-swap rewrite: between
    its two ``os.replace`` calls the store path briefly does not exist
    (ENOENT), and a manifest read before the swap can pair with a leaf
    read after it (dtype/shape mismatch → ``ValueError``).  Both windows
    are microseconds wide and the post-swap store is complete, so the load
    retries ONCE with a short backoff before letting the error escape —
    a genuinely missing or corrupt store still fails loudly."""
    # resolve the path once per load: every manifest and leaf read below
    # must refer to the same directory even if the caller's cwd (or a
    # symlink along the way) changes mid-load
    store_dir = os.path.abspath(store_dir)
    try:
        return _load_flat_once(store_dir, mmap)
    except (OSError, ValueError):
        time.sleep(0.05)
        return _load_flat_once(store_dir, mmap)


def _load_flat_once(store_dir: str, mmap: bool) -> Dict[str, np.ndarray]:
    manifest = read_manifest(store_dir)
    if manifest is None:
        raise FileNotFoundError(
            f"{store_dir!r} is not a weight store (no {MANIFEST})")
    mode = "r" if mmap else None
    out = {}
    for key, meta in manifest["leaves"].items():
        a = np.load(os.path.join(store_dir, meta["file"]),
                    mmap_mode=mode, allow_pickle=False)
        # manifest dtype/shape check (PR 14): quantized stores carry
        # int8/uint8-packed and f32-scale leaves whose bit patterns must
        # survive VERBATIM — a leaf file that drifted from its manifest
        # entry (partial rewrite, wrong-store mixup) must fail loudly,
        # never dequantize garbage
        if np.dtype(a.dtype).str != meta["dtype"] \
                or list(a.shape) != list(meta["shape"]):
            raise ValueError(
                f"weight store {store_dir}: leaf {key!r} is "
                f"{a.shape}/{np.dtype(a.dtype).str} on disk but the "
                f"manifest records {meta['shape']}/{meta['dtype']}")
        out[key] = a
    return out


def _natural(path: str):
    """Sort key splitting digit runs out of each path segment, so
    auto-name suffixes order numerically (dense_9 < dense_10) — plain
    lexicographic order diverges from creation order at every power-of-10
    suffix boundary and would cross-wire a positional container remap."""
    import re
    return tuple(tuple(int(p) if p.isdigit() else p
                       for p in re.split(r"(\d+)", seg))
                 for seg in path.split("/"))


def _nest(flat: Dict[str, np.ndarray]) -> dict:
    """{path: leaf} -> nested dicts keyed by path segments (the ONE
    flat-to-nested rebuild shared by load_store and load_store_nested)."""
    nested: dict = {}
    for key, val in flat.items():
        cur = nested
        parts = key.split("/")
        for part in parts[:-1]:
            cur = cur.setdefault(part, {})
        cur[parts[-1]] = val
    return nested


def load_store_nested(store_dir: str, like=None, mmap: bool = True):
    """Nested path-keyed restore for trees whose LEAF structure differs
    from any available template — the quantized-store path (PR 14): a
    store exported after ``do_quantize`` holds {W_q/W_q4, s_w/s_g, s_x}
    leaves no float init skeleton matches, so the structure must come from
    the store itself.

    With ``like``, container DIRECTORIES are remapped positionally onto
    the template's (layer auto-naming is process-global, so a template
    built after other models carries shifted name suffixes — the same
    rationale as :func:`load_store`'s positional fallback), and every leaf
    name present in BOTH a mapped container and its template counterpart
    (biases, any unquantized weight) is shape/dtype-verified; a mismatch
    raises ``KeyError`` rather than serving someone else's weights."""
    from analytics_zoo_tpu.utils.serialization import _path_str
    flat = load_flat(store_dir, mmap=mmap)
    mapping = {}
    if like is not None:
        import jax
        paths, _ = jax.tree_util.tree_flatten_with_path(like)
        tflat = {"/".join(_path_str(p) for p in path_elems): leaf
                 for path_elems, leaf in paths}
        sdirs = sorted({k.rsplit("/", 1)[0] for k in flat if "/" in k},
                       key=_natural)
        tdirs = sorted({k.rsplit("/", 1)[0] for k in tflat if "/" in k},
                       key=_natural)
        if sdirs != tdirs:
            if len(sdirs) != len(tdirs):
                raise KeyError(
                    f"store {store_dir}: {len(sdirs)} containers cannot "
                    f"map onto the template's {len(tdirs)}")
            mapping = dict(zip(sdirs, tdirs))
        # verify every leaf name present in BOTH a (possibly remapped)
        # container and its template counterpart — identity mappings
        # included, so a same-named store from a different topology still
        # fails loudly here instead of at first predict
        for skey, leaf in flat.items():
            if "/" not in skey:
                continue
            sdir, name = skey.rsplit("/", 1)
            tdir = mapping.get(sdir, sdir)
            want = tflat.get(f"{tdir}/{name}")
            if want is not None and (
                    tuple(np.shape(want)) != tuple(leaf.shape)
                    or np.dtype(getattr(want, "dtype", np.float32))
                    != leaf.dtype):
                raise KeyError(
                    f"store {store_dir}: container {sdir!r} -> {tdir!r} — "
                    f"shared leaf {name!r} is {leaf.shape}/{leaf.dtype}, "
                    f"template expects {np.shape(want)}")
        if mapping:
            logger.warning(
                "weightstore: %s restored with remapped container names "
                "(auto-named layers built in a different order?); shared "
                "leaves verified shape/dtype", store_dir)
    if mapping:
        flat = {(f"{mapping[k.rsplit('/', 1)[0]]}/{k.rsplit('/', 1)[1]}"
                 if "/" in k else k): v for k, v in flat.items()}
    return _nest(flat)


def graft_containers(skeleton, got, require_leaves: bool = True):
    """Rebuild ``skeleton``'s dict structure around the real leaves in
    ``got``: container dicts (including EMPTY ones — paramless/stateless
    layers' slots, which a flattened store cannot represent) come from the
    skeleton; skeleton leaves may be abstract ``eval_shape`` values and
    are never returned.  With ``require_leaves`` every skeleton leaf
    position must exist in ``got``; without it, missing skeleton leaves
    are allowed — the quantized-params case, where {W_q4, s_g} replace the
    skeleton's {W}."""
    if not isinstance(skeleton, dict):
        return got
    out = dict(got) if isinstance(got, dict) else {}
    for key, val in skeleton.items():
        if isinstance(val, dict):
            out[key] = graft_containers(val, out.get(key, {}),
                                        require_leaves=require_leaves)
        elif key not in out and require_leaves:
            raise KeyError(f"leaf {key!r} missing from the restored tree")
    return out


def load_store(store_dir: str, like=None, mmap: bool = True):
    """Restore the pytree from the store.  ``like`` (a template tree, e.g.
    a freshly-initialized model's ``{"params": ..., "state": ...}``)
    rebuilds the exact structure; without it a nested dict keyed by path
    segments is returned."""
    import jax
    from analytics_zoo_tpu.utils.serialization import _path_str
    flat = load_flat(store_dir, mmap=mmap)
    if like is None:
        return _nest(flat)
    paths, treedef = jax.tree_util.tree_flatten_with_path(like)
    like_keys = ["/".join(_path_str(p) for p in path_elems)
                 for path_elems, _ in paths]
    if all(k in flat for k in like_keys):
        return jax.tree_util.tree_unflatten(
            treedef, [flat[k] for k in like_keys])
    # positional fallback: layer auto-naming is process-global, so a
    # template built AFTER other models in the same process carries
    # shifted name suffixes (dense_3/W for the store's dense_1/W).  The
    # NATURALLY-sorted leaf order is name-stable (numeric suffixes order
    # as numbers, so a _9/_10 boundary cannot cross-wire the zip); accept
    # it only when every leaf's shape+dtype matches exactly, else fail
    # loudly.
    store_keys = sorted(flat, key=_natural)
    if len(store_keys) != len(like_keys):
        raise KeyError(
            f"store {store_dir} has {len(store_keys)} leaves, template "
            f"expects {len(like_keys)}")
    order = sorted(range(len(like_keys)),
                   key=lambda i: _natural(like_keys[i]))
    leaves: list = [None] * len(like_keys)
    template_leaves = [leaf for _, leaf in paths]
    for skey, i in zip(store_keys, order):
        want = template_leaves[i]
        got = flat[skey]
        if tuple(np.shape(want)) != tuple(got.shape) or \
                np.dtype(getattr(want, "dtype", np.float32)) != got.dtype:
            raise KeyError(
                f"missing leaf {like_keys[i]!r} in store {store_dir} and "
                f"positional match failed ({skey!r} is "
                f"{got.shape}/{got.dtype})")
        leaves[i] = got
    logger.warning(
        "weightstore: %s restored by position (template leaf names did "
        "not match — auto-named layers built in a different order?); "
        "shapes and dtypes verified leaf-for-leaf", store_dir)
    return jax.tree_util.tree_unflatten(treedef, leaves)
