"""Post-training int8 quantization for the inference path (VERDICT r2 #5).

The reference's optimized-inference story is OpenVINO int8 with VNNI
(pipeline/inference/OpenVinoInferenceSupportive.scala:1-631,
OpenVINOModel.scala:1-214) — calibrate on sample data, quantize weights and
activations to int8, run on the CPU's int8 dot units.  The TPU-native
equivalent implemented here targets the MXU's s8 x s8 -> s32 path (2x the
bf16 peak on v5e):

  * weights: symmetric per-OUTPUT-CHANNEL int8 (w_q = round(w / s_w),
    s_w = absmax_channel / 127) — standard PTQ, no accuracy tuning knobs;
  * activations: symmetric per-tensor scale from a calibration sweep
    (absmax of each quantizable layer's input over the calibration batches);
  * compute: int8 matmul/conv with int32 accumulation, dequantized by
    s_x * s_w, bias added in f32 (see Dense.call / _ConvND.call "W_q" path).

Only Dense and the _ConvND family are quantized; everything else (BN folded
stats, pooling, activations) stays in the float path.  Layers the calibration
sweep never saw (absmax missing/zero) are left in float.

Usage:
    absmax = calibrate(model, params, state, calib_inputs)
    qparams = quantize_params(model, params, absmax)
    y = model.apply(qparams, state, x, training=False)   # int8 inference
or via InferenceModel.do_quantize(calib_inputs).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.nn.layers.conv import _ConvND
from analytics_zoo_tpu.nn.layers.core import Dense

QUANTIZABLE = (Dense, _ConvND)


def _target_layers(model, params) -> List[Tuple[object, dict]]:
    """(layer, its params) for every quantizable layer, recursing into
    containers (Sequential.layers_list / graph Model.graph_layers)."""
    out = []

    def walk(layer, p):
        if isinstance(layer, QUANTIZABLE) and isinstance(p, dict) \
                and ("W" in p or "W_q" in p):
            out.append((layer, p))
            return
        subs = getattr(layer, "graph_layers", None) or \
            getattr(layer, "layers_list", None)
        if subs:
            for sub in subs:
                if isinstance(p, dict) and sub.name in p:
                    walk(sub, p[sub.name])

    walk(model, params)
    return out


def calibrate(model, params, state, calib_inputs) -> Dict[str, float]:
    """Run `calib_inputs` (one batch or a list of batches) through the model
    EAGERLY, recording the absmax of every quantizable layer's input.
    Returns {layer_name: absmax}."""
    records: Dict[str, float] = {}
    targets = [l for l, _ in _target_layers(model, params)]
    saved = []
    for layer in targets:
        orig = layer.call

        def wrapped(p, x, *, training=False, rng=None,
                    _name=layer.name, _orig=orig):
            a = float(jnp.max(jnp.abs(x)))
            records[_name] = max(records.get(_name, 0.0), a)
            return _orig(p, x, training=training, rng=rng)

        layer.call = wrapped
        saved.append((layer, orig))
    try:
        batches_ = calib_inputs if isinstance(calib_inputs, list) \
            else [calib_inputs]
        for xb in batches_:
            model.apply(params, state, xb, training=False)
    finally:
        for layer, orig in saved:
            try:
                del layer.call          # restore the class method
            except AttributeError:
                layer.call = orig
    return records


def quantize_params(model, params, absmax: Dict[str, float]):
    """Return a new params pytree with quantizable layers' weights replaced by
    {"W_q" int8, "s_w" f32 per-out-channel, "s_x" f32 scalar, "b"?}."""
    def copy_tree(p):
        return {k: copy_tree(v) if isinstance(v, dict) else v
                for k, v in p.items()}

    qp = copy_tree(params)

    def locate(p, name):
        # find the sub-dict for `name` within the (possibly nested) params
        if name in p:
            return p
        for v in p.values():
            if isinstance(v, dict):
                found = locate(v, name)
                if found is not None:
                    return found
        return None

    for layer, _ in _target_layers(model, params):
        a = absmax.get(layer.name, 0.0)
        if a <= 0.0:
            continue                     # never calibrated: leave in float
        holder = locate(qp, layer.name)
        lp = holder[layer.name]
        if "W" not in lp:
            # already quantized: re-calibration refreshes the activation scale
            lp["s_x"] = jnp.asarray(a / 127.0, jnp.float32)
            continue
        W = np.asarray(lp["W"], np.float32)
        red = tuple(range(W.ndim - 1))   # all but the output-channel axis
        s_w = np.maximum(np.abs(W).max(axis=red), 1e-12) / 127.0
        W_q = np.clip(np.round(W / s_w), -127, 127).astype(np.int8)
        new = {"W_q": jnp.asarray(W_q),
               "s_w": jnp.asarray(s_w, jnp.float32),
               "s_x": jnp.asarray(a / 127.0, jnp.float32)}
        if "b" in lp:
            new["b"] = lp["b"]
        holder[layer.name] = new
    return qp


def quantize(model, params, state, calib_inputs):
    """calibrate + quantize_params in one call."""
    absmax = calibrate(model, params, state, calib_inputs)
    return quantize_params(model, params, absmax)
