"""Post-training weight quantization for the inference path (PR 14).

The reference's optimized-inference story is OpenVINO int8 with VNNI
(pipeline/inference/OpenVinoInferenceSupportive.scala:1-631,
OpenVINOModel.scala:1-214) — calibrate on sample data, quantize weights and
activations to int8, run on the CPU's int8 dot units.  The TPU-native
equivalent here produces weights that stay COMPACT in HBM and serve through
the fused-dequant kernels in ``ops/quant_matmul.py``:

  * **W8A8** (``bits=8``): symmetric per-OUTPUT-CHANNEL int8 weights
    (w_q = round(w / s_w), s_w = absmax_channel / 127) + symmetric
    per-tensor activation scales from a calibration sweep — compute is
    s8 x s8 -> s32 on the MXU, dequantized by ``s_x * s_w`` on the output
    tile (~4x less weight HBM per predict than f32).
  * **W4A16** (``bits=4``): weight-only symmetric int4 with GROUP-WISE
    scales along the contraction axis (two weights per byte,
    ``group_size`` rows per scale) — activations stay 16/32-bit, ~8x less
    weight HBM, the usual int4 recipe for memory-bound serving.

Calibration (``calibrate`` / ``calibrate_featureset``) records each
quantizable layer's input magnitude keyed by its PATH in the params tree
(two same-named layers in different containers calibrate independently —
the bare-name keying this replaces shared one absmax between them and
quantized whichever sub-dict a depth-first search found first).  Next to
plain absmax, ``percentile=99.9`` clips the activation range at that
percentile of |x| — outlier-robust scales for heavy-tailed activations.

Only Dense and the _ConvND family are quantized; everything else (BN folded
stats, pooling, activations) stays in the float path.  For W8A8, layers the
calibration sweep never saw (absmax missing/zero) are left in float; W4A16
is weight-only, so no calibration is required.

Usage:
    absmax = calibrate(model, params, state, calib_inputs)       # or
    absmax = calibrate_featureset(model, params, state, fs, n_batches=8)
    qparams = quantize_params(model, params, absmax)             # int8
    qparams = quantize_params(model, params, {}, bits=4)         # int4
    y = model.apply(qparams, state, x, training=False)
or via InferenceModel.do_quantize(calib, bits=8|4).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.nn.layers.conv import _ConvND
from analytics_zoo_tpu.nn.layers.core import Dense

QUANTIZABLE = (Dense, _ConvND)

# leaves the quantizer emits; weight-byte accounting + "already quantized"
# detection key off these
QUANT_LEAVES = ("W_q", "W_q4", "s_w", "s_g", "s_x")

# per-layer cap on the |x| sample kept for percentile calibration: enough
# for a stable tail estimate, bounded regardless of batch count/size
_PCTL_SAMPLE = 8192


def _target_layers(model, params) -> List[Tuple[object, dict, str]]:
    """(layer, its params, path) for every quantizable layer, recursing
    into containers (Sequential.layers_list / graph Model.graph_layers).
    ``path`` is the slash-joined key chain inside ``params`` — the
    collision-proof identity two same-named layers in different containers
    do not share."""
    out = []

    def walk(layer, p, path):
        if isinstance(layer, QUANTIZABLE) and isinstance(p, dict) \
                and ("W" in p or "W_q" in p or "W_q4" in p):
            out.append((layer, p, path or layer.name))
            return
        subs = getattr(layer, "graph_layers", None) or \
            getattr(layer, "layers_list", None)
        if subs:
            for sub in subs:
                if isinstance(p, dict) and sub.name in p:
                    walk(sub, p[sub.name],
                         f"{path}/{sub.name}" if path else sub.name)

    walk(model, params, "")
    return out


def calibrate(model, params, state, calib_inputs,
              percentile: Optional[float] = None) -> Dict[str, float]:
    """Run ``calib_inputs`` (one batch or a list of batches) through the
    model EAGERLY, recording each quantizable layer's input magnitude.
    Returns ``{layer_path: clip}`` where clip is the absmax (default) or,
    with ``percentile=p``, the p-th percentile of |x| over the sweep —
    robust scales when a few outliers would otherwise stretch the int8
    range over mostly-empty codes."""
    if percentile is not None and not (0.0 < float(percentile) <= 100.0):
        raise ValueError(f"percentile={percentile!r}: expected (0, 100]")
    records: Dict[str, float] = {}
    samples: Dict[str, List[np.ndarray]] = {}
    saved = []
    for layer, _, path in _target_layers(model, params):
        orig = layer.call

        def wrapped(p, x, *, training=False, rng=None,
                    _path=path, _orig=orig):
            ax = jnp.abs(x)
            a = float(jnp.max(ax))
            records[_path] = max(records.get(_path, 0.0), a)
            if percentile is not None:
                flat = np.asarray(ax, np.float32).ravel()
                stride = max(1, flat.size // _PCTL_SAMPLE)
                kept = samples.setdefault(_path, [])
                kept.append(flat[::stride][:_PCTL_SAMPLE])
                if sum(c.size for c in kept) > 4 * _PCTL_SAMPLE:
                    # fold down so the retained sample stays bounded over
                    # arbitrarily long calibration sweeps, not per batch
                    merged = np.concatenate(kept)
                    st = max(1, merged.size // _PCTL_SAMPLE)
                    kept[:] = [merged[::st][:_PCTL_SAMPLE]]
            return _orig(p, x, training=training, rng=rng)

        layer.call = wrapped
        saved.append((layer, orig))
    try:
        batches_ = calib_inputs if isinstance(calib_inputs, list) \
            else [calib_inputs]
        for xb in batches_:
            model.apply(params, state, xb, training=False)
    finally:
        for layer, orig in saved:
            try:
                del layer.call          # restore the class method
            except AttributeError:
                layer.call = orig
    if percentile is not None:
        for path, chunks in samples.items():
            clip = float(np.percentile(np.concatenate(chunks),
                                       float(percentile)))
            # the clip can only TIGHTEN the absmax range; a degenerate
            # all-tiny sample must not zero the scale out entirely
            if clip > 0.0:
                records[path] = min(records[path], clip)
    return records


def calibrate_featureset(model, params, state, fs, n_batches: int = 8,
                         batch_size: int = 32,
                         percentile: Optional[float] = None
                         ) -> Dict[str, float]:
    """Draw the calibration sample from a ``FeatureSet`` iterator (the
    training-side data abstraction) instead of hand-built arrays: the
    first ``n_batches`` batches of ``fs.batches(batch_size)`` — labels and
    pad-weights dropped, inputs fed through :func:`calibrate`."""
    batches = []
    for item in fs.batches(int(batch_size)):
        x = item[0] if isinstance(item, tuple) else item
        batches.append(list(x) if isinstance(x, (list, tuple)) else x)
        if len(batches) >= int(n_batches):
            break
    if not batches:
        raise ValueError("calibrate_featureset: the FeatureSet yielded no "
                         "batches")
    return calibrate(model, params, state, batches, percentile=percentile)


def _locate_holder(tree: dict, path: str):
    """The dict holding ``path``'s final segment, navigated by the exact
    key chain (never a depth-first name search — that is the collision
    bug this replaces)."""
    segs = path.split("/")
    cur = tree
    for seg in segs[:-1]:
        cur = cur[seg]
    return cur, segs[-1]


def _quantize_w8(W: np.ndarray, a: float) -> dict:
    red = tuple(range(W.ndim - 1))   # all but the output-channel axis
    s_w = np.maximum(np.abs(W).max(axis=red), 1e-12) / 127.0
    W_q = np.clip(np.round(W / s_w), -127, 127).astype(np.int8)
    return {"W_q": jnp.asarray(W_q),
            "s_w": jnp.asarray(s_w, jnp.float32),
            "s_x": jnp.asarray(a / 127.0, jnp.float32)}


def _quantize_w4(W: np.ndarray, group_size: int) -> dict:
    """Symmetric int4 with group-wise scales: the weight tensor flattens
    to (K, N) over all-but-the-output-channel axis, groups run along K.
    The requested group size is NORMALIZED to ``ceil(K / ceil(K/gs))`` so
    the effective size is derivable from the stored shapes alone (jitted
    consumers reconstruct it without a side-channel leaf)."""
    from analytics_zoo_tpu.ops import quant_matmul as qm
    n = W.shape[-1]
    k = int(np.prod(W.shape[:-1]))
    W2 = W.reshape(k, n)
    g = max(1, -(-k // max(1, int(group_size))))
    gs = -(-k // g)                  # effective group size (see docstring)
    s_rows = np.empty((g, n), np.float32)
    q = np.empty((k, n), np.int8)
    for i in range(g):
        lo, hi = i * gs, min((i + 1) * gs, k)
        s = np.maximum(np.abs(W2[lo:hi]).max(axis=0), 1e-12) / 7.0
        s_rows[i] = s
        q[lo:hi] = np.clip(np.round(W2[lo:hi] / s), -7, 7).astype(np.int8)
    return {"W_q4": jnp.asarray(qm.pack_int4(q)),
            "s_g": jnp.asarray(s_rows, jnp.float32)}


# -- int8 KV-cache packing (PR 18 paged KV) -----------------------------------
# The ONE pack/unpack contract shared by the paged-attention kernels
# (ops/paged_attention.py), the decode append path
# (models/textmodels.TransformerLM.decode_paged) and the prefill commit
# program (serving/generate.py): symmetric int8 with one scale per
# (block, head) — same recipe as `_quantize_w8` (scale = absmax/127,
# round-clip to [-127, 127]) but jnp-traceable, because the quantize
# happens INSIDE the compiled decode/commit programs as tokens append.

def kv_pack_int8(x):
    """Quantize KV block(s) ``x`` (..., block_len, heads, head_dim) f32 ->
    ``(q int8 same shape, scale f32 (..., heads))``.  The scale is the
    per-(block, head) absmax over the (block_len, head_dim) axes — padded
    /unwritten positions must arrive ZEROED so they cannot inflate it
    (zeros quantize to zero exactly at any scale)."""
    x = jnp.asarray(x, jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=(-3, -1))
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(x / scale[..., None, :, None]), -127, 127)
    return q.astype(jnp.int8), scale


def kv_unpack_int8(q, scale):
    """Inverse of :func:`kv_pack_int8`: int8 blocks + per-(block, head)
    scales -> f32 values (exact for zeros; |err| <= scale/2 elsewhere)."""
    return jnp.asarray(q, jnp.float32) * jnp.asarray(
        scale, jnp.float32)[..., None, :, None]


def quantize_params(model, params, absmax: Dict[str, float], bits: int = 8,
                    group_size: int = 64):
    """Return a new params pytree with quantizable layers' weights replaced
    by their quantized leaves:

    - ``bits=8``: {"W_q" int8, "s_w" f32 per-out-channel, "s_x" f32
      scalar, "b"?} — layers ``absmax`` never saw stay float.
    - ``bits=4``: {"W_q4" uint8 nibble-packed, "s_g" f32 (groups, out),
      "b"?} — weight-only, every quantizable layer converts (``absmax``
      is not consulted).

    ``absmax`` keys are layer PATHS (see :func:`calibrate`); bare layer
    names are accepted for top-level layers, where path == name."""
    if bits not in (8, 4):
        raise ValueError(f"bits={bits!r}: expected 8 or 4")

    def copy_tree(p):
        return {k: copy_tree(v) if isinstance(v, dict) else v
                for k, v in p.items()}

    qp = copy_tree(params)
    for layer, _, path in _target_layers(model, params):
        a = absmax.get(path, absmax.get(layer.name, 0.0))
        if bits == 8 and a <= 0.0:
            continue                     # never calibrated: leave in float
        holder, key = _locate_holder(qp, path)
        lp = holder[key]
        if "W" not in lp:
            if bits == 8 and "W_q" in lp and a > 0.0:
                # already int8: re-calibration refreshes the activation
                # scale
                lp["s_x"] = jnp.asarray(a / 127.0, jnp.float32)
            continue                     # already quantized otherwise
        W = np.asarray(lp["W"], np.float32)
        new = _quantize_w8(W, a) if bits == 8 \
            else _quantize_w4(W, group_size)
        if "b" in lp:
            new["b"] = lp["b"]
        holder[key] = new
    return qp


def quantize(model, params, state, calib_inputs, bits: int = 8,
             group_size: int = 64, percentile: Optional[float] = None):
    """calibrate + quantize_params in one call.  ``calib_inputs`` may be a
    ``FeatureSet`` (sampled via :func:`calibrate_featureset`), a batch / a
    list of batches, or None for the weight-only ``bits=4`` mode."""
    from analytics_zoo_tpu.feature.dataset import FeatureSet
    if calib_inputs is None:
        if bits == 8:
            raise ValueError("int8 quantization needs calibration inputs "
                             "(activation scales); bits=4 is weight-only")
        absmax: Dict[str, float] = {}
    elif isinstance(calib_inputs, FeatureSet):
        absmax = calibrate_featureset(model, params, state, calib_inputs,
                                      percentile=percentile)
    else:
        absmax = calibrate(model, params, state, calib_inputs,
                           percentile=percentile)
    return quantize_params(model, params, absmax, bits=bits,
                           group_size=group_size)


# -- introspection / accounting ------------------------------------------------

def quantized_bits(params) -> int:
    """0 (float), 8 or 4 — what the params tree serves with.  Mixed trees
    report the SMALLEST width present (the headline compression)."""
    bits = 0
    for path, _ in _leaf_items(params):
        leaf = path.rsplit("/", 1)[-1]
        if leaf == "W_q4":
            return 4
        if leaf == "W_q":
            bits = 8
    return bits


def _leaf_items(params):
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in flat:
        segs = []
        for p in path:
            segs.append(str(getattr(p, "key", getattr(p, "idx", p))))
        yield "/".join(segs), leaf


def weight_bytes(params) -> int:
    """Bytes of parameters read from HBM per forward pass — every leaf of
    the tree (weights, scales, biases) at its stored dtype.  The
    STRUCTURAL half of the quantized-serving claim: int8 trees come out
    ~4x smaller than f32, int4 ~8x, independent of wall clocks."""
    total = 0
    for _, leaf in _leaf_items(params):
        total += int(np.size(leaf)) * int(np.dtype(
            getattr(leaf, "dtype", np.float32)).itemsize)
    return total
