"""InferenceModel — the multi-backend concurrent-inference holder.

Reference parity: pipeline/inference/InferenceModel.scala:30-889 — loaders for multiple
model formats + a blocking queue of weight-sharing model clones for concurrent predict
(modelQueue, :67,741-790).

TPU-native redesign: a jitted predict function IS thread-safe and weight-sharing —
no clone queue needed; concurrency is handled by XLA's stream executor.  What remains is
(a) the loader surface: zoo weights (`do_load`), TF SavedModel (`do_load_tensorflow`,
via the interop bridge — the TFNet analog), ONNX when available, and (b) **bucketed
batching**: inputs are padded to the nearest power-of-two batch so a handful of compiled
programs serve any request size (the serving-latency answer to the reference's per-core
BLAS threading, SURVEY.md §7 hard-parts).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from analytics_zoo_tpu.nn.module import Layer


def _bucket(n: int, max_batch: int) -> int:
    b = 1
    while b < n and b < max_batch:
        b *= 2
    return min(b, max_batch)


def _pad_to_bucket(xs: List[np.ndarray], scales, n: int, bucket: int):
    """Zero-pad the batch arrays (and per-row scales, padded with ones)
    from ``n`` rows up to the pow-2 ``bucket``.  The ONE padding
    implementation shared by `do_predict` and `dispatch`, so both paths
    produce identical padded signatures and hit one compile cache."""
    if n < bucket:
        xs = [np.concatenate(
            [a, np.zeros((bucket - n,) + a.shape[1:], a.dtype)])
            for a in xs]
    if scales is None:
        return xs, None
    sc = np.concatenate([np.asarray(scales, np.float32),
                         np.ones((bucket - n,), np.float32)])
    return xs, sc


class _LazyPending:
    """Deferred-call result handle (`dispatch` oversized-batch fallback):
    the work happens at ``result()``, matching `_Pending`'s interface."""

    def __init__(self, fn: Callable):
        self._fn = fn

    def result(self):
        return self._fn()


class InferenceModel:
    """supported_concurrent_num is the concurrency CONTRACT
    (InferenceModel.scala:33,67: a queue of N weight-sharing clones): here it
    bounds (a) how many predict() callers may dispatch simultaneously (a
    semaphore replaces the clone queue — the jitted program is already
    weight-sharing and thread-safe) and (b) how many batches a single
    predict() keeps IN FLIGHT on the device before reading results back —
    JAX dispatch is async, so host-side padding/decode of batch k+1..k+N
    overlaps device compute of batch k."""

    def __init__(self, supported_concurrent_num: int = 2,
                 max_batch: int = 1024, registry=None):
        self.max_batch = int(max_batch)
        self.concurrent_num = max(1, int(supported_concurrent_num))
        self._predict_fn: Optional[Callable] = None
        self._params = None
        self._state = None
        self._model: Optional[Layer] = None
        self._jitted = None
        self._sem = threading.BoundedSemaphore(self.concurrent_num)
        # unified telemetry (PR 4): predict/dispatch latency + batch-size
        # histograms.  `registry` is an observability.MetricsRegistry; left
        # None it binds lazily — to the serving engine's registry when this
        # model is handed to a ClusterServing (re-bound per engine, so a
        # model reused across engines follows the live one), else the
        # process-wide one.  An EXPLICIT registry is pinned: engines won't
        # re-bind it.
        self._obs_registry = registry
        self._obs_registry_explicit = registry is not None
        self._obs = None

    def bind_registry(self, registry) -> bool:
        """Adopt `registry` for the predict/dispatch histograms — called by
        a ClusterServing at construction so one scrape covers the whole
        data plane.  A model constructed with an EXPLICIT registry stays
        pinned (returns False); otherwise the model follows the most recent
        binder (a model reused across engines, e.g. bench --sweep, reports
        into the live engine's scrape) and the cached histogram handles are
        dropped so they re-create in the new registry."""
        if self._obs_registry_explicit:
            return False
        self._obs_registry = registry
        self._obs = None
        return True

    def _observe(self, method: str, n: int, dt_s: float) -> None:
        """Record one predict/dispatch call: wall latency and batch size,
        labeled by entry point (`do_predict` blocks on readback; `dispatch`
        measures enqueue-to-device only)."""
        if self._obs is None:
            from analytics_zoo_tpu.common.observability import get_registry
            reg = self._obs_registry or get_registry()
            self._obs_registry = reg
            self._obs = (
                reg.histogram("inference_predict_seconds",
                              "Model predict/dispatch wall latency",
                              labels=("method",)),
                reg.histogram("inference_batch_size",
                              "Records per predict/dispatch call",
                              labels=("method",),
                              buckets=tuple(float(1 << i)
                                            for i in range(12))))
        self._obs[0].labels(method=method).observe(dt_s)
        self._obs[1].labels(method=method).observe(float(n))

    # -- loaders --------------------------------------------------------------
    def do_load_model(self, model: Layer, params=None, state=None):
        """Load an in-memory zoo layer/container (doLoadBigDL analog)."""
        self._model = model
        if params is None and hasattr(model, "_params"):
            params, state = model._params, model._state
        self._params, self._state = params, state
        self._jitted = jax.jit(
            lambda p, s, x: model.apply(p, s, x, training=False)[0])
        return self

    def do_load(self, topology_builder: Callable[[], Layer], weights_path: str):
        """Rebuild topology via `topology_builder` and load weights from `.npz`
        (doLoad analog — weights file + known architecture)."""
        model = topology_builder()
        model.init_weights()
        model.load_weights(weights_path)
        return self.do_load_model(model, model._params, model._state)

    def do_load_tensorflow(self, saved_model_path: str,
                           signature: str = "serving_default"):
        """Wrap a TF SavedModel as the predict function (TFNet analog — see
        interop/tfnet.py; runs through the TF runtime bridge)."""
        from analytics_zoo_tpu.interop.tfnet import TFNet
        net = TFNet.from_saved_model(saved_model_path, signature=signature)
        self._model = net
        self._params, self._state = {}, {}
        self._jitted = lambda p, s, x: net.call({}, x)
        return self

    def do_load_onnx(self, onnx_path: str):
        """ONNX model -> native predict function (reference: doLoadOpenVINO /
        onnx_loader.py ModelLoader; here via interop/onnx_loader.py)."""
        from analytics_zoo_tpu.interop.onnx_loader import load_onnx
        net = load_onnx(onnx_path)
        params = net.build(None, None)
        return self.do_load_model(net, params, {})

    def do_load_pytorch(self, model_or_path, example_input=None):
        """PyTorch model -> native predict function (reference: doLoadPyTorch,
        TorchNet.scala:39-242; here the TorchScript graph is imported into
        jnp via interop/torchnet.py — no libtorch at serve time)."""
        from analytics_zoo_tpu.interop.torchnet import TorchNet
        if isinstance(model_or_path, str):
            net = TorchNet(model_or_path)
        else:
            net = TorchNet.from_pytorch(model_or_path, example_input)
        params = net.build(None, None)
        return self.do_load_model(net, params, {})

    # -- quantization ----------------------------------------------------------
    def do_quantize(self, calib_inputs, force: bool = False):
        """Post-training int8 quantization of the loaded model (the
        OpenVINO-int8 capability, pipeline/inference/OpenVinoInferenceSupportive
        .scala analog — here targeting the MXU s8xs8->s32 path).

        `calib_inputs`: one batch (or list of batches) shaped like predict
        inputs; used to calibrate per-layer activation scales.  Dense/conv
        weights become int8 with per-output-channel scales; predict() then
        runs the quantized graph.

        OPT-IN on TPU v5e (re-measured 2026-07-30 round 5 with the
        LICM-proof timing loop, bench.py bench_resnet50_int8): raw
        s8xs8->s32 kernels reach only ~1.0-1.2x the bf16 rate through this
        XLA stack (tools/int8_matrix.py; bf16 already runs near the
        197 TF/s nameplate — int8 does NOT unlock a doubled MXU rate), and
        the per-layer quantize/clip/dequant elementwise passes push the
        END-TO-END quantized ResNet-50 to 0.82x bf16.  Unlike the reference's
        AVX512-VNNI target, int8 here costs speed; accuracy parity holds
        (top-1 agreement 1.0).  Pass force=True to quantize anyway (memory
        footprint, numerics experiments)."""
        import warnings

        from analytics_zoo_tpu.inference.quantize import (
            _target_layers, quantize)
        if self._model is None:
            raise RuntimeError("load a model first")
        if not force:
            warnings.warn(
                "int8 PTQ is measurably SLOWER than bf16 on this TPU stack "
                "(~0.84x end-to-end ResNet-50; raw-kernel matrix in "
                "tools/int8_matrix.py) — skipping quantization. Pass "
                "force=True to quantize anyway.", stacklevel=2)
            return self
        if not _target_layers(self._model, self._params or {}):
            # nothing quantizable (e.g. a TFNet-backed model whose predict
            # lambda must stay un-jitted) — leave the loaded path untouched
            return self
        self._params = quantize(self._model, self._params, self._state or {},
                                calib_inputs)
        model = self._model
        self._jitted = jax.jit(
            lambda p, s, x: model.apply(p, s, x, training=False)[0])
        return self

    # -- async dispatch (serving hot path, PR 3) ------------------------------
    class _Pending:
        """Handle for one async-dispatched batch: the jitted program is
        already enqueued on the device; ``result()`` blocks on the host
        transfer and strips the bucket padding."""

        def __init__(self, device_out, take: int):
            self._out = device_out
            self._take = take

        def result(self):
            take = self._take
            return jax.tree.map(lambda a: np.asarray(a)[:take], self._out)

    def dispatch(self, x, scales: Optional[np.ndarray] = None) -> "_Pending":
        """Dispatch ONE batch to the device without blocking on the host
        readback.  JAX dispatch is asynchronous, so the caller's next stage
        (preprocessing batch k+1, writing batch k-1's results) overlaps this
        batch's device compute; call ``.result()`` on the returned handle to
        transfer the outputs.  Pads to the same power-of-two bucket as
        `do_predict`, so the two paths share one compile cache.

        Unlike `do_predict` this takes no concurrency semaphore and does no
        internal chunking — callers (the serving engine's
        ``inflight_batches`` bound) cap how many handles they keep open; a
        batch larger than ``max_batch`` falls back to the chunking
        synchronous path, evaluated lazily at ``result()``."""
        if self._jitted is None:
            raise RuntimeError("load a model first")
        t0 = time.perf_counter()
        multi = isinstance(x, (list, tuple))
        if scales is not None and multi:
            raise ValueError("scales= supports single-input models only")
        xs = [np.asarray(a) for a in (x if multi else [x])]
        n = xs[0].shape[0]
        if n > self.max_batch:
            return _LazyPending(lambda: self.do_predict(x, scales=scales))
        bucket = _bucket(n, self.max_batch)
        xs, sc = _pad_to_bucket(xs, scales, n, bucket)
        if sc is not None:
            out = self._jitted_with_scales()(self._params, self._state,
                                             xs[0], sc)
        else:
            arg = xs if multi else xs[0]
            out = self._jitted(self._params, self._state, arg)
        self._observe("dispatch", n, time.perf_counter() - t0)
        return self._Pending(out, n)

    # -- predict --------------------------------------------------------------
    def _jitted_with_scales(self):
        """Lazily-built dequantizing predict: the int8/uint8 batch is
        TRANSFERRED in its compact dtype and multiplied by the per-row scale
        on device (round 5 serving wire path) — 4x less host->device
        traffic than shipping f32."""
        if getattr(self, "_jitted_scaled", None) is None \
                or getattr(self, "_jitted_scaled_base", None) \
                is not self._jitted:
            import jax.numpy as jnp
            base = self._jitted
            if hasattr(base, "lower"):        # a real jitted program

                def fn(p, s, x, sc):
                    xf = x.astype(jnp.float32) \
                        * sc.reshape(sc.shape + (1,) * (x.ndim - 1))
                    return base(p, s, xf)
                self._jitted_scaled = jax.jit(fn)
            else:
                # un-jittable bridge path (e.g. TFNet lambda): dequantize on
                # host — correctness over the transfer win
                def fn(p, s, x, sc):
                    xf = np.asarray(x, np.float32) * np.asarray(
                        sc, np.float32).reshape(
                            sc.shape + (1,) * (np.ndim(x) - 1))
                    return base(p, s, xf)
                self._jitted_scaled = fn
            self._jitted_scaled_base = base
        return self._jitted_scaled

    def do_predict(self, x, batch_size: Optional[int] = None,
                   scales: Optional[np.ndarray] = None) -> np.ndarray:
        """Batched forward with power-of-two bucket padding: at most
        log2(max_batch) compiled programs ever exist per input signature.
        Up to `supported_concurrent_num` batches stay in flight on the
        device before their (blocking) host readback.

        `scales` (round 5): per-row dequantization factors for a compact
        int8/uint8 `x` — the rows reach the device in their wire dtype and
        are dequantized there (single-input models only)."""
        if self._jitted is None:
            raise RuntimeError("load a model first")
        t0 = time.perf_counter()
        multi = isinstance(x, (list, tuple))
        if scales is not None and multi:
            raise ValueError("scales= supports single-input models only")
        xs = [np.asarray(a) for a in (x if multi else [x])]
        sc = None if scales is None else np.asarray(scales, np.float32)
        n = xs[0].shape[0]
        step = batch_size or self.max_batch
        outs = []
        pending: List = []   # (device result, take) not yet read back

        def drain_one():
            y, take = pending.pop(0)
            outs.append(jax.tree.map(lambda a: np.asarray(a)[:take], y))

        with self._sem:
            i = 0
            while i < n:
                take = min(step, n - i)
                bucket = _bucket(take, self.max_batch)
                chunk = [a[i:i + take] for a in xs]
                chunk, schunk = _pad_to_bucket(
                    chunk, None if sc is None else sc[i:i + take],
                    take, bucket)
                if schunk is not None:
                    pending.append((self._jitted_with_scales()(
                        self._params, self._state, chunk[0], schunk), take))
                else:
                    arg = chunk if multi else chunk[0]
                    pending.append(
                        (self._jitted(self._params, self._state, arg), take))
                if len(pending) >= self.concurrent_num:
                    drain_one()
                i += take
            while pending:
                drain_one()
        self._observe("do_predict", n, time.perf_counter() - t0)
        if isinstance(outs[0], (list, tuple)):
            return [np.concatenate([o[j] for o in outs])
                    for j in range(len(outs[0]))]
        return np.concatenate(outs)

    # reference-style aliases
    predict = do_predict
