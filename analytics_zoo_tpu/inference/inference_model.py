"""InferenceModel — the multi-backend concurrent-inference holder.

Reference parity: pipeline/inference/InferenceModel.scala:30-889 — loaders for multiple
model formats + a blocking queue of weight-sharing model clones for concurrent predict
(modelQueue, :67,741-790).

TPU-native redesign: a jitted predict function IS thread-safe and weight-sharing —
no clone queue needed; concurrency is handled by XLA's stream executor.  What remains is
(a) the loader surface: zoo weights (`do_load`), TF SavedModel (`do_load_tensorflow`,
via the interop bridge — the TFNet analog), ONNX when available, and (b) **bucketed
batching**: inputs are padded to the nearest power-of-two batch so a handful of compiled
programs serve any request size (the serving-latency answer to the reference's per-core
BLAS threading, SURVEY.md §7 hard-parts).

Sharded multi-chip serving (PR 6): `shard()` places the parameters over a
`data` x `model` device mesh once (ShardingPlan.shard) and commits every
padded batch with a batch-axis NamedSharding before dispatch, so the GSPMD
partitioner runs the SAME jitted program over all chips — batch-sharded for
small models (replicated params), megatron tensor-sharded for large
transformer stacks — and XLA overlaps the ICI transfers with compute.  The
pow-2 buckets become mesh-aware: rounded up to a multiple of the batch-axis
size so every device gets an equal slice and the compile cache stays one
program per bucket.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from analytics_zoo_tpu.nn.module import Layer

logger = logging.getLogger(__name__)


def _pow2_floor(n: int) -> int:
    return 1 << (max(1, int(n)).bit_length() - 1)


def _bucket(n: int, max_batch: int, multiple: int = 1) -> int:
    """Power-of-two bucket for an n-row batch, rounded UP to a multiple of
    `multiple` (the mesh batch-axis size) so padded batches shard evenly
    over the data axis; `max_batch` is a pow-2 multiple of `multiple`
    (InferenceModel clamps/validates), so buckets stay pow-2."""
    b = 1
    while b < n and b < max_batch:
        b *= 2
    b = min(b, max_batch)
    if multiple > 1 and b % multiple != 0:
        b = min(-(-b // multiple) * multiple, max(max_batch, multiple))
    return b


def _pad_to_bucket(xs: List[np.ndarray], scales, n: int, bucket: int):
    """Zero-pad the batch arrays (and per-row scales, padded with ones)
    from ``n`` rows up to the pow-2 ``bucket``.  The ONE padding
    implementation shared by `do_predict` and `dispatch`, so both paths
    produce identical padded signatures and hit one compile cache."""
    if n < bucket:
        xs = [np.concatenate(
            [a, np.zeros((bucket - n,) + a.shape[1:], a.dtype)])
            for a in xs]
    if scales is None:
        return xs, None
    sc = np.concatenate([np.asarray(scales, np.float32),
                         np.ones((bucket - n,), np.float32)])
    return xs, sc


class _LazyPending:
    """Deferred-call result handle (`dispatch` oversized-batch fallback):
    the work happens at ``result()``, matching `_Pending`'s interface."""

    def __init__(self, fn: Callable):
        self._fn = fn

    def result(self):
        return self._fn()


class InferenceModel:
    """supported_concurrent_num is the concurrency CONTRACT
    (InferenceModel.scala:33,67: a queue of N weight-sharing clones): here it
    bounds (a) how many predict() callers may dispatch simultaneously (a
    semaphore replaces the clone queue — the jitted program is already
    weight-sharing and thread-safe) and (b) how many batches a single
    predict() keeps IN FLIGHT on the device before reading results back —
    JAX dispatch is async, so host-side padding/decode of batch k+1..k+N
    overlaps device compute of batch k."""

    def __init__(self, supported_concurrent_num: int = 2,
                 max_batch: int = 1024, registry=None):
        # the bucket ladder is pow-2 by contract: a non-pow-2 max_batch
        # would add a non-pow-2 TERMINAL bucket (e.g. 100 after 64),
        # silently doubling the compile-cache footprint per signature —
        # clamp DOWN to the nearest power of two instead
        mb = max(1, int(max_batch))
        self.max_batch = _pow2_floor(mb)
        if self.max_batch != mb:
            logger.warning(
                "InferenceModel: max_batch=%d is not a power of two; "
                "clamping to %d so the bucket ladder stays pow-2 (a "
                "non-pow-2 terminal bucket doubles the compile cache)",
                mb, self.max_batch)
        self.concurrent_num = max(1, int(supported_concurrent_num))
        # sharded multi-chip serving (PR 6): populated by shard()
        self._mesh = None                 # jax.sharding.Mesh when sharded
        self._plan = None                 # the params ShardingPlan in force
        self._sharding_mode: Optional[str] = None   # batch|tensor|hybrid
        self._batch_multiple = 1          # mesh data-axis size (bucket quantum)
        self._sharded_calls = 0           # batches committed to the mesh
        self._mesh_gauge = None           # (gauge, provider) registration
        self._predict_fn: Optional[Callable] = None
        self._params = None
        self._state = None
        self._model: Optional[Layer] = None
        self._jitted = None
        self._sem = threading.BoundedSemaphore(self.concurrent_num)
        # AOT executable cache (PR 11 zero cold start): one compiled
        # program per (load epoch, padded signature), consulted by
        # do_predict/dispatch BEFORE tracing.  aot.warm_up pre-populates
        # it at load time; live misses compile once and join it.  The
        # epoch bumps whenever the underlying program changes (re-load,
        # quantize, shard) so stale executables can never serve — and a
        # `_jitted_scaled_base` wrapper rebuild alone can NOT invalidate
        # it (the old churn: every rebuild emptied the jit cache).
        self._aot: Dict = {}
        self._aot_lock = threading.Lock()
        self._aot_epoch = 0
        self.aot_hits = 0               # padded calls served by the cache
        self.aot_compiles = 0           # lower().compile() calls we made
        # per-program execution counters (PR 15 resource accounting):
        # label -> executions, keyed the way the warm-up manifest names
        # programs (bucket x tail-shape / dtype [+scales]) so "which
        # program is actually hot" reads straight off the health doc
        self._aot_execs: Dict[str, int] = {}
        self.load_seconds: Optional[float] = None   # last do_load* wall
        self.load_mmap = False          # last load used the mmap store
        # scaled-program wrappers per base program (bounded): a base that
        # drifts A -> B -> A (instance patches, chaos shims) re-uses A's
        # wrapper and its jit cache instead of rebuilding from scratch
        self._scaled_wrappers: Dict = {}
        # unified telemetry (PR 4): predict/dispatch latency + batch-size
        # histograms.  `registry` is an observability.MetricsRegistry; left
        # None it binds lazily — to the serving engine's registry when this
        # model is handed to a ClusterServing (re-bound per engine, so a
        # model reused across engines follows the live one), else the
        # process-wide one.  An EXPLICIT registry is pinned: engines won't
        # re-bind it.
        self._obs_registry = registry
        self._obs_registry_explicit = registry is not None
        self._obs = None

    def bind_registry(self, registry) -> bool:
        """Adopt `registry` for the predict/dispatch histograms — called by
        a ClusterServing at construction so one scrape covers the whole
        data plane.  A model constructed with an EXPLICIT registry stays
        pinned (returns False); otherwise the model follows the most recent
        binder (a model reused across engines, e.g. bench --sweep, reports
        into the live engine's scrape) and the cached histogram handles are
        dropped so they re-create in the new registry."""
        if self._obs_registry_explicit:
            return False
        self._obs_registry = registry
        self._obs = None
        return True

    def _observe(self, method: str, n: int, dt_s: float) -> None:
        """Record one predict/dispatch call: wall latency and batch size,
        labeled by entry point (`do_predict` blocks on readback; `dispatch`
        measures enqueue-to-device only) and by the sharding mode in force
        (`off` single-chip, `batch`/`tensor`/`hybrid` over the mesh)."""
        if self._obs is None:
            from analytics_zoo_tpu.common.observability import get_registry
            reg = self._obs_registry or get_registry()
            self._obs_registry = reg
            self._obs = (
                reg.histogram("inference_predict_seconds",
                              "Model predict/dispatch wall latency",
                              labels=("method", "sharding")),
                reg.histogram("inference_batch_size",
                              "Records per predict/dispatch call",
                              labels=("method",),
                              buckets=tuple(float(1 << i)
                                            for i in range(12))))
            # the mesh-devices provider holds only a WEAK ref to the model
            # (models have no shutdown hook, and a registry — possibly the
            # process-global one — must not keep a discarded model's params
            # alive); the previous registration is dropped on re-bind so
            # stale providers don't pile up in old registries
            if self._mesh_gauge is not None:
                old_gauge, old_fn = self._mesh_gauge
                old_gauge.remove_function(old_fn)
            self_ref = weakref.ref(self)

            def _mesh_devices_provider() -> float:
                model = self_ref()
                return float(model.mesh_devices) if model is not None else 1.0

            gauge = reg.gauge("inference_mesh_devices",
                              "Devices in the serving mesh (1 = single-chip)")
            gauge.set_function(_mesh_devices_provider)
            self._mesh_gauge = (gauge, _mesh_devices_provider)
        sharding = self._sharding_mode or "off"
        self._obs[0].labels(method=method, sharding=sharding).observe(dt_s)
        self._obs[1].labels(method=method).observe(float(n))

    # -- sharded multi-chip serving (PR 6 tentpole) ---------------------------
    @property
    def mesh_devices(self) -> int:
        """Devices the sharded predict spans (1 = single-chip)."""
        if self._mesh is None:
            return 1
        return int(np.prod(self._mesh.devices.shape))

    def _mesh_matches(self, req) -> bool:
        """Does a shard() mesh request describe the placement already in
        force?  (int = device count, tuple = (data, model) axes, Mesh =
        identity)."""
        from jax.sharding import Mesh
        if isinstance(req, Mesh):
            return req is self._mesh
        shape = self._mesh.shape
        if isinstance(req, (tuple, list)):
            return (len(req) == 2
                    and int(req[0]) == int(shape.get("data", 1))
                    and int(req[1]) == int(shape.get("model", 1)))
        return int(req) == self.mesh_devices

    def mesh_info(self) -> Dict:
        """Mesh topology + structural-evidence counters (serving_bench A/B:
        on CPU sim the win is asserted from these, not wall clock)."""
        if self._mesh is None:
            return {"devices": 1, "sharding": "off", "sharded_calls": 0}
        return {"devices": self.mesh_devices,
                "sharding": self._sharding_mode,
                "axes": {k: int(v) for k, v in self._mesh.shape.items()},
                "sharded_calls": self._sharded_calls}

    def shard(self, mesh=None, sharding: str = "auto", plan=None):
        """Route predict/dispatch through a sharded program over a device
        mesh: parameters are placed ONCE (`ShardingPlan.shard`), every
        padded batch is committed with a batch-axis `NamedSharding`, and the
        jitted program partitions via GSPMD — batch-sharded for small models
        (replicated params), megatron tensor-sharded for large transformer
        stacks, `sharding="auto"` choosing by parameter count.

        `mesh` may be None (all devices), an int (first N devices), a
        `(data, model)` shape tuple (hybrid layouts), or a prebuilt
        `jax.sharding.Mesh`.  Idempotent: a model already sharded keeps its
        mesh (bench replicas share one model across N engines).  On CPU,
        simulate with XLA_FLAGS=--xla_force_host_platform_device_count=N."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from analytics_zoo_tpu.parallel import sharding as shardlib
        mode = sharding or "auto"
        if mode == "off":
            return self
        if mode not in ("auto", "batch", "tensor"):
            raise ValueError(f"sharding={mode!r}: expected one of "
                             "auto|batch|tensor|off")
        if self._jitted is None:
            raise RuntimeError("load a model first")
        if not hasattr(self._jitted, "lower"):
            raise ValueError(
                "sharded serving needs a jax-native model; bridge predict "
                "functions (TF SavedModel via TFNet) cannot be partitioned")
        if self._mesh is not None:
            if mode not in ("auto", self._sharding_mode):
                logger.warning(
                    "InferenceModel: already sharded %s over %d devices; "
                    "ignoring shard(sharding=%r) — one placement per load",
                    self._sharding_mode, self.mesh_devices, mode)
            elif mesh is not None and not self._mesh_matches(mesh):
                logger.warning(
                    "InferenceModel: already sharded over %d device(s) %s; "
                    "ignoring the conflicting mesh=%r — one placement per "
                    "load (re-load the model to re-shard)",
                    self.mesh_devices, dict(self._mesh.shape), mesh)
            return self
        if isinstance(mesh, Mesh):
            m = mesh
        elif isinstance(mesh, (tuple, list)):
            m = shardlib.serving_mesh(shape=tuple(mesh))
        else:
            if mode == "auto":
                mode = shardlib.serving_mode_for(self._params)
            m = shardlib.serving_mesh(n_devices=mesh, mode=mode)
        dd = int(m.shape.get("data", 1))
        mm = int(m.shape.get("model", 1))
        if mode == "auto":
            mode = "hybrid" if (dd > 1 and mm > 1) else \
                ("tensor" if mm > 1 else "batch")
        if dd > 1 and self.max_batch % dd != 0:
            if isinstance(mesh, (Mesh, tuple, list)):
                # the caller chose this layout explicitly: reject with an
                # attainable fix (max_batch is pow-2 by construction, so
                # "raise max_batch" can never make a non-pow-2 axis divide)
                raise ValueError(
                    f"mesh data axis {dd} does not divide max_batch="
                    f"{self.max_batch}; choose a power-of-2 data axis")
            # auto-built batch mesh over a non-pow-2 device count (3, 6,
            # 12 chips): use the largest batch axis that divides the pow-2
            # max_batch instead of refusing to shard at all
            usable = min(_pow2_floor(dd), self.max_batch)
            logger.warning(
                "InferenceModel: %d visible device(s) do not divide the "
                "pow-2 max_batch=%d; sharding over the largest usable "
                "batch axis (%d device(s)) instead", dd, self.max_batch,
                usable)
            m = shardlib.serving_mesh(n_devices=usable, mode="batch")
            dd, mm = usable, 1
        if plan is None:
            if mode == "batch":
                # batch mode is an explicit contract: params replicated,
                # ONLY the batch splits — even for models the auto
                # heuristic would tensor-shard
                plan = shardlib.replicated_plan()
            else:
                # tensor/hybrid: the caller (or auto's size gate) decided,
                # so skip the parameter-count threshold
                plan = shardlib.serving_plan(self._params, m,
                                             min_tensor_params=0)
                if not plan.rules:
                    logger.warning(
                        "InferenceModel: tensor sharding requested but no "
                        "parameter leaf matches the megatron plan; params "
                        "stay replicated (inputs still shard over the "
                        "batch axis)")
        self._params = plan.shard(self._params, m)
        if self._state:
            self._state = jax.tree.map(
                lambda a: jax.device_put(a, NamedSharding(m, P())),
                self._state)
        self._mesh = m
        self._plan = plan
        self._sharding_mode = mode
        self._batch_multiple = max(1, dd)
        self._bump_epoch()     # committed shardings change the programs
        self._obs = None       # histogram children re-label with the mode
        logger.info(
            "InferenceModel: sharded predict enabled — mode=%s mesh=%dx%d "
            "(data x model) over %d device(s)", mode, dd, mm,
            self.mesh_devices)
        return self

    def _commit(self, xs: List, scales):
        """Commit one padded batch (and its per-row scales) to the mesh with
        the batch NamedSharding: device_put is asynchronous, so the ICI/PCIe
        transfer of batch k+1 overlaps batch k's compute.  Single-chip mode
        passes host arrays straight through (jit transfers them itself)."""
        if self._mesh is None:
            return xs, scales
        from jax.sharding import NamedSharding, PartitionSpec as P
        m = self._mesh
        xs = [jax.device_put(
            a, NamedSharding(m, P("data", *([None] * (a.ndim - 1)))))
            for a in xs]
        if scales is not None:
            # int8 wire path: the per-row dequant scales ride the same
            # batch axis as their rows
            scales = jax.device_put(scales, NamedSharding(m, P("data")))
        self._sharded_calls += 1
        return xs, scales

    # -- AOT executable cache (PR 11 zero cold start) -------------------------
    def _bump_epoch(self) -> None:
        """Invalidate every compiled executable: the underlying program
        changed (new weights, quantized graph, mesh placement)."""
        with self._aot_lock:
            self._aot_epoch += 1
            self._aot.clear()
            self._scaled_wrappers.clear()
            self._aot_execs.clear()    # counts name the OLD epoch's programs

    def _aot_key(self, fn, xs: List, sc, multi: bool):
        # `fn` (the jitted base or its per-base scaled wrapper) is part of
        # the key: an external `_jitted` patch that skips the epoch bump
        # must MISS, never serve the old program — while the per-base
        # wrapper cache keeps the fn identity stable across scaled/
        # unscaled interleaving, so legitimate reuse still hits
        return (self._aot_epoch, fn, multi, sc is not None,
                tuple((tuple(a.shape), np.dtype(a.dtype).str) for a in xs))

    def _padded_call(self, xs: List, sc, multi: bool, execute: bool = True):
        """Run ONE padded, committed bucket batch — the single exec path
        shared by `do_predict`, `dispatch` and `warm`.  An AOT executable
        for this signature (warm-up or an earlier call) runs without any
        tracing; a miss lowers+compiles once via the same jitted program
        and joins the cache (hitting the persistent compilation cache when
        one is configured), so at most one compile per signature per load
        epoch ever happens, no matter how wrappers churn.

        ``execute=False`` (the warm-up path) stops after the executable
        exists: compiling is what warm-up buys — running every program on
        a dummy batch would burn real forward-pass CPU against the live
        pipeline for nothing."""
        if sc is not None:
            fn = self._jitted_with_scales()
            if not hasattr(fn, "lower"):
                # host bridge path (TFNet lambda): nothing to compile
                return fn(self._params, self._state, xs[0], sc) \
                    if execute else None
            args = (self._params, self._state, xs[0], sc)
        else:
            fn = self._jitted
            if not hasattr(fn, "lower"):
                return fn(self._params, self._state,
                          xs if multi else xs[0]) if execute else None
            args = (self._params, self._state, xs if multi else xs[0])
        key = self._aot_key(fn, xs, sc, multi)
        exe = self._aot.get(key)
        if exe is None:
            # compile OUTSIDE the lock: the warm-up thread walks its
            # manifest through here, and a live request racing it for a
            # different bucket must not queue behind the whole set.  Two
            # threads racing the SAME signature both compile (the
            # persistent cache makes the loser cheap) and the dict keeps
            # whichever registered first.
            exe = fn.lower(*args).compile()
            with self._aot_lock:
                self.aot_compiles += 1
                if key[0] == self._aot_epoch:
                    exe = self._aot.setdefault(key, exe)
        else:
            self.aot_hits += 1
        if execute:
            label = self.program_label(xs, scales=sc)
            with self._aot_lock:
                self._aot_execs[label] = self._aot_execs.get(label, 0) + 1
            return exe(*args)
        return None

    @staticmethod
    def program_label(xs: List, scales=None) -> str:
        """Human-stable program name matching the warm-up manifest entry
        naming: ``b<bucket>x<tail shape>/<dtype>[+scales]``."""
        a = xs[0]
        tail = "x".join(str(int(s)) for s in a.shape[1:]) or "scalar"
        label = (f"b{int(a.shape[0])}x{tail}/"
                 f"{np.dtype(a.dtype).str}")
        return label + "+scales" if scales is not None else label

    def aot_memory_bytes(self) -> Optional[int]:
        """Best-effort total generated-code size of the cached AOT
        executables (the ``executables`` HBM component of the resource
        ledger).  None when this jax/backend exposes no memory analysis —
        the count is still exact either way."""
        total, seen = 0, 0
        with self._aot_lock:
            exes = list(self._aot.values())
        for exe in exes:
            try:
                ma = exe.memory_analysis()
                total += int(getattr(ma, "generated_code_size_in_bytes",
                                     0) or 0)
                seen += 1
            except Exception:  # noqa: BLE001 — backend without analysis
                continue
        return total if seen else None

    def warm(self, bucket: int, shape, dtype: str = "<f4",
             scales: bool = False) -> bool:
        """Compile (or confirm cached) the program for one warm-up entry:
        a `(bucket,) + shape` batch of `dtype`, optionally the int8-wire
        per-row-scales variant.  Runs the REAL padded/committed exec path
        so the cached executable is byte-for-byte the one `do_predict` and
        `dispatch` will look up — but does NOT execute it (execute=False:
        `.compile()` returning IS the warm state).  Returns True when this
        call compiled a fresh executable, False when already cached."""
        x = np.zeros((int(bucket),) + tuple(int(s) for s in shape),
                     np.dtype(dtype))
        sc = np.ones((int(bucket),), np.float32) if scales else None
        xs, sc = self._commit([x], sc)
        fn = self._jitted_with_scales() if sc is not None else self._jitted
        if not hasattr(fn, "lower"):
            # bridge path (TFNet lambda): nothing compilable exists, so
            # nothing can become "fresh" — reporting True forever would
            # make warm_up claim compile progress that never happened
            return False
        fresh = self._aot_key(fn, xs, sc, False) not in self._aot
        self._padded_call(xs, sc, False, execute=False)
        return fresh

    def aot_stats(self) -> Dict:
        """AOT-cache evidence counters (bench/test surface) + the
        per-program execution counts (PR 15): which compiled program is
        actually serving traffic, keyed by its manifest-style label."""
        with self._aot_lock:
            return {"epoch": self._aot_epoch,
                    "cached_programs": len(self._aot),
                    "hits": self.aot_hits,
                    "compiles": self.aot_compiles,
                    "programs": dict(self._aot_execs)}

    # -- loaders --------------------------------------------------------------
    def do_load_model(self, model: Layer, params=None, state=None):
        """Load an in-memory zoo layer/container (doLoadBigDL analog).
        Re-loading resets any mesh placement — call `shard()` again for the
        new weights."""
        self._model = model
        if params is None and hasattr(model, "_params"):
            params, state = model._params, model._state
        self._params, self._state = params, state
        self._jitted = jax.jit(
            lambda p, s, x: model.apply(p, s, x, training=False)[0])
        self._mesh = None
        self._plan = None
        self._sharding_mode = None
        self._batch_multiple = 1
        self._bump_epoch()
        return self

    def do_load(self, topology_builder: Callable[[], Layer],
                weights_path: str):
        """Rebuild topology via `topology_builder` and load weights from
        `.npz` (doLoad analog — weights file + known architecture).  A
        DIRECTORY path is an mmap'd weight store (inference/weightstore.py,
        PR 11): leaves restore as memory-mapped views — no deserialization
        copy at boot, and N replicas on one host share the page cache —
        then move to the device with one `jax.device_put` per leaf."""
        t0 = time.perf_counter()
        if os.path.isdir(weights_path):
            return self.do_load_store(topology_builder, weights_path)
        model = topology_builder()
        model.init_weights()
        model.load_weights(weights_path)
        out = self.do_load_model(model, model._params, model._state)
        self.load_seconds = time.perf_counter() - t0
        self.load_mmap = False
        return out

    def do_load_store(self, topology_builder: Callable[[], Layer],
                      store_dir: str):
        """Restore weights from an mmap'd store directory (PR 11 zero cold
        start): each leaf is a bare `.npy` read with
        ``np.load(mmap_mode="r")`` — the boot touches no weight bytes until
        the device transfer pages them in, and every replica on the host
        maps the SAME page-cache pages — then the whole tree is placed with
        `jax.device_put` once, so predict calls never re-transfer host
        params."""
        from analytics_zoo_tpu.inference import weightstore
        t0 = time.perf_counter()
        model = topology_builder()
        # the restore needs only the tree SKELETON (paths + shapes), not
        # computed weights: eval_shape traces init abstractly — no random
        # generation, no initializer compiles — shaving the warm boot
        # further.  Builders whose init resists abstract evaluation fall
        # back to a real init.
        try:
            p0, s0 = jax.eval_shape(model.init, jax.random.PRNGKey(0))
            like = {"params": p0, "state": s0}
        except Exception:  # noqa: BLE001 — data-dependent init
            model.init_weights()
            like = {"params": model._params, "state": model._state}
        try:
            tree = weightstore.load_store(store_dir, like=like)
        except KeyError:
            # a QUANTIZED store (int8/int4 leaves + scales, PR 14) does not
            # match the float init skeleton — restore by the stored paths
            # (container names remapped onto the fresh model's auto-names,
            # shared leaves verified); layer lookup is key-based, so the
            # nested dicts slot straight in and predict serves quantized
            # from the mmap'd leaves.  The fallback is gated on the store
            # actually holding quantized leaves: a FLOAT store that failed
            # the keyed+positional match is corrupt or belongs to another
            # topology, and must keep failing loudly here, not at first
            # predict
            from analytics_zoo_tpu.inference.quantize import QUANT_LEAVES
            manifest = weightstore.read_manifest(store_dir) or {}
            names = {k.rsplit("/", 1)[-1]
                     for k in (manifest.get("leaves") or {})}
            if not names & set(QUANT_LEAVES):
                raise
            tree = weightstore.load_store_nested(store_dir, like=like)
            # paramless/stateless layers' empty {} slots produce no store
            # leaves; the executor still looks each one up — graft the
            # container skeleton from the template around the restored
            # leaves (params leaves may legitimately differ: {W_q4, s_g}
            # replace the skeleton's {W})
            tree["params"] = weightstore.graft_containers(
                like.get("params", {}), tree.get("params", {}),
                require_leaves=False)
            tree["state"] = weightstore.graft_containers(
                like.get("state", {}), tree.get("state", {}))
        params, state = tree["params"], tree["state"]
        # one transfer at load (vs one per predict for host-resident
        # params): DMA reads the mapped pages directly
        params = jax.device_put(params)
        if state:
            state = jax.device_put(state)
        model.set_weights(params, state)
        out = self.do_load_model(model, params, state)
        self.load_seconds = time.perf_counter() - t0
        self.load_mmap = True
        return out

    def do_load_tensorflow(self, saved_model_path: str,
                           signature: str = "serving_default"):
        """Wrap a TF SavedModel as the predict function (TFNet analog — see
        interop/tfnet.py; runs through the TF runtime bridge)."""
        from analytics_zoo_tpu.interop.tfnet import TFNet
        net = TFNet.from_saved_model(saved_model_path, signature=signature)
        self._model = net
        self._params, self._state = {}, {}
        self._jitted = lambda p, s, x: net.call({}, x)
        self._bump_epoch()
        return self

    def do_load_onnx(self, onnx_path: str):
        """ONNX model -> native predict function (reference: doLoadOpenVINO /
        onnx_loader.py ModelLoader; here via interop/onnx_loader.py)."""
        from analytics_zoo_tpu.interop.onnx_loader import load_onnx
        net = load_onnx(onnx_path)
        params = net.build(None, None)
        return self.do_load_model(net, params, {})

    def do_load_pytorch(self, model_or_path, example_input=None):
        """PyTorch model -> native predict function (reference: doLoadPyTorch,
        TorchNet.scala:39-242; here the TorchScript graph is imported into
        jnp via interop/torchnet.py — no libtorch at serve time)."""
        from analytics_zoo_tpu.interop.torchnet import TorchNet
        if isinstance(model_or_path, str):
            net = TorchNet(model_or_path)
        else:
            net = TorchNet.from_pytorch(model_or_path, example_input)
        params = net.build(None, None)
        return self.do_load_model(net, params, {})

    # -- quantization ----------------------------------------------------------
    def do_quantize(self, calib_inputs, force: bool = False, bits: int = 8,
                    group_size: int = 64,
                    percentile: Optional[float] = None):
        """Post-training weight quantization of the loaded model (the
        OpenVINO-int8 capability, pipeline/inference/OpenVinoInferenceSupportive
        .scala analog — served through the fused-dequant kernels in
        ops/quant_matmul.py).

        ``bits=8`` (W8A8): `calib_inputs` — one batch, a list of batches,
        or a `FeatureSet` (sampled via quantize.calibrate_featureset) —
        calibrates per-layer activation scales (`percentile` clips the
        range at that percentile of |x| instead of absmax); dense/conv
        weights become int8 with per-output-channel scales, ~4x less
        weight HBM per predict.  ``bits=4`` (W4A16): weight-only int4 with
        group-wise scales (`group_size` contraction rows per scale, two
        weights per byte, ~8x less weight HBM) — no calibration needed,
        `calib_inputs` may be None.

        OPT-IN on TPU v5e (re-measured 2026-07-30 round 5 with the
        LICM-proof timing loop, bench.py bench_resnet50_int8): raw
        s8xs8->s32 kernels reach only ~1.0-1.2x the bf16 rate through this
        XLA stack (tools/int8_matrix.py; bf16 already runs near the
        197 TF/s nameplate — int8 does NOT unlock a doubled MXU rate) — a
        COMPUTE-bound model quantizes for footprint, not speed; the win
        this path exists for is the MEMORY-bound serving regime (wide
        heads, decode steps), where weight bytes are the wall.  Accuracy
        parity holds (top-1 agreement 1.0 int8).  Pass force=True to
        quantize."""
        import warnings

        from analytics_zoo_tpu.inference.quantize import (
            _target_layers, quantize)
        if self._model is None:
            raise RuntimeError("load a model first")
        if not force:
            warnings.warn(
                "weight PTQ trades speed for HBM footprint on compute-bound "
                "models through this XLA stack (~0.84x end-to-end ResNet-50; "
                "raw-kernel matrix in tools/int8_matrix.py) — skipping "
                "quantization. Pass force=True to quantize anyway.",
                stacklevel=2)
            return self
        if not _target_layers(self._model, self._params or {}):
            # nothing quantizable (e.g. a TFNet-backed model whose predict
            # lambda must stay un-jitted) — leave the loaded path untouched
            return self
        self._params = quantize(self._model, self._params, self._state or {},
                                calib_inputs, bits=bits,
                                group_size=group_size, percentile=percentile)
        model = self._model
        self._jitted = jax.jit(
            lambda p, s, x: model.apply(p, s, x, training=False)[0])
        self._bump_epoch()     # the quantized graph is a new program
        if self._mesh is not None:
            # quantize rebuilt the params tree on host: re-place it under
            # the plan already in force (leaves whose new shapes no longer
            # divide fall back per _fit, with its one-time warning)
            self._params = self._plan.shard(self._params, self._mesh)
        return self

    # -- async dispatch (serving hot path, PR 3) ------------------------------
    class _Pending:
        """Handle for one async-dispatched batch: the jitted program is
        already enqueued on the device; ``result()`` blocks on the host
        transfer and strips the bucket padding."""

        def __init__(self, device_out, take: int):
            self._out = device_out
            self._take = take

        def result(self):
            take = self._take
            return jax.tree.map(lambda a: np.asarray(a)[:take], self._out)

    def dispatch(self, x, scales: Optional[np.ndarray] = None) -> "_Pending":
        """Dispatch ONE batch to the device without blocking on the host
        readback.  JAX dispatch is asynchronous, so the caller's next stage
        (preprocessing batch k+1, writing batch k-1's results) overlaps this
        batch's device compute; call ``.result()`` on the returned handle to
        transfer the outputs.  Pads to the same power-of-two bucket as
        `do_predict`, so the two paths share one compile cache.

        Unlike `do_predict` this takes no concurrency semaphore and does no
        internal chunking — callers (the serving engine's
        ``inflight_batches`` bound) cap how many handles they keep open; a
        batch larger than ``max_batch`` falls back to the chunking
        synchronous path, evaluated lazily at ``result()``."""
        if self._jitted is None:
            raise RuntimeError("load a model first")
        t0 = time.perf_counter()
        multi = isinstance(x, (list, tuple))
        if scales is not None and multi:
            raise ValueError("scales= supports single-input models only")
        xs = [np.asarray(a) for a in (x if multi else [x])]
        n = xs[0].shape[0]
        if n > self.max_batch:
            return _LazyPending(lambda: self.do_predict(x, scales=scales))
        bucket = _bucket(n, self.max_batch, self._batch_multiple)
        xs, sc = _pad_to_bucket(xs, scales, n, bucket)
        xs, sc = self._commit(xs, sc)
        out = self._padded_call(xs, sc, multi)
        self._observe("dispatch", n, time.perf_counter() - t0)
        return self._Pending(out, n)

    # -- predict --------------------------------------------------------------
    def _jitted_with_scales(self):
        """Lazily-built dequantizing predict: the int8/uint8 batch is
        TRANSFERRED in its compact dtype and multiplied by the per-row scale
        on device (round 5 serving wire path) — 4x less host->device
        traffic than shipping f32.

        Wrappers are cached PER BASE PROGRAM (PR 11 churn fix): the old
        single-slot cache was discarded whenever `_jitted` drifted, so a
        base that flipped A -> B -> A (instance patches, chaos shims,
        re-quantize round-trips) rebuilt the jit wrapper — and with it an
        empty compile cache — every flip.  Now each base keeps its wrapper
        (bounded; epoch bumps clear the table), and the AOT executable
        cache keys by signature rather than wrapper identity, so interleaved
        scaled/unscaled dispatches never recompile a bucket they have
        already paid for."""
        base = self._jitted
        fn = self._scaled_wrappers.get(base)
        if fn is not None:
            self._jitted_scaled, self._jitted_scaled_base = fn, base
            return fn
        import jax.numpy as jnp
        if hasattr(base, "lower"):        # a real jitted program

            def fn(p, s, x, sc):
                xf = x.astype(jnp.float32) \
                    * sc.reshape(sc.shape + (1,) * (x.ndim - 1))
                return base(p, s, xf)
            fn = jax.jit(fn)
        else:
            # un-jittable bridge path (e.g. TFNet lambda): dequantize on
            # host — correctness over the transfer win
            def fn(p, s, x, sc):
                xf = np.asarray(x, np.float32) * np.asarray(
                    sc, np.float32).reshape(
                        sc.shape + (1,) * (np.ndim(x) - 1))
                return base(p, s, xf)
        if len(self._scaled_wrappers) >= 8:
            # bounded: drop the oldest wrapper (its AOT executables stay
            # valid — they are keyed by signature, not by the wrapper)
            self._scaled_wrappers.pop(next(iter(self._scaled_wrappers)))
        self._scaled_wrappers[base] = fn
        # legacy aliases (pre-PR-11 callers/tests poked at these)
        self._jitted_scaled, self._jitted_scaled_base = fn, base
        return fn

    def do_predict(self, x, batch_size: Optional[int] = None,
                   scales: Optional[np.ndarray] = None) -> np.ndarray:
        """Batched forward with power-of-two bucket padding: at most
        log2(max_batch) compiled programs ever exist per input signature.
        Up to `supported_concurrent_num` batches stay in flight on the
        device before their (blocking) host readback.

        `scales` (round 5): per-row dequantization factors for a compact
        int8/uint8 `x` — the rows reach the device in their wire dtype and
        are dequantized there (single-input models only)."""
        if self._jitted is None:
            raise RuntimeError("load a model first")
        t0 = time.perf_counter()
        multi = isinstance(x, (list, tuple))
        if scales is not None and multi:
            raise ValueError("scales= supports single-input models only")
        xs = [np.asarray(a) for a in (x if multi else [x])]
        sc = None if scales is None else np.asarray(scales, np.float32)
        n = xs[0].shape[0]
        step = batch_size or self.max_batch
        outs = []
        pending: List = []   # (device result, take) not yet read back

        def drain_one():
            y, take = pending.pop(0)
            outs.append(jax.tree.map(lambda a: np.asarray(a)[:take], y))

        with self._sem:
            i = 0
            while i < n:
                take = min(step, n - i)
                bucket = _bucket(take, self.max_batch, self._batch_multiple)
                chunk = [a[i:i + take] for a in xs]
                chunk, schunk = _pad_to_bucket(
                    chunk, None if sc is None else sc[i:i + take],
                    take, bucket)
                chunk, schunk = self._commit(chunk, schunk)
                pending.append(
                    (self._padded_call(chunk, schunk, multi), take))
                if len(pending) >= self.concurrent_num:
                    drain_one()
                i += take
            while pending:
                drain_one()
        self._observe("do_predict", n, time.perf_counter() - t0)
        if isinstance(outs[0], (list, tuple)):
            return [np.concatenate([o[j] for o in outs])
                    for j in range(len(outs[0]))]
        return np.concatenate(outs)

    # reference-style aliases
    predict = do_predict
