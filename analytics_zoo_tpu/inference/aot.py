"""Ahead-of-time compile warm-up for serving replicas (PR 11 tentpole).

A fresh replica used to pay a full XLA trace+compile the first time each
power-of-two bucket arrived — the PR 10 chaos bench had to pre-warm buckets
by hand so cold compiles would not read as SLO violations, and the
autoscaler's scale-up decisions actuated a compile-time late.  This module
makes cold start a *derived, measured* path:

- ``warmup_manifest(model, ...)`` enumerates every program a deployment can
  hit — one entry per ``(bucket, dtype, scales-variant)`` over the mesh
  placement in force — straight from the same ``_bucket`` ladder
  ``do_predict``/``dispatch`` use (including the non-pow-2 ``max_batch``
  clamp and the PR 6 mesh-multiple rounding), so the warm-up set is exactly
  the serve-time compile set, not a guess.
- ``warm_up(model, manifest)`` compiles each entry via
  ``jax.jit(...).lower().compile()`` and parks the executable in the
  model's AOT cache, which ``do_predict``/``dispatch``/
  ``_jitted_with_scales`` consult BEFORE tracing — a warmed bucket is never
  traced again, and a ``_jitted_scaled_base`` rebuild cannot invalidate it
  (the cache is keyed by load epoch + signature, not wrapper identity).
- ``enable_persistent_cache(dir)`` wires jax's persistent compilation cache
  at a per-deployment directory (the manager points every replica of one
  deployment at ``<pidfile>.xla_cache``): the *second* replica of a
  topology loads executables from disk instead of compiling at all.
- ``COMPILE_STATS`` counts what actually happened via jax's monitoring
  events: compile REQUESTS (fired whether the persistent cache answers or
  not) and persistent-cache hits/misses — with every program cacheable,
  ``cache_misses`` is the true backend-compile count, so "the warm path
  performs zero XLA compiles" is a tested number, not a hope.

Single-input models only (the serving engine stacks one tensor per record);
multi-input ``do_predict`` callers still go through the same AOT cache,
they just warm lazily on first use.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)


class WarmupEntry(NamedTuple):
    """One compiled program of the warm-up set.  ``shape`` is the
    per-record tail shape (the batch axis is ``bucket``); ``scales`` marks
    the int8-wire variant that dequantizes on device with per-row scales;
    ``mesh``/``sharding`` record the placement the program is lowered
    against (informational — the model's live mesh is what the compile
    actually uses)."""

    bucket: int
    shape: Tuple[int, ...]
    dtype: str                       # numpy dtype str of the wire batch
    scales: bool
    mesh: Optional[Tuple[int, int]]  # (data, model) axes, None = single-chip
    sharding: str                    # off | batch | tensor | hybrid
    # quantized-weight program variant (PR 14): "float" | "w8" | "w4" —
    # informational like mesh/sharding (the model's live params decide what
    # the compile lowers against), but it makes the quantized program set
    # explicit in `manager warmup` output and pins the manifest derivation
    # to the graph actually deployed
    variant: str = "float"


class CompileStats:
    """Process-wide XLA compile accounting, fed by jax's monitoring
    events.  ``compile_requests`` counts trips into
    ``compile_or_get_cached`` (the ``backend_compile_duration`` event
    wraps the whole call on this jax, so it fires even when the
    persistent cache serves the binary — it measures how often the
    tracing layer ASKED for an executable, and its seconds include cache
    retrieval).  ``cache_hits``/``cache_misses`` count persistent-cache
    traffic once a cache dir is configured: with every program cacheable
    (see ``enable_persistent_cache``), **``cache_misses`` IS the true
    backend-compile count** — the warm path asserts it stays zero."""

    def __init__(self):
        self._lock = threading.Lock()
        self.compile_requests = 0
        self.compile_seconds = 0.0
        self.cache_hits = 0
        self.cache_misses = 0

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {"compile_requests": self.compile_requests,
                    "compile_seconds": round(self.compile_seconds, 3),
                    "cache_hits": self.cache_hits,
                    "cache_misses": self.cache_misses}

    def _event(self, key: str, **kw) -> None:
        if key == "/jax/compilation_cache/cache_hits":
            with self._lock:
                self.cache_hits += 1
        elif key == "/jax/compilation_cache/cache_misses":
            with self._lock:
                self.cache_misses += 1

    def _duration(self, key: str, dur: float, **kw) -> None:
        if key == "/jax/core/compile/backend_compile_duration":
            with self._lock:
                self.compile_requests += 1
                self.compile_seconds += float(dur)
            # incident flight recorder (PR 15): compile requests are
            # first-class forensic events — "the replica was compiling"
            # explains a stall better than any latency histogram
            try:
                from analytics_zoo_tpu.common.observability import (
                    get_recorder)
                get_recorder().record("compile",
                                      seconds=round(float(dur), 4))
            except Exception:  # noqa: BLE001 — diagnostics only
                pass


COMPILE_STATS = CompileStats()
_LISTENERS_INSTALLED = False
_INSTALL_LOCK = threading.Lock()


def install_compile_listeners() -> CompileStats:
    """Register the monitoring listeners feeding ``COMPILE_STATS``
    (idempotent; jax keeps listeners for the process lifetime)."""
    global _LISTENERS_INSTALLED
    with _INSTALL_LOCK:
        if _LISTENERS_INSTALLED:
            return COMPILE_STATS
        from jax._src import monitoring
        monitoring.register_event_listener(COMPILE_STATS._event)
        monitoring.register_event_duration_secs_listener(
            COMPILE_STATS._duration)
        _LISTENERS_INSTALLED = True
    return COMPILE_STATS


def enable_persistent_cache(path: str) -> str:
    """Point jax's persistent compilation cache at ``path`` (created if
    missing) and drop the min-compile-time/min-entry-size thresholds so
    EVERY serving program lands in it — the serving bucket programs are
    individually small and fast to compile, exactly what the default
    thresholds skip.  Process-global (jax.config); every replica of one
    deployment shares the same directory, so the second replica of a
    topology reads executables instead of compiling.  Returns the path."""
    import jax
    if getattr(jax.config, "jax_compilation_cache_dir", None) == path:
        # already wired (a replica boot enables before model load AND at
        # engine start): skip the config churn and the repeat log line
        install_compile_listeners()
        return path
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except AttributeError:  # older jax without the size threshold
        pass
    install_compile_listeners()
    logger.info("aot: persistent XLA compilation cache at %s", path)
    return path


def bucket_ladder(max_batch: int, multiple: int = 1,
                  model_cap: Optional[int] = None) -> List[int]:
    """Every bucket ``_bucket(n, cap, multiple)`` can produce for
    ``1 <= n <= max_batch`` — the exact compile set a deployment serving
    batches up to ``max_batch`` walks through.  ``model_cap`` is the
    model's (pow-2-clamped) ``max_batch`` ceiling; the engine's adaptive
    batcher never reads more than its own ``max_batch`` records, so the
    ladder stops at the smaller of the two."""
    from analytics_zoo_tpu.inference.inference_model import _bucket
    cap = int(model_cap) if model_cap is not None else int(max_batch)
    seen = []
    n = 1
    while n <= max(1, int(max_batch)):
        b = _bucket(n, cap, multiple)
        if b not in seen:
            seen.append(b)
        if n >= max_batch:
            break
        n = min(n * 2, int(max_batch))
    return sorted(seen)


def infer_input_spec(model) -> Optional[Tuple[Tuple[int, ...], str]]:
    """Best-effort per-record input spec ``(tail_shape, dtype)`` from the
    loaded topology's declared input shape (Sequential/Model builders
    carry it); None when the model does not declare one — the caller must
    then supply an explicit spec."""
    inner = getattr(model, "_model", None)
    shape = getattr(inner, "_declared_input_shape", None)
    if shape is None:
        return None
    try:
        return tuple(int(s) for s in shape), "<f4"
    except (TypeError, ValueError):
        return None


def warmup_manifest(model, input_shape=None, dtype: str = "<f4",
                    max_batch: Optional[int] = None,
                    scales: str = "auto",
                    scale_dtypes: Sequence[str] = ("|i1",)
                    ) -> List[WarmupEntry]:
    """Derive the warm-up set for ``model`` as deployed: one entry per
    ``(bucket, dtype, scales-variant)`` over the placement in force.

    ``input_shape``/``dtype`` describe ONE record on the wire (default:
    the topology's declared input shape, f32).  ``max_batch`` is the
    engine's adaptive-batcher ceiling (default: the model's own pow-2
    ``max_batch``); buckets come from the same ladder ``do_predict`` pads
    to, so the mesh-multiple rounding and the non-pow-2 clamp are
    reproduced, not re-implemented.  ``scales``: ``"off"`` plain-only,
    ``"both"`` every bucket per scale dtype (plus the plain entry),
    ``"auto"``/``"on"`` = scale variants when the program is jit-compiled
    (the int8 wire is part of the serving surface), plain-only for bridge
    models.  ``scale_dtypes`` names the compact wire dtypes the scale
    variants arrive in — default the int8 wire; deployments serving u8
    images (``QuantizedTensor(uint8, 1.0)`` records) add ``"|u1"`` via
    the spec so their per-row-scale program warms too."""
    if input_shape is None:
        spec = infer_input_spec(model)
        if spec is None:
            raise ValueError(
                "warmup_manifest: the model declares no input shape; pass "
                "input_shape=(d0, ...) for one record")
        input_shape, dtype = spec
    tail = tuple(int(s) for s in input_shape)
    multiple = int(getattr(model, "_batch_multiple", 1) or 1)
    cap = int(getattr(model, "max_batch", 1024) or 1024)
    mb = int(max_batch) if max_batch else cap
    mesh = None
    mode = getattr(model, "_sharding_mode", None) or "off"
    m = getattr(model, "_mesh", None)
    if m is not None:
        mesh = (int(m.shape.get("data", 1)), int(m.shape.get("model", 1)))
    jit_ok = hasattr(getattr(model, "_jitted", None), "lower")
    if scales in ("auto", "on"):
        want_scales = jit_ok
    elif scales == "both":
        want_scales = True
    else:
        want_scales = False
    # quantized-weight deployments (PR 14): the manifest enumerates the
    # SAME (bucket, dtype, scales) surface, but every program lowers
    # against the quantized graph — stamp the variant so the warm set is
    # explicit about which program family it compiled (do_quantize bumps
    # the AOT epoch, so float and quantized executables can never mix)
    try:
        from analytics_zoo_tpu.inference.quantize import quantized_bits
        variant = {8: "w8", 4: "w4"}.get(
            quantized_bits(getattr(model, "_params", None) or {}), "float")
    except Exception:  # noqa: BLE001 — exotic bridge params
        variant = "float"
    entries: List[WarmupEntry] = []
    for bucket in bucket_ladder(mb, multiple, model_cap=cap):
        entries.append(WarmupEntry(bucket, tail, np.dtype(dtype).str,
                                   False, mesh, mode, variant))
        if want_scales:
            # compact-wire variants: the batch arrives in its wire dtype
            # with per-row dequant scales (engine QuantizedTensor path)
            for sdt in scale_dtypes:
                entries.append(WarmupEntry(bucket, tail,
                                           np.dtype(sdt).str, True,
                                           mesh, mode, variant))
    return entries


class GenWarmupEntry(NamedTuple):
    """One program of a generation deployment's warm-up set (PR 12
    continuous batching): the scheduler runs one ``prefill`` program per
    (admission-batch, prompt-bucket, lane), one ``insert`` per
    (admission-batch, lane), and one ``decode_step`` per lane — the
    (prefill-bucket x decode-step) set a warm replica must hold to serve
    its first token with zero compiles."""

    kind: str                        # prefill | decode_step | insert |
    #                                  paged_prefill | paged_shared |
    #                                  paged_decode
    prefill_bucket: Optional[int]    # prompt padding bucket (prefill only)
    lane_bucket: int                 # decode lane capacity bucket
    prefill_batch: Optional[int] = None   # admission batch bucket (pow-2)
    prefix_blocks: Optional[int] = None   # prefix-table bucket
    #                                       (paged_shared only)


def generation_manifest(prefill_buckets: Sequence[int],
                        lane_buckets: Sequence[int],
                        prefill_batches: Sequence[int] = (1,),
                        cache_model: bool = True,
                        paged: bool = False,
                        prefix_blocks: Sequence[int] = ()
                        ) -> List[GenWarmupEntry]:
    """Enumerate the continuous-batching program set: for every decode
    lane, its step program, plus — per admission-batch bucket — one
    insert program and one prefill program per prompt bucket.  The ONE
    enumeration shared by ``ContinuousBatcher.warm`` and the serving
    warm-up manifest, so the pre-warm pass compiles exactly the set the
    scheduler will look up.  ``cache_model=True`` keeps only prompt
    buckets that fit the lane (prefill allocates the KV cache at lane
    capacity, so bigger prompts can never run there); bare-state models
    (lane capacity is not a prompt bound — the scheduler pads any
    admissible prompt to any bucket of the ladder) keep them all.

    ``paged=True`` (PR 18) swaps the set for the paged-pool programs:
    one ``paged_decode`` per lane, one ``paged_prefill`` (prompt forward
    + block commit, no separate insert) per (batch, prompt bucket), and
    — when prefix sharing is on (``prefix_blocks`` non-empty) — one
    ``paged_shared`` per (batch, suffix bucket, prefix-table bucket)."""
    entries: List[GenWarmupEntry] = []
    for lane in sorted({int(b) for b in lane_buckets}):
        if paged:
            entries.append(GenWarmupEntry("paged_decode", None, lane))
            for bb in sorted({int(b) for b in prefill_batches}):
                for pb in sorted({int(b) for b in prefill_buckets}):
                    if pb > lane and cache_model:
                        continue
                    entries.append(GenWarmupEntry(
                        "paged_prefill", pb, lane, bb))
                    for npb in sorted({int(b) for b in prefix_blocks}):
                        entries.append(GenWarmupEntry(
                            "paged_shared", pb, lane, bb, npb))
            continue
        entries.append(GenWarmupEntry("decode_step", None, lane))
        for bb in sorted({int(b) for b in prefill_batches}):
            entries.append(GenWarmupEntry("insert", None, lane, bb))
            for pb in sorted({int(b) for b in prefill_buckets}):
                if pb <= lane or not cache_model:
                    entries.append(GenWarmupEntry("prefill", pb, lane, bb))
    return entries


def resolve_manifest(model, warmup_spec) -> List[WarmupEntry]:
    """Manifest from a ``ServingParams.warmup`` value: ``True`` derives
    everything from the model, a spec dict ``{"shape", "dtype", "scales",
    "max_batch"}`` overrides per key — the ONE resolution shared by the
    serving engine and ``manager warmup`` so the pre-warm pass compiles
    exactly the set the replicas will look up."""
    spec = warmup_spec if isinstance(warmup_spec, dict) else {}
    return warmup_manifest(
        model,
        input_shape=spec.get("shape"),
        dtype=str(spec.get("dtype", "<f4")),
        max_batch=spec.get("max_batch"),
        scales=str(spec.get("scales", "auto")),
        scale_dtypes=tuple(spec.get("scale_dtypes") or ("|i1",)))


def warm_up(model, manifest: Optional[Sequence[WarmupEntry]] = None,
            progress=None, stop=None, **manifest_kw) -> Dict:
    """Compile every program in ``manifest`` (default: derived via
    ``warmup_manifest``) into the model's AOT executable cache.  Each
    entry that is already cached (an earlier warm-up, or a live request
    that beat us to it) is skipped for free.  ``progress(done, total,
    entry)`` is called after each entry — the serving engine uses it to
    publish per-bucket progress on ``/readyz``.

    Returns ``{"programs", "compiled", "skipped", "failed", "seconds",
    "compile_stats"}`` where ``compile_stats`` is the COMPILE_STATS delta
    for the pass — on a process whose persistent cache is already
    populated, ``cache_misses`` stays 0 and ``cache_hits`` covers the
    set (the zero-cold-start evidence)."""
    install_compile_listeners()
    if manifest is None:
        manifest = warmup_manifest(model, **manifest_kw)
    before = COMPILE_STATS.snapshot()
    t0 = time.monotonic()
    compiled = skipped = failed = 0
    stopped = False
    for i, entry in enumerate(manifest):
        if stop is not None and stop():
            # a draining engine must not keep the process alive compiling
            # programs nobody will run
            stopped = True
            break
        try:
            fresh = model.warm(entry.bucket, entry.shape, dtype=entry.dtype,
                               scales=entry.scales)
            compiled += 1 if fresh else 0
            skipped += 0 if fresh else 1
        except Exception as e:  # noqa: BLE001 — one bad entry must not
            # strand the rest of the set (the live path falls back to
            # tracing for whatever stays cold)
            failed += 1
            logger.warning("aot: warm-up entry %s failed (%s: %s)",
                           entry, type(e).__name__, e)
        if progress is not None:
            progress(i + 1, len(manifest), entry)
    after = COMPILE_STATS.snapshot()
    stats = {
        "programs": len(manifest),
        "compiled": compiled,
        "skipped": skipped,
        "failed": failed,
        "stopped": stopped,
        "seconds": round(time.monotonic() - t0, 3),
        "compile_stats": {k: round(after[k] - before[k], 3)
                          for k in after},
    }
    logger.info("aot: warm-up %d program(s) in %.2fs (%d fresh, %d cached, "
                "%d failed; %s backend compile(s), %s cache hit(s))",
                stats["programs"], stats["seconds"], compiled, skipped,
                failed, stats["compile_stats"]["cache_misses"],
                stats["compile_stats"]["cache_hits"])
    return stats
