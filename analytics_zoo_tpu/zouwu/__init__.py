from analytics_zoo_tpu.zouwu.forecast import (
    AutoTSTrainer, Forecaster, LSTMForecaster, MTNetForecaster, MTNetLayer,
    Seq2SeqForecaster, TSPipeline)

__all__ = ["AutoTSTrainer", "TSPipeline", "Forecaster", "LSTMForecaster",
           "Seq2SeqForecaster", "MTNetForecaster", "MTNetLayer"]
