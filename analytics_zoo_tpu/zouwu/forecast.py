"""Zouwu — time-series forecasting toolkit.

Reference parity: pyzoo/zoo/zouwu — `LSTMForecaster` (model/forecast.py:49-107),
`MTNetForecaster` (:108-160), `AutoTSTrainer` (autots/forecast.py:22-79) and
`TSPipeline` (:81-170).  Forecasters are thin KerasNet builds (the reference builds
TFPark KerasModels); AutoTS wraps the automl TimeSequencePredictor.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import pandas as pd

from analytics_zoo_tpu.automl.regression import (
    Recipe, TimeSequencePipeline, TimeSequencePredictor)
from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.nn.graph import Input
from analytics_zoo_tpu.nn.layers.conv import Convolution1D
from analytics_zoo_tpu.nn.layers.core import (
    Dense, Dropout, Flatten, Lambda, merge)
from analytics_zoo_tpu.nn.layers.recurrent import GRU, LSTM
from analytics_zoo_tpu.nn.models import Model, Sequential


class Forecaster(ZooModel):
    """Common fit/predict surface over (B, lookback, features) windows."""

    def fit(self, x, y, **kw):
        kw.setdefault("verbose", False)
        return self.model.fit(x, y, **kw)


class LSTMForecaster(Forecaster):
    """Two stacked LSTMs + dropout -> dense horizon head (forecast.py:49-107)."""

    def __init__(self, horizon: int = 1, feature_dim: int = 1,
                 lookback: int = 10, lstm_1_units: int = 16,
                 lstm_2_units: int = 8, dropout: float = 0.2,
                 target_col_num: int = 1):
        self.horizon = horizon
        self.feature_dim = feature_dim
        self.lookback = lookback
        self.l1, self.l2 = lstm_1_units, lstm_2_units
        self.dropout = dropout
        super().__init__()

    def build_model(self) -> Sequential:
        m = Sequential(name="LSTMForecaster")
        m.add(LSTM(self.l1, return_sequences=True,
                   input_shape=(self.lookback, self.feature_dim),
                   name="zf_lstm1"))
        m.add(Dropout(self.dropout, name="zf_drop1"))
        m.add(LSTM(self.l2, return_sequences=False, name="zf_lstm2"))
        m.add(Dropout(self.dropout, name="zf_drop2"))
        m.add(Dense(self.horizon, name="zf_out"))
        return m


class Seq2SeqForecaster(Forecaster):
    """GRU encoder-decoder forecaster (automl/model Seq2Seq flavour)."""

    def __init__(self, horizon: int = 1, feature_dim: int = 1,
                 lookback: int = 10, latent_dim: int = 32,
                 dropout: float = 0.1):
        self.horizon = horizon
        self.feature_dim = feature_dim
        self.lookback = lookback
        self.latent = latent_dim
        self.dropout = dropout
        super().__init__()

    def build_model(self) -> Sequential:
        m = Sequential(name="Seq2SeqForecaster")
        m.add(GRU(self.latent, return_sequences=True,
                  input_shape=(self.lookback, self.feature_dim), name="s2s_enc"))
        m.add(Dropout(self.dropout, name="s2s_drop"))
        m.add(GRU(self.latent, return_sequences=False, name="s2s_dec"))
        m.add(Dense(self.horizon, name="s2s_out"))
        return m


class MTNetForecaster(Forecaster):
    """Memory-augmented CNN + attention + autoregressive skip path
    (MTNet, zouwu model/forecast.py:108-160; simplified long/short memory series)."""

    def __init__(self, horizon: int = 1, feature_dim: int = 1,
                 lookback: int = 16, cnn_filters: int = 32,
                 cnn_kernel: int = 3, ar_window: int = 4,
                 dropout: float = 0.1):
        self.horizon = horizon
        self.feature_dim = feature_dim
        self.lookback = lookback
        self.filters = cnn_filters
        self.kernel = cnn_kernel
        self.ar_window = min(ar_window, lookback)
        self.dropout = dropout
        super().__init__()

    def build_model(self) -> Model:
        import jax.numpy as jnp
        inp = Input(shape=(self.lookback, self.feature_dim), name="mt_input")
        conv = Convolution1D(self.filters, self.kernel, activation="relu",
                             border_mode="same", name="mt_conv")(inp)
        enc = GRU(self.filters, return_sequences=False, name="mt_gru")(conv)
        enc = Dropout(self.dropout, name="mt_drop")(enc)
        nonlinear = Dense(self.horizon, name="mt_nl_out")(enc)
        # autoregressive highway on the target channel (last ar_window steps)
        ar_in = Lambda(lambda t: t[:, -self.ar_window:, 0], name="mt_ar_slice")(inp)
        ar = Dense(self.horizon, name="mt_ar")(ar_in)
        out = merge([nonlinear, ar], mode="sum", name="mt_sum")
        return Model(input=inp, output=out, name="MTNetForecaster")


class AutoTSTrainer:
    """AutoML-driven forecaster selection (autots/forecast.py:22-79)."""

    def __init__(self, dt_col: str = "datetime", target_col: str = "value",
                 horizon: int = 1,
                 extra_features_col: Optional[Sequence[str]] = None,
                 recipe: Optional[Recipe] = None):
        self._predictor = TimeSequencePredictor(
            dt_col=dt_col, target_col=target_col,
            extra_features_col=extra_features_col, future_seq_len=horizon,
            recipe=recipe)

    def fit(self, train_df: pd.DataFrame,
            validation_df: Optional[pd.DataFrame] = None) -> "TSPipeline":
        pipe = self._predictor.fit(train_df, validation_df)
        return TSPipeline(pipe)


class TSPipeline:
    """Deployable fitted pipeline (autots/forecast.py:81-170)."""

    def __init__(self, pipeline: TimeSequencePipeline):
        self._p = pipeline

    def predict(self, df: pd.DataFrame) -> np.ndarray:
        return self._p.predict(df)

    def evaluate(self, df: pd.DataFrame, metrics=("mse", "smape")):
        return self._p.evaluate(df, metrics)

    def save(self, path: str):
        self._p.save(path)

    @staticmethod
    def load(path: str) -> "TSPipeline":
        return TSPipeline(TimeSequencePipeline.load(path))
