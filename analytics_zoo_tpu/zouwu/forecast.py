"""Zouwu — time-series forecasting toolkit.

Reference parity: pyzoo/zoo/zouwu — `LSTMForecaster` (model/forecast.py:49-107),
`MTNetForecaster` (:108-160), `AutoTSTrainer` (autots/forecast.py:22-79) and
`TSPipeline` (:81-170).  Forecasters are thin KerasNet builds (the reference builds
TFPark KerasModels); AutoTS wraps the automl TimeSequencePredictor.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np
import pandas as pd

from analytics_zoo_tpu.automl.regression import (
    Recipe, TimeSequencePipeline, TimeSequencePredictor)
from analytics_zoo_tpu.nn.module import Layer as _Layer
from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.nn.graph import Input
from analytics_zoo_tpu.nn.layers.conv import Convolution1D
from analytics_zoo_tpu.nn.layers.core import (
    Dense, Dropout, Flatten, Lambda, merge)
from analytics_zoo_tpu.nn.layers.recurrent import GRU, LSTM
from analytics_zoo_tpu.nn.models import Model, Sequential


class Forecaster(ZooModel):
    """Common fit/predict surface over (B, lookback, features) windows."""

    def fit(self, x, y, **kw):
        kw.setdefault("verbose", False)
        return self.model.fit(x, y, **kw)


class LSTMForecaster(Forecaster):
    """Two stacked LSTMs + dropout -> dense horizon head (forecast.py:49-107)."""

    def __init__(self, horizon: int = 1, feature_dim: int = 1,
                 lookback: int = 10, lstm_1_units: int = 16,
                 lstm_2_units: int = 8, dropout: float = 0.2,
                 target_col_num: int = 1):
        self.horizon = horizon
        self.feature_dim = feature_dim
        self.lookback = lookback
        self.l1, self.l2 = lstm_1_units, lstm_2_units
        self.dropout = dropout
        super().__init__()

    def build_model(self) -> Sequential:
        m = Sequential(name="LSTMForecaster")
        m.add(LSTM(self.l1, return_sequences=True,
                   input_shape=(self.lookback, self.feature_dim),
                   name="zf_lstm1"))
        m.add(Dropout(self.dropout, name="zf_drop1"))
        m.add(LSTM(self.l2, return_sequences=False, name="zf_lstm2"))
        m.add(Dropout(self.dropout, name="zf_drop2"))
        m.add(Dense(self.horizon, name="zf_out"))
        return m


class Seq2SeqForecaster(Forecaster):
    """GRU encoder-decoder forecaster (automl/model Seq2Seq flavour)."""

    def __init__(self, horizon: int = 1, feature_dim: int = 1,
                 lookback: int = 10, latent_dim: int = 32,
                 dropout: float = 0.1):
        self.horizon = horizon
        self.feature_dim = feature_dim
        self.lookback = lookback
        self.latent = latent_dim
        self.dropout = dropout
        super().__init__()

    def build_model(self) -> Sequential:
        m = Sequential(name="Seq2SeqForecaster")
        m.add(GRU(self.latent, return_sequences=True,
                  input_shape=(self.lookback, self.feature_dim), name="s2s_enc"))
        m.add(Dropout(self.dropout, name="s2s_drop"))
        m.add(GRU(self.latent, return_sequences=False, name="s2s_dec"))
        m.add(Dense(self.horizon, name="s2s_out"))
        return m


class MTNetLayer(_Layer):
    """Memory Time-series Network (MTNet, Chang et al. 2018) — the FULL
    architecture behind the reference's MTNetForecaster
    (zouwu/model/forecast.py:108-160 over zoo.automl.model.MTNet):

    Input (B, (long_num + 1) * time_step, D): the first long_num*time_step
    rows are the long-term memory blocks X_1..X_n; the last time_step rows
    are the short-term query series Q.

      * three block encoders (separate weights, shared across blocks):
        Conv1D(filters, kernel, same) -> relu -> dropout -> GRU(uni_size)
        last state: Enc_m (memory keys m_i), Enc_c (memory values c_i),
        Enc_in (query embedding u from Q);
      * memory attention: p = softmax(<m_i, u>); context o = sum_i p_i c_i;
      * nonlinear head: y_nl = [o ; u] W + b;
      * autoregressive highway on the target channel's last ar_size steps:
        y = y_nl + y_ar.
    """

    def __init__(self, horizon: int, time_step: int, long_num: int,
                 filters: int = 32, kernel: int = 3, uni_size: int = 32,
                 ar_size: int = 4, dropout: float = 0.1, **kwargs):
        super().__init__(**kwargs)
        self.horizon = int(horizon)
        self.time_step = int(time_step)
        self.long_num = int(long_num)
        # ar_size=0 disables the autoregressive highway entirely
        self.ar_size = min(max(int(ar_size), 0), self.time_step)
        self.drop = float(dropout)
        nm = self.name
        self._encs = {}
        for which in ("m", "c", "q"):
            self._encs[which] = (
                Convolution1D(filters, kernel, activation="relu",
                              border_mode="same", name=f"{nm}_conv_{which}"),
                GRU(uni_size, return_sequences=False,
                    name=f"{nm}_gru_{which}"))
        self.uni = int(uni_size)

    def build(self, rng, input_shape):
        import jax
        T, D = input_shape[-2] // (self.long_num + 1), input_shape[-1]
        rs = jax.random.split(rng, 8)
        p = {}
        for i, which in enumerate(("m", "c", "q")):
            conv, gru = self._encs[which]
            p[f"conv_{which}"] = conv.build(rs[2 * i], (T, D))
            cout = self._encs[which][0].nb_filter
            p[f"gru_{which}"] = gru.build(rs[2 * i + 1], (T, cout))
        p["head"] = {
            "W": 0.05 * jax.random.normal(rs[6], (2 * self.uni, self.horizon)),
            "b": jnp.zeros((self.horizon,))}
        if self.ar_size > 0:
            p["ar"] = {
                "W": 0.05 * jax.random.normal(
                    rs[7], (self.ar_size, self.horizon)),
                "b": jnp.zeros((self.horizon,))}
        return p

    def _encode(self, params, which, x, *, training, rng):
        conv, gru = self._encs[which]
        h = conv.call(params[f"conv_{which}"], x, training=training)
        if training and rng is not None and self.drop > 0:
            import jax
            keep = 1.0 - self.drop
            h = jnp.where(jax.random.bernoulli(rng, keep, h.shape),
                          h / keep, 0.0)
        return gru.call(params[f"gru_{which}"], h, training=training)

    def call(self, params, x, *, training=False, rng=None):
        import jax
        B, total, D = x.shape
        n, T = self.long_num, self.time_step
        mem = x[:, :n * T].reshape(B * n, T, D)
        q = x[:, n * T:]
        rngs = (jax.random.split(rng, 3) if rng is not None
                else (None, None, None))
        m = self._encode(params, "m", mem, training=training,
                         rng=rngs[0]).reshape(B, n, self.uni)
        c = self._encode(params, "c", mem, training=training,
                         rng=rngs[1]).reshape(B, n, self.uni)
        u = self._encode(params, "q", q, training=training, rng=rngs[2])
        att = jax.nn.softmax(jnp.einsum("bnu,bu->bn", m, u), axis=-1)
        o = jnp.einsum("bn,bnu->bu", att, c)
        y_nl = jnp.concatenate([o, u], axis=-1) @ params["head"]["W"] \
            + params["head"]["b"]
        if self.ar_size == 0:
            return y_nl
        ar_in = x[:, -self.ar_size:, 0]
        y_ar = ar_in @ params["ar"]["W"] + params["ar"]["b"]
        return y_nl + y_ar


class MTNetForecaster(Forecaster):
    """MTNet forecaster (reference zouwu model/forecast.py:108-160).

    lookback must equal (long_num + 1) * time_step; when time_step is not
    given it is derived as lookback // (long_num + 1)."""

    def __init__(self, horizon: int = 1, feature_dim: int = 1,
                 lookback: int = 16, cnn_filters: int = 32,
                 cnn_kernel: int = 3, ar_window: int = 4,
                 dropout: float = 0.1, long_num: int = 3,
                 time_step: Optional[int] = None, uni_size: int = 32):
        self.horizon = horizon
        self.feature_dim = feature_dim
        self.long_num = int(long_num)
        self.time_step = (int(time_step) if time_step
                          else lookback // (self.long_num + 1))
        if (self.long_num + 1) * self.time_step != lookback:
            raise ValueError(
                f"lookback={lookback} must equal (long_num+1)*time_step "
                f"= {(self.long_num + 1) * self.time_step}")
        self.lookback = lookback
        self.filters = cnn_filters
        self.kernel = cnn_kernel
        self.ar_window = ar_window
        self.dropout = dropout
        self.uni_size = uni_size
        super().__init__()

    def build_model(self) -> Model:
        inp = Input(shape=(self.lookback, self.feature_dim), name="mt_input")
        out = MTNetLayer(self.horizon, self.time_step, self.long_num,
                         filters=self.filters, kernel=self.kernel,
                         uni_size=self.uni_size, ar_size=self.ar_window,
                         dropout=self.dropout, name="mt_net")(inp)
        return Model(input=inp, output=out, name="MTNetForecaster")


class AutoTSTrainer:
    """AutoML-driven forecaster selection (autots/forecast.py:22-79)."""

    def __init__(self, dt_col: str = "datetime", target_col: str = "value",
                 horizon: int = 1,
                 extra_features_col: Optional[Sequence[str]] = None,
                 recipe: Optional[Recipe] = None, distributed: bool = False):
        self._predictor = TimeSequencePredictor(
            dt_col=dt_col, target_col=target_col,
            extra_features_col=extra_features_col, future_seq_len=horizon,
            recipe=recipe, distributed=distributed)

    def fit(self, train_df: pd.DataFrame,
            validation_df: Optional[pd.DataFrame] = None) -> "TSPipeline":
        pipe = self._predictor.fit(train_df, validation_df)
        return TSPipeline(pipe)


class TSPipeline:
    """Deployable fitted pipeline (autots/forecast.py:81-170)."""

    def __init__(self, pipeline: TimeSequencePipeline):
        self._p = pipeline

    def predict(self, df: pd.DataFrame) -> np.ndarray:
        return self._p.predict(df)

    def evaluate(self, df: pd.DataFrame, metrics=("mse", "smape")):
        return self._p.evaluate(df, metrics)

    def save(self, path: str):
        self._p.save(path)

    @staticmethod
    def load(path: str) -> "TSPipeline":
        return TSPipeline(TimeSequencePipeline.load(path))
