r"""Sharding plans — how parameter pytrees lay out over the mesh.

Green-field beyond the reference (SURVEY.md §2.3: the reference is data-parallel only).
A `ShardingPlan` maps parameter tree paths (regex over "layer/leaf" path strings) to
`PartitionSpec`s; the Estimator places params accordingly and GSPMD partitions the
matmuls — Megatron-style tensor parallelism without touching layer code:

    plan = ShardingPlan([
        (r".*_fc\d*/W$",  P(None, "model")),   # column-parallel
        (r".*_proj/W$",   P("model", None)),   # row-parallel
        (r".*embed.*/E$", P("model", None)),   # vocab-sharded embedding
    ])

Axis names follow common/context.py: data / model / pipe / seq / expert.
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.common.context import global_put


def leaf_paths(tree):
    """Flatten a pytree into ("a/b/c", leaf) pairs."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            else:
                parts.append(str(p))
        out.append(("/".join(parts), leaf))
    return out


class ShardingPlan:
    def __init__(self, rules: Sequence[Tuple[str, P]], default: P = P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.default = default

    def spec_for(self, path: str, leaf=None) -> P:
        for pat, spec in self.rules:
            if pat.search(path):
                if leaf is not None and len(spec) > np.ndim(leaf):
                    continue  # rule doesn't fit this rank; keep looking
                return spec
        return self.default

    def shard(self, tree, mesh: Mesh):
        """device_put every leaf with its matched spec (axes not in the mesh are
        dropped from the spec so plans are portable across mesh shapes)."""
        pairs = leaf_paths(tree)
        flat, treedef = jax.tree_util.tree_flatten(tree)
        placed = []
        for (path, leaf), _ in zip(pairs, flat):
            spec = self._fit(self.spec_for(path, leaf), mesh, np.shape(leaf))
            placed.append(global_put(leaf, NamedSharding(mesh, spec)))
        return jax.tree_util.tree_unflatten(treedef, placed)

    def shardings(self, tree, mesh: Mesh):
        """NamedSharding pytree matching `tree` (for jit in_shardings)."""
        pairs = leaf_paths(tree)
        flat, treedef = jax.tree_util.tree_flatten(tree)
        out = [NamedSharding(mesh, self._fit(self.spec_for(p, l), mesh,
                                             np.shape(l)))
               for (p, l) in pairs]
        return jax.tree_util.tree_unflatten(treedef, out)

    @staticmethod
    def _fit(spec: P, mesh: Mesh, shape) -> P:
        """Drop axes missing from the mesh or sized 1; trim to leaf rank; drop axes
        that don't divide the dimension evenly (GSPMD requires divisibility)."""
        rank = len(shape)
        parts = list(spec) + [None] * (rank - len(spec))
        fitted = []
        for dim, ax in zip(shape, parts[:rank]):
            n = mesh.shape.get(ax, 1) if ax is not None else 1
            if ax is None or n == 1 or dim % n != 0:
                fitted.append(None)
            else:
                fitted.append(ax)
        while fitted and fitted[-1] is None:
            fitted.pop()
        return P(*fitted)


def replicated_plan() -> ShardingPlan:
    return ShardingPlan([], default=P())


def megatron_plan(column_patterns: Optional[Sequence[str]] = None,
                  row_patterns: Optional[Sequence[str]] = None,
                  embed_patterns: Optional[Sequence[str]] = None
                  ) -> ShardingPlan:
    """Default tensor-parallel plan for transformer-ish stacks: qkv/ffn-in are
    column-parallel, attention-out/ffn-proj are row-parallel, embeddings vocab-sharded."""
    rules: List[Tuple[str, P]] = []
    for pat in (column_patterns or [r".*qkv/W$", r".*_ffn/fc/W$",
                                    r".*fc\d*/W$"]):
        rules.append((pat, P(None, "model")))
    for pat in (column_patterns or [r".*qkv/b$", r".*_ffn/fc/b$"]):
        rules.append((pat.replace("/W$", "/b$"), P("model",)))
    for pat in (row_patterns or [r".*attn/out/W$", r".*_ffn/proj/W$"]):
        rules.append((pat, P("model", None)))
    for pat in (embed_patterns or [r".*(wte|word|embed.*)/(E)$", r".*wte$",
                                   r".*word$"]):
        rules.append((pat, P("model", None)))
    return rules and ShardingPlan(rules) or replicated_plan()


def data_parallel_batch(ctx, *arrays):
    """Shard batch arrays over the data axis (helper mirroring Estimator._shard)."""
    out = []
    for a in arrays:
        out.append(jax.tree.map(
            lambda v: jax.device_put(v, ctx.data_sharding(np.ndim(v))), a))
    return out
