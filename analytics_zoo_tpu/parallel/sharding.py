r"""Sharding plans — how parameter pytrees lay out over the mesh.

Green-field beyond the reference (SURVEY.md §2.3: the reference is data-parallel only).
A `ShardingPlan` maps parameter tree paths (regex over "layer/leaf" path strings) to
`PartitionSpec`s; the Estimator places params accordingly and GSPMD partitions the
matmuls — Megatron-style tensor parallelism without touching layer code:

    plan = ShardingPlan([
        (r".*_fc\d*/W$",  P(None, "model")),   # column-parallel
        (r".*_proj/W$",   P("model", None)),   # row-parallel
        (r".*embed.*/E$", P("model", None)),   # vocab-sharded embedding
    ])

Axis names follow common/context.py: data / model / pipe / seq / expert.
"""

from __future__ import annotations

import logging
import re
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.common.context import (DATA_AXIS, MODEL_AXIS,
                                              global_put)

logger = logging.getLogger(__name__)


def leaf_paths(tree):
    """Flatten a pytree into ("a/b/c", leaf) pairs."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            else:
                parts.append(str(p))
        out.append(("/".join(parts), leaf))
    return out


class ShardingPlan:
    def __init__(self, rules: Sequence[Tuple[str, P]], default: P = P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.default = default
        # one-time divisibility-fallback warnings (see _fit): a serving
        # replica re-places params per engine, and a repeated warning per
        # request would drown the log without adding information
        self._warned: set = set()

    def spec_for(self, path: str, leaf=None) -> P:
        for pat, spec in self.rules:
            if pat.search(path):
                if leaf is not None and len(spec) > np.ndim(leaf):
                    continue  # rule doesn't fit this rank; keep looking
                return spec
        return self.default

    def shard(self, tree, mesh: Mesh):
        """device_put every leaf with its matched spec (axes not in the mesh are
        dropped from the spec so plans are portable across mesh shapes)."""
        pairs = leaf_paths(tree)
        flat, treedef = jax.tree_util.tree_flatten(tree)
        placed = []
        for (path, leaf), _ in zip(pairs, flat):
            spec = self._fit(self.spec_for(path, leaf), mesh, np.shape(leaf),
                             path=path)
            placed.append(global_put(leaf, NamedSharding(mesh, spec)))
        return jax.tree_util.tree_unflatten(treedef, placed)

    def shardings(self, tree, mesh: Mesh):
        """NamedSharding pytree matching `tree` (for jit in_shardings)."""
        pairs = leaf_paths(tree)
        flat, treedef = jax.tree_util.tree_flatten(tree)
        out = [NamedSharding(mesh, self._fit(self.spec_for(p, l), mesh,
                                             np.shape(l), path=p))
               for (p, l) in pairs]
        return jax.tree_util.tree_unflatten(treedef, out)

    def _fit(self, spec: P, mesh: Mesh, shape, path: Optional[str] = None) -> P:
        """Drop axes missing from the mesh or sized 1; trim to leaf rank; drop axes
        that don't divide the dimension evenly (GSPMD requires divisibility).

        The divisibility fallback replicates THAT dimension and warns once
        per (leaf, axis) instead of letting pjit raise mid-request: a plan
        written for one model must degrade, not crash, when a leaf's
        batch/feature dim doesn't split over the mesh axis."""
        rank = len(shape)
        parts = list(spec) + [None] * (rank - len(spec))
        fitted = []
        for dim, ax in zip(shape, parts[:rank]):
            n = mesh.shape.get(ax, 1) if ax is not None else 1
            if ax is None or n == 1:
                fitted.append(None)
            elif dim % n != 0:
                key = (path, ax, dim, n)
                if key not in self._warned:
                    self._warned.add(key)
                    logger.warning(
                        "sharding plan: leaf %s dim %d is not divisible by "
                        "mesh axis %r (size %d); replicating that dimension "
                        "instead", path or "<unknown>", dim, ax, n)
                fitted.append(None)
            else:
                fitted.append(ax)
        while fitted and fitted[-1] is None:
            fitted.pop()
        return P(*fitted)


def replicated_plan() -> ShardingPlan:
    return ShardingPlan([], default=P())


def _quantized_companions(pat: str, column: bool) -> List[Tuple[str, P]]:
    """Sharding rules for a ``.../W$`` rule's quantized siblings (PR 14):
    the int8/int4 weight leaves split exactly like the float weight they
    replace, and each scale leaf rides the axis its values are indexed by
    — per-out-channel ``s_w`` (N,) splits with a column-parallel out dim
    and replicates for row-parallel; group-wise ``s_g`` (groups, N) keeps
    its group axis with the contraction dim.  (The int8-wire per-row
    activation scales already shard alongside the batch — PR 6; this is
    the same principle applied to the weight-side scales.)"""
    if not pat.endswith("/W$"):
        return []
    wq = pat.replace("/W$", "/W_q$")
    wq4 = pat.replace("/W$", "/W_q4$")
    sw = pat.replace("/W$", "/s_w$")
    sg = pat.replace("/W$", "/s_g$")
    if column:       # (K, N) split on N
        return [(wq, P(None, "model")), (wq4, P(None, "model")),
                (sw, P("model",)), (sg, P(None, "model"))]
    # row-parallel: (K, N) split on K — packed nibbles and groups split
    # along the same contraction axis (ShardingPlan._fit replicates any
    # leaf whose rows don't divide, with its one-time warning)
    return [(wq, P("model", None)), (wq4, P("model", None)),
            (sw, P()), (sg, P("model", None))]


def megatron_plan(column_patterns: Optional[Sequence[str]] = None,
                  row_patterns: Optional[Sequence[str]] = None,
                  embed_patterns: Optional[Sequence[str]] = None
                  ) -> ShardingPlan:
    """Default tensor-parallel plan for transformer-ish stacks: qkv/ffn-in are
    column-parallel, attention-out/ffn-proj are row-parallel, embeddings
    vocab-sharded.  Every weight rule carries its quantized-sibling rules
    (W_q/W_q4 + scales) so a quantized model re-shards consistently under
    the same plan."""
    rules: List[Tuple[str, P]] = []
    for pat in (column_patterns or [r".*qkv/W$", r".*_ffn/fc/W$",
                                    r".*fc\d*/W$"]):
        rules.append((pat, P(None, "model")))
        rules.extend(_quantized_companions(pat, column=True))
    for pat in (column_patterns or [r".*qkv/b$", r".*_ffn/fc/b$"]):
        rules.append((pat.replace("/W$", "/b$"), P("model",)))
    for pat in (row_patterns or [r".*attn/out/W$", r".*_ffn/proj/W$"]):
        rules.append((pat, P("model", None)))
        rules.extend(_quantized_companions(pat, column=False))
    for pat in (embed_patterns or [r".*(wte|word|embed.*)/(E)$", r".*wte$",
                                   r".*word$"]):
        rules.append((pat, P("model", None)))
    return rules and ShardingPlan(rules) or replicated_plan()


def data_parallel_batch(ctx, *arrays):
    """Shard batch arrays over the data axis (helper mirroring Estimator._shard)."""
    out = []
    for a in arrays:
        out.append(jax.tree.map(
            lambda v: jax.device_put(v, ctx.data_sharding(np.ndim(v))), a))
    return out


# -- serving-side plan selection (PR 6: sharded multi-chip serving) -----------

# Below this many parameters, tensor parallelism costs more in per-layer
# all-reduces than it buys in per-chip FLOPs at serving batch sizes: small
# models replicate and shard the BATCH instead.  ~bert_base sits under it,
# bert_large (340M) and up go tensor-parallel.
SERVING_TP_MIN_PARAMS = 50_000_000


def _param_count(params) -> int:
    return int(sum(np.size(l) for l in jax.tree_util.tree_leaves(params)))


def tensor_parallel_applicable(params) -> bool:
    """True when at least one leaf of `params` matches a megatron_plan rule
    (qkv/ffn/proj/embedding weights) — i.e. the model has transformer-ish
    structure the tensor-parallel plan knows how to split."""
    plan = megatron_plan()
    return any(len(plan.spec_for(p, l)) > 0 for p, l in leaf_paths(params))


def serving_mode_for(params,
                     min_tensor_params: int = SERVING_TP_MIN_PARAMS) -> str:
    """The `sharding=auto` heuristic: "batch" (replicated params, batch split
    over the `data` axis) for small models, "tensor" (megatron_plan) for
    large transformer-ish ones."""
    if _param_count(params) >= min_tensor_params \
            and tensor_parallel_applicable(params):
        return "tensor"
    return "batch"


def serving_mesh(n_devices: Optional[int] = None, mode: str = "batch",
                 devices: Optional[Sequence] = None,
                 shape: Optional[Tuple[int, int]] = None) -> Mesh:
    """Build the 2-D serving mesh (axes `data` x `model`).  `mode="batch"`
    lays all chips on the data axis, `mode="tensor"` on the model axis; an
    explicit `shape=(dd, mm)` overrides both for hybrid layouts."""
    devs = list(devices if devices is not None else jax.devices())
    if shape is not None:
        dd, mm = int(shape[0]), int(shape[1])
    else:
        n = int(n_devices) if n_devices else len(devs)
        dd, mm = (1, n) if mode == "tensor" else (n, 1)
    need = dd * mm
    if need > len(devs):
        raise ValueError(
            f"serving mesh {dd}x{mm} needs {need} devices, have {len(devs)} "
            "(on CPU, simulate with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={need})")
    return Mesh(np.asarray(devs[:need]).reshape(dd, mm),
                (DATA_AXIS, MODEL_AXIS))


def serving_plan(model_or_params, mesh: Mesh,
                 min_tensor_params: int = SERVING_TP_MIN_PARAMS
                 ) -> ShardingPlan:
    """Pick the parameter ShardingPlan for serving over `mesh`: replicate
    small models (the engine batch-shards inputs over `data`), tensor-shard
    large transformer blocks via megatron_plan when the mesh has a `model`
    axis to put them on.  Accepts an InferenceModel/Layer or a raw params
    pytree."""
    params = getattr(model_or_params, "_params", None)
    if params is None:
        params = model_or_params
    if mesh.shape.get(MODEL_AXIS, 1) > 1 \
            and _param_count(params) >= min_tensor_params \
            and tensor_parallel_applicable(params):
        return megatron_plan()
    return replicated_plan()
