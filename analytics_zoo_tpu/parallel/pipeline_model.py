"""Estimator-trainable pipeline-parallel transformer (VERDICT r4 weak #4).

`PipelinedTransformer` is a model-zoo Layer whose transformer blocks run as
GPipe stages over the mesh `pipe` axis (parallel/pipeline.py), end to end
through `Estimator.fit`: embeddings and the tied-embedding LM head are
replicated, the S homogeneous blocks' parameters are STACKED on a leading
axis placed `P('pipe')` (sharding_plan()), and the forward microbatches the
embedded activations through the `shard_map`+`ppermute` schedule.  Gradients
flow through scan+ppermute, so the SAME program trains — verified
loss-identical to the sequential equivalent in tests/test_parallel.py.

`pipelined=False` applies the identical stacked parameters as a plain
sequential loop — the single-device reference used by the loss-matching
tests and by CPU debugging.

Limitations (documented, not silent): stages must be homogeneous (the same
TransformerBlock shape — the GPipe stacked-params design), and in-pipeline
dropout is unsupported (pass dropout rates of 0; the embedding dropout of the
replicated front-end still works).

Green-field: the reference has no pipeline parallelism (SURVEY.md §2.3);
TransformerLayer parity lives in nn/layers/attention.py — this class reuses
its TransformerBlock as the stage body.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from analytics_zoo_tpu.common import dtypes
from analytics_zoo_tpu.common.context import PIPE_AXIS, get_context
from analytics_zoo_tpu.nn.layers.attention import TransformerBlock
from analytics_zoo_tpu.nn.module import Layer, to_shape
from analytics_zoo_tpu.parallel.pipeline import (
    from_microbatches, pipeline_apply, stack_stage_params, to_microbatches)
from analytics_zoo_tpu.parallel.sharding import ShardingPlan


class PipelinedTransformer(Layer):
    """GPT-style LM over token ids, blocks pipelined over `pipe`.

    Input (B, T) int ids; output (B, T, vocab) logits (tied embedding head).
    `n_micro` microbatches per global batch (B must be divisible)."""

    def __init__(self, vocab: int, hidden_size: int = 128, n_stages: int = 2,
                 n_head: int = 4, seq_len: int = 64, n_micro: int = 4,
                 intermediate_size: Optional[int] = None,
                 bidirectional: bool = False, pipelined: bool = True,
                 initializer_range: float = 0.02, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self.vocab = int(vocab)
        self.hidden_size = int(hidden_size)
        self.n_stages = int(n_stages)
        self.seq_len = int(seq_len)
        self.n_micro = int(n_micro)
        self.pipelined = bool(pipelined)
        self.std = initializer_range
        self._mesh = mesh
        # one template block: every stage shares its SHAPE (homogeneous
        # stages); per-stage parameters come from the stacked leading axis
        self.block = TransformerBlock(
            hidden_size, n_head, intermediate_size=intermediate_size,
            causal=not bidirectional, attn_drop=0.0, resid_drop=0.0,
            initializer_range=initializer_range,
            name=self.name + "_stage")

    # -- params ---------------------------------------------------------------
    def build(self, rng, input_shape):
        T = to_shape(input_shape)[0]
        rw, rp, *rb = jax.random.split(rng, 2 + self.n_stages)
        H = self.hidden_size
        stage_params = [self.block.build(r, (T, H)) for r in rb]
        return {"wte": self.std * jax.random.normal(
                    rw, (self.vocab, H), dtypes.param_dtype()),
                "wpe": self.std * jax.random.normal(
                    rp, (self.seq_len, H), dtypes.param_dtype()),
                "stages": stack_stage_params(stage_params)}

    @staticmethod
    def sharding_plan() -> ShardingPlan:
        """Estimator param_plan: stacked stage params over `pipe`, embeddings
        replicated."""
        return ShardingPlan([(r"^stages/", P(PIPE_AXIS))])

    # -- forward --------------------------------------------------------------
    def _stage_fn(self, p, x):
        return self.block.forward(p, x, training=False, rng=None)

    def call(self, params, x, *, training=False, rng=None):
        ids = x.astype(jnp.int32)
        if ids.ndim == 3:
            ids = ids[..., 0]
        T = ids.shape[1]
        h = dtypes.cast_compute(
            jnp.take(params["wte"], ids, axis=0) + params["wpe"][:T])
        if self.pipelined:
            mesh = self._mesh or get_context().mesh
            if mesh.shape.get(PIPE_AXIS, 1) != self.n_stages:
                raise ValueError(
                    f"mesh pipe axis {mesh.shape.get(PIPE_AXIS, 1)} != "
                    f"n_stages {self.n_stages}; build the context with "
                    f"mesh_axes including ('{PIPE_AXIS}', {self.n_stages})")
            hm = to_microbatches(h, self.n_micro)
            y = from_microbatches(
                pipeline_apply(self._stage_fn, params["stages"], hm, mesh))
        else:
            y = h
            for i in range(self.n_stages):
                y = self._stage_fn(
                    jax.tree.map(lambda a, i=i: a[i], params["stages"]), y)
        yw, W = dtypes.cast_compute(y, params["wte"])
        return jnp.einsum("bth,vh->btv", yw, W,
                          preferred_element_type=jnp.float32)
