"""Pipeline parallelism — GPipe-style microbatch pipelining over the `pipe` mesh axis.

Green-field (the reference has no pipeline parallelism, SURVEY.md §2.3).  Design for
homogeneous stages (e.g. transformer blocks): per-stage parameters are STACKED on a
leading axis sharded P('pipe'), so each device holds exactly its stage's weights.
Inside `shard_map`, the schedule runs M + S - 1 ticks: stage 0 injects microbatch t at
tick t, every stage applies its block and hands the activation to the next stage over
ICI via `lax.ppermute`, and the last stage's outputs are all-gathered at the end.
Forward AND backward differentiate through scan+ppermute, so the same program trains.

Bubble fraction is (S-1)/(M+S-1) — pick microbatches >> stages as usual.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.common.context import PIPE_AXIS
from analytics_zoo_tpu.utils import jaxcompat


def stack_stage_params(params_list):
    """Stack per-stage param pytrees along a new leading axis (to shard P('pipe'))."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *params_list)


def _pipeline_local(stage_params, x, *, stage_fn, axis_name: str):
    """Per-device body.  stage_params: leaves (1, ...) — this device's stage slice;
    x: (M, Bm, ...) full microbatched input (replicated)."""
    params = jax.tree.map(lambda a: a[0], stage_params)
    S = jaxcompat.axis_size(axis_name)
    s = jax.lax.axis_index(axis_name)
    M = x.shape[0]
    perm = [(i, (i + 1) % S) for i in range(S)]
    # activation buffer entering this stage each tick; pcast marks it varying over
    # the pipe axis (shard_map manual-axes typing, jax >= 0.9)
    zero_act = jaxcompat.pcast_varying(jnp.zeros_like(x[0]), axis_name)

    def tick(carry, t):
        act = carry
        mb = jnp.clip(t, 0, M - 1)
        inp = jnp.where(s == 0, x[mb], act)
        out = stage_fn(params, inp)
        nxt = jax.lax.ppermute(out, axis_name, perm)
        return nxt, out

    _, outs = jax.lax.scan(tick, zero_act, jnp.arange(M + S - 1))
    # last stage's outputs for microbatch m appear at tick m + S - 1
    results = outs[S - 1:]
    mask = (s == S - 1).astype(results.dtype)
    return jax.lax.psum(results * mask, axis_name)   # broadcast from last stage


def pipeline_apply(stage_fn: Callable, stacked_params, x_microbatches,
                   mesh: Mesh, axis_name: str = PIPE_AXIS):
    """Run x through S pipelined stages.

    stage_fn(params, x) -> y with y.shape == x.shape (homogeneous stages).
    stacked_params: leaves (S, ...); x_microbatches: (M, Bm, ...).
    Returns (M, Bm, ...) outputs (replicated over the pipe axis)."""
    pspec = jax.tree.map(lambda _: P(axis_name), stacked_params)
    fn = jaxcompat.shard_map(
        functools.partial(_pipeline_local, stage_fn=stage_fn,
                          axis_name=axis_name),
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis_name), stacked_params), P()),
        out_specs=P())
    return fn(stacked_params, x_microbatches)


def pipeline_apply_stages(stage_fns, stage_params_list, x_microbatches,
                          mesh: Mesh, axis_name: str = PIPE_AXIS):
    """HETEROGENEOUS GPipe (round 5): stage i applies its OWN function and
    its OWN param pytree — structures may differ freely across stages (the
    stacked-params `pipeline_apply` requires homogeneous stages).

    Mechanics: each stage's pytree ravels to a flat vector, the vectors pad
    to a common length and stack on a leading axis sharded P(pipe) — every
    device holds ONLY its stage's weights (plus the pad), and inside
    `shard_map` each device unflattens its slice and applies its stage via
    `lax.switch`.  Params travel one stacked f32 buffer but unflatten back
    to their ORIGINAL leaf dtypes before the stage runs, and gradients
    return in the caller's dtypes (the astype transpose casts back —
    verified with bf16 params).  Constraint shared with all GPipe schedules
    here: activations crossing stage boundaries (and the injected
    microbatch input) must share one shape/dtype, since they travel one
    `ppermute` buffer.

    stage_fns: [fn_i(params_i, x) -> y] with y.shape == x.shape;
    stage_params_list: their pytrees; x_microbatches: (M, Bm, ...).
    Returns (M, Bm, ...) outputs (replicated over the pipe axis)."""
    from jax.flatten_util import ravel_pytree

    S = len(stage_fns)
    if mesh.shape[axis_name] != S:
        raise ValueError(f"mesh {axis_name} axis is {mesh.shape[axis_name]} "
                         f"but {S} stages were given")
    # Each stage's pytree ravels to a flat f32 vector; vectors pad to a
    # common length and STACK on a leading axis sharded P(pipe) — the same
    # proven sharded-params path as the homogeneous pipeline (each device
    # holds only its stage's weights, and the shard_map transpose psums the
    # per-device grads correctly; explicit replicated params or closures do
    # NOT transpose through the stage switch).
    flats = [ravel_pytree(p) for p in stage_params_list]
    sizes = [int(v.size) for v, _ in flats]
    L = max(sizes)
    stacked = jnp.stack([jnp.pad(v.astype(jnp.float32), (0, L - n))
                         for (v, _), n in zip(flats, sizes)])
    unflattens = [u for _, u in flats]

    def local(pv, x):
        # pv: (1, L) — this device's stage vector
        vec = pv[0]
        s = jax.lax.axis_index(axis_name)
        M = x.shape[0]
        perm = [(i, (i + 1) % S) for i in range(S)]
        zero_act = jaxcompat.pcast_varying(jnp.zeros_like(x[0]),
                                           axis_name)
        branches = [
            functools.partial(
                lambda f, u, n, t: f(u(vec[:n]), t), f, u, n)
            for f, u, n in zip(stage_fns, unflattens, sizes)]

        def tick(carry, t):
            act = carry
            mb = jnp.clip(t, 0, M - 1)
            inp = jnp.where(s == 0, x[mb], act)
            out = jax.lax.switch(s, branches, inp)
            nxt = jax.lax.ppermute(out, axis_name, perm)
            return nxt, out

        _, outs = jax.lax.scan(tick, zero_act, jnp.arange(M + S - 1))
        results = outs[S - 1:]
        mask = (s == S - 1).astype(results.dtype)
        return jax.lax.psum(results * mask, axis_name)

    fn = jaxcompat.shard_map(local, mesh=mesh, in_specs=(P(axis_name), P()),
                       out_specs=P())
    return fn(stacked, x_microbatches)


def to_microbatches(x, n_micro: int):
    B = x.shape[0]
    assert B % n_micro == 0, f"batch {B} not divisible by {n_micro} microbatches"
    return x.reshape((n_micro, B // n_micro) + x.shape[1:])


def from_microbatches(y):
    return y.reshape((-1,) + y.shape[2:])
