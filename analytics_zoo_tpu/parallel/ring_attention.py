"""Ring attention — sequence/context parallelism over the mesh `seq` axis.

Green-field (SURVEY.md §5 long-context: the reference has NO sequence parallelism; its
TransformerLayer materialises the full (T, T) matrix).  Design: shard the sequence axis
of q/k/v across devices; each step every device computes attention of its local query
block against the k/v block it currently holds, accumulates via online softmax
(flash-attention statistics m/l), then rotates k/v one hop around the ring with
`lax.ppermute` — compute overlaps the ICI transfer and full attention is recovered in
`seq` hops with O(T/n) memory per device.

Causal masking uses absolute positions, so fully-masked future blocks contribute zero
(their statistics are washed out by the online-softmax correction term).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.common.context import SEQ_AXIS
from analytics_zoo_tpu.utils import jaxcompat


def _ring_local(q, k, v, *, axis_name: str, causal: bool,
                scale: Optional[float]):
    """Per-shard body.  q/k/v: (B, H, T_local, D)."""
    n = jaxcompat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    s = scale if scale is not None else 1.0 / np.sqrt(D)
    q32 = q.astype(jnp.float32)
    q_pos = idx * Tq + jnp.arange(Tq)

    # derive accumulators from q so they carry the same varying-axis type as the
    # rotating k/v blocks (jax>=0.9 shard_map manual-axes typing)
    o0 = q32 * 0.0
    l0 = q32[..., 0] * 0.0
    m0 = q32[..., 0] * 0.0 - 1e30
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        o, l, m, k_blk, v_blk = carry
        src = (idx - i) % n
        logits = jnp.einsum("bhqd,bhkd->bhqk", q32,
                            k_blk.astype(jnp.float32)) * s
        if causal:
            k_pos = src * Tk + jnp.arange(Tk)
            mask = k_pos[None, :] <= q_pos[:, None]          # (Tq, Tk)
            logits = jnp.where(mask[None, None], logits, -1e9)
        m_new = jnp.maximum(m, logits.max(-1))
        p = jnp.exp(logits - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, l, m_new, k_blk, v_blk

    o, l, _, _, _ = jax.lax.fori_loop(0, n, body, (o0, l0, m0, k, v))
    return (o / jnp.maximum(l[..., None], 1e-30)).astype(q.dtype)


def _ring_local_flash(q, k, v, *, axis_name: str, causal: bool,
                      scale: Optional[float]):
    """Per-shard body with the Pallas flash kernel computing each hop
    (round 5): O(block) VMEM per hop instead of the O(T_local^2) logits the
    einsum body materializes — ring handles the cross-chip axis, flash the
    on-chip blocks, so sequence length is bounded by neither.  Hop partials
    merge exactly through their log-sum-exp statistics
    (flash_attention_with_lse; o = sum_i o_i * exp(lse_i - lse_total)),
    and the merge is differentiable end to end (the lse cotangent enters
    the flash backward as a delta shift)."""
    from analytics_zoo_tpu.ops.flash_attention import flash_attention_with_lse

    n = jaxcompat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    NEG = jnp.float32(-1e30)

    def full_hop(args):
        qq, kb, vb = args
        # f32 hop partials: the accumulator stays full-precision across all
        # hops (like the einsum body), rounding once at the end
        return flash_attention_with_lse(qq, kb, vb, False, s,
                                        out_dtype=jnp.float32)

    def diag_hop(args):
        qq, kb, vb = args
        return flash_attention_with_lse(qq, kb, vb, causal, s,
                                        out_dtype=jnp.float32)

    def masked_hop(args):
        qq, _, _ = args
        return (jnp.zeros(qq.shape, jnp.float32),
                jnp.full(qq.shape[:-1], NEG, jnp.float32))

    o0 = (q.astype(jnp.float32) * 0.0)
    l0 = q.astype(jnp.float32)[..., 0] * 0.0 + NEG
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        o_run, lse_run, k_blk, v_blk = carry
        src = (idx - i) % n
        if causal:
            o_h, lse_h = jax.lax.cond(
                src == idx, diag_hop,
                lambda args: jax.lax.cond(src < idx, full_hop, masked_hop,
                                          args),
                (q, k_blk, v_blk))
        else:
            o_h, lse_h = full_hop((q, k_blk, v_blk))
        lse_new = jnp.logaddexp(lse_run, lse_h)
        w_old = jnp.exp(lse_run - lse_new)[..., None]
        w_new = jnp.exp(lse_h - lse_new)[..., None]
        o_run = o_run * w_old + o_h * w_new
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return o_run, lse_new, k_blk, v_blk

    o, _, _, _ = jax.lax.fori_loop(0, n, body, (o0, l0, k, v))
    return o.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, causal: bool = False,
                   scale: Optional[float] = None,
                   axis_name: str = SEQ_AXIS, impl: str = "auto"):
    """q/k/v: (B, H, T, D) with T sharded over `axis_name`.  Returns attention output
    with the same sharding.  Equivalent to full softmax attention (see tests).

    impl: "xla" (einsum hop body — materializes (T_local, T_local) logits
    per hop), "flash" (Pallas flash kernel per hop, O(block) memory — the
    long-context composition), or "auto" (flash from the measured T>=1024
    crossover on TPU, else xla)."""
    n = mesh.shape[axis_name]
    t_local = q.shape[2] // max(n, 1)
    if impl == "auto":
        from analytics_zoo_tpu.ops.attention import _flash_worthwhile
        # same eligibility gates as the single-chip flash dispatch
        # (_select_flash): measured crossover AND the kernel's head-dim limit
        impl = ("flash" if jax.default_backend() == "tpu"
                and _flash_worthwhile(t_local) and q.shape[-1] <= 256
                else "xla")
    if impl not in ("flash", "xla"):
        raise ValueError(f"unknown ring attention impl {impl!r} "
                         "(expected 'auto', 'flash', or 'xla')")
    body = (_ring_local_flash if impl == "flash" else _ring_local)
    spec = P(None, None, axis_name, None)
    fn = jaxcompat.shard_map(
        functools.partial(body, axis_name=axis_name, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        # pallas_call outputs carry no varying-mesh-axes typing, so the
        # flash body opts out of vma checking (all its inputs/outputs are
        # uniformly seq-sharded; the einsum body keeps full checking)
        check_vma=(impl != "flash"))
    return fn(q, k, v)


def sequence_sharded_spec(mesh: Mesh, axis_name: str = SEQ_AXIS):
    return P(None, None, axis_name, None)
