"""Ring attention — sequence/context parallelism over the mesh `seq` axis.

Green-field (SURVEY.md §5 long-context: the reference has NO sequence parallelism; its
TransformerLayer materialises the full (T, T) matrix).  Design: shard the sequence axis
of q/k/v across devices; each step every device computes attention of its local query
block against the k/v block it currently holds, accumulates via online softmax
(flash-attention statistics m/l), then rotates k/v one hop around the ring with
`lax.ppermute` — compute overlaps the ICI transfer and full attention is recovered in
`seq` hops with O(T/n) memory per device.

Causal masking uses absolute positions, so fully-masked future blocks contribute zero
(their statistics are washed out by the online-softmax correction term).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.common.context import SEQ_AXIS


def _ring_local(q, k, v, *, axis_name: str, causal: bool,
                scale: Optional[float]):
    """Per-shard body.  q/k/v: (B, H, T_local, D)."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    s = scale if scale is not None else 1.0 / np.sqrt(D)
    q32 = q.astype(jnp.float32)
    q_pos = idx * Tq + jnp.arange(Tq)

    # derive accumulators from q so they carry the same varying-axis type as the
    # rotating k/v blocks (jax>=0.9 shard_map manual-axes typing)
    o0 = q32 * 0.0
    l0 = q32[..., 0] * 0.0
    m0 = q32[..., 0] * 0.0 - 1e30
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        o, l, m, k_blk, v_blk = carry
        src = (idx - i) % n
        logits = jnp.einsum("bhqd,bhkd->bhqk", q32,
                            k_blk.astype(jnp.float32)) * s
        if causal:
            k_pos = src * Tk + jnp.arange(Tk)
            mask = k_pos[None, :] <= q_pos[:, None]          # (Tq, Tk)
            logits = jnp.where(mask[None, None], logits, -1e9)
        m_new = jnp.maximum(m, logits.max(-1))
        p = jnp.exp(logits - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, l, m_new, k_blk, v_blk

    o, l, _, _, _ = jax.lax.fori_loop(0, n, body, (o0, l0, m0, k, v))
    return (o / jnp.maximum(l[..., None], 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, causal: bool = False,
                   scale: Optional[float] = None,
                   axis_name: str = SEQ_AXIS):
    """q/k/v: (B, H, T, D) with T sharded over `axis_name`.  Returns attention output
    with the same sharding.  Equivalent to full softmax attention (see tests)."""
    spec = P(None, None, axis_name, None)
    fn = jax.shard_map(
        functools.partial(_ring_local, axis_name=axis_name, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def sequence_sharded_spec(mesh: Mesh, axis_name: str = SEQ_AXIS):
    return P(None, None, axis_name, None)
