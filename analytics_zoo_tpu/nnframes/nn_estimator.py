"""NNFrames — DataFrame Estimator/Transformer integration.

Reference parity: `NNEstimator.fit → NNModel.transform` (nnframes/NNEstimator.scala:
198-923), `NNClassifier/NNClassifierModel` (NNClassifier.scala:42-306), and
`NNImageReader` (NNImageReader.scala:1-182).  The tabular substrate is pandas (Arrow
interchange covers Spark handoff — SURVEY.md §7 step 6): `fit(df)` assembles feature/
label arrays through `sample_preprocessing`, trains on the mesh via the Estimator, and
returns an `NNModel` whose `transform(df)` appends a prediction column partition-wise.

The Spark-ML param-setter surface (setFeaturesCol etc.) is kept as chainable set_*
methods.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

import numpy as np
import pandas as pd

from analytics_zoo_tpu.estimator.estimator import Estimator
from analytics_zoo_tpu.nn.module import Layer


def _column_to_array(df: pd.DataFrame, col: str) -> np.ndarray:
    """A column of scalars or fixed-length lists -> (N, ...) float32 array."""
    first = df[col].iloc[0]
    if np.isscalar(first):
        return df[col].to_numpy(np.float32)[:, None]
    return np.stack([np.asarray(v, np.float32) for v in df[col]])


class NNEstimator:
    """DataFrame estimator with the reference's preprocessing-param surface
    (NNEstimator.scala:382-412): `feature_preprocessing` /
    `label_preprocessing` accept a `feature.common.Preprocessing` chain
    (built with `>>`) or any callable; `sample_preprocessing` operates on the
    whole (features, label) pair and OVERRIDES the two-sided params when set
    (setSamplePreprocessing semantics)."""

    def __init__(self, model: Layer, loss,
                 feature_preprocessing: Optional[Callable] = None,
                 label_preprocessing: Optional[Callable] = None,
                 sample_preprocessing: Optional[Callable] = None):
        self.model = model
        self.loss = loss
        self.feature_preprocessing = feature_preprocessing
        self.label_preprocessing = label_preprocessing
        self.sample_preprocessing = sample_preprocessing
        self.features_col: Union[str, List[str]] = "features"
        self.label_col = "label"
        self.batch_size = 32
        self.max_epoch = 1
        self.optimizer = "adam"
        self.metrics: Sequence = ()
        self.ckpt_dir: Optional[str] = None
        self.validation_df: Optional[pd.DataFrame] = None
        self.tb: Optional[tuple] = None

    # -- Spark-ML-style param setters ----------------------------------------
    def set_features_col(self, col):
        self.features_col = col
        return self

    def set_feature_preprocessing(self, pre: Callable):
        """Preprocessing chain (feature/common.py) or callable applied to each
        feature array (setFeaturePreprocessing parity)."""
        self.feature_preprocessing = pre
        return self

    def set_label_preprocessing(self, pre: Callable):
        self.label_preprocessing = pre
        return self

    def set_sample_preprocessing(self, pre: Callable):
        """Whole-sample (features, label) -> (features, label) transform;
        overrides feature/label preprocessing (setSamplePreprocessing
        parity, NNEstimator.scala:382-412).  The callable must tolerate
        label=None: NNModel.transform invokes it at predict time with
        (features, None) and uses only the returned features."""
        self.sample_preprocessing = pre
        return self

    def set_label_col(self, col):
        self.label_col = col
        return self

    def set_batch_size(self, n):
        self.batch_size = int(n)
        return self

    def set_max_epoch(self, n):
        self.max_epoch = int(n)
        return self

    def set_optim_method(self, optimizer):
        self.optimizer = optimizer
        return self

    def set_metrics(self, metrics):
        self.metrics = metrics
        return self

    def set_checkpoint(self, path):
        self.ckpt_dir = path
        return self

    def set_validation(self, df: pd.DataFrame):
        self.validation_df = df
        return self

    def set_tensorboard(self, log_dir: str, app_name: str):
        self.tb = (log_dir, app_name)
        return self

    # -- feature assembly (getDataSet / samplePreprocessing analog) -----------
    def _assemble(self, df: pd.DataFrame, with_label: bool = True):
        cols = (self.features_col if isinstance(self.features_col, list)
                else [self.features_col])
        xs = [_column_to_array(df, c) for c in cols]
        y = None
        if with_label and self.label_col in df.columns:
            y = _column_to_array(df, self.label_col)
        if self.sample_preprocessing is not None:
            # whole-sample transform wins (setSamplePreprocessing semantics)
            x = xs if len(xs) > 1 else xs[0]
            x, y = self.sample_preprocessing((x, y))
            return x, y
        if self.feature_preprocessing is not None:
            xs = [np.asarray(self.feature_preprocessing(x)) for x in xs]
        x = xs if len(xs) > 1 else xs[0]
        if y is not None and self.label_preprocessing is not None:
            y = np.asarray(self.label_preprocessing(y))
        return x, y

    # -- fit -------------------------------------------------------------------
    def fit(self, df: pd.DataFrame) -> "NNModel":
        x, y = self._assemble(df)
        est = Estimator(self.model, optimizer=self.optimizer, loss=self.loss,
                        metrics=self.metrics)
        if self.ckpt_dir:
            est.set_checkpoint(self.ckpt_dir)
        if self.tb:
            est.set_tensorboard(*self.tb)
        val = None
        if self.validation_df is not None:
            val = self._assemble(self.validation_df)
        est.fit(x, y, batch_size=self.batch_size, epochs=self.max_epoch,
                validation_data=val, verbose=False)
        return self._wrap_model(est)

    def _wrap_model(self, est: Estimator) -> "NNModel":
        m = NNModel(self.model, est)
        m.features_col = self.features_col
        m.feature_preprocessing = self.feature_preprocessing
        m.sample_preprocessing = self.sample_preprocessing
        m.batch_size = self.batch_size
        return m


class NNModel:
    """Spark-ML Transformer analog: transform(df) appends `prediction`."""

    def __init__(self, model: Layer, est: Optional[Estimator] = None):
        self.model = model
        self.est = est or Estimator(model)
        self.features_col: Union[str, List[str]] = "features"
        self.feature_preprocessing: Optional[Callable] = None
        self.sample_preprocessing: Optional[Callable] = None
        self.batch_size = 32
        self.prediction_col = "prediction"

    def set_prediction_col(self, col):
        self.prediction_col = col
        return self

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        cols = (self.features_col if isinstance(self.features_col, list)
                else [self.features_col])
        xs = [_column_to_array(df, c) for c in cols]
        if self.sample_preprocessing is not None:
            x = xs if len(xs) > 1 else xs[0]
            x, _ = self.sample_preprocessing((x, None))
        else:
            if self.feature_preprocessing is not None:
                xs = [np.asarray(self.feature_preprocessing(x)) for x in xs]
            x = xs if len(xs) > 1 else xs[0]
        pred = self.est.predict(x, batch_size=self.batch_size)
        out = df.copy()
        out[self.prediction_col] = [self._format(p) for p in np.asarray(pred)]
        return out

    def _format(self, p: np.ndarray):
        return p.tolist() if p.ndim > 0 and p.size > 1 else float(np.ravel(p)[0])


class NNClassifier(NNEstimator):
    """Classification specialisation: argmax prediction column
    (NNClassifier.scala:42-306; labels zero-based here)."""

    def fit(self, df: pd.DataFrame) -> "NNClassifierModel":
        base = super().fit(df)
        m = NNClassifierModel(self.model, base.est)
        m.features_col = base.features_col
        m.feature_preprocessing = base.feature_preprocessing
        m.sample_preprocessing = base.sample_preprocessing
        m.batch_size = base.batch_size
        return m


class NNClassifierModel(NNModel):
    def _format(self, p: np.ndarray):
        if p.ndim == 0 or p.size == 1:
            return float(np.ravel(p)[0] > 0.5)
        return float(int(np.argmax(p)))


class Pipeline:
    """Spark-ML Pipeline analog for DataFrame stages (the composability the
    reference gets for free from org.apache.spark.ml.Pipeline — NNEstimator
    is designed to slot into one, NNEstimator.scala:198-254).

    A stage is either a *transformer* (has .transform(df)) or an *estimator*
    (has .fit(df) returning a transformer).  fit() walks the stages in order,
    fitting estimators on the progressively-transformed frame; the result is
    a PipelineModel of the fitted transformers."""

    def __init__(self, stages: Sequence):
        self.stages = list(stages)

    def fit(self, df: pd.DataFrame) -> "PipelineModel":
        fitted = []
        cur = df
        for stage in self.stages:
            if hasattr(stage, "fit"):
                model = stage.fit(cur)
                fitted.append(model)
                cur = model.transform(cur)
            elif hasattr(stage, "transform"):
                fitted.append(stage)
                cur = stage.transform(cur)
            else:
                raise TypeError(f"pipeline stage {stage!r} has neither "
                                "fit() nor transform()")
        return PipelineModel(fitted)


class PipelineModel:
    def __init__(self, stages: Sequence):
        self.stages = list(stages)

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        for stage in self.stages:
            df = stage.transform(df)
        return df


class SQLTransformer:
    """Column-expression transformer for pipelines (the pandas stand-in for
    Spark's SQLTransformer): each output column is computed by a callable on
    the frame."""

    def __init__(self, **columns: Callable[[pd.DataFrame], "pd.Series"]):
        self.columns = columns

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        out = df.copy()
        for name, fn in self.columns.items():
            out[name] = fn(out)
        return out


class NNImageReader:
    """Read an image directory into a DataFrame with an image-schema column
    (NNImageReader.scala / NNImageSchema parity)."""

    @staticmethod
    def read_images(path: str, with_label: bool = False) -> pd.DataFrame:
        from analytics_zoo_tpu.feature.image import ImageSet
        iset = ImageSet.read(path, with_label=with_label)
        rows = []
        for f in iset.features:
            img = f.image
            row = {"image": {"origin": f.get("uri"), "height": img.shape[0],
                             "width": img.shape[1], "nChannels": img.shape[2],
                             "data": img}}
            if with_label:
                row["label"] = f.get("label")
            rows.append(row)
        return pd.DataFrame(rows)
