from analytics_zoo_tpu.nnframes.nn_estimator import (
    NNClassifier, NNClassifierModel, NNEstimator, NNImageReader, NNModel,
    Pipeline, PipelineModel, SQLTransformer)

__all__ = ["NNEstimator", "NNModel", "NNClassifier", "NNClassifierModel",
           "NNImageReader", "Pipeline", "PipelineModel", "SQLTransformer"]
