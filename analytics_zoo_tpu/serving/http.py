"""Serving HTTP layer — availability probes (PR 2) + ingestion gateway (PR 7).

The reference platform ships an HTTP front-end for Cluster Serving
(serving/http — POST a record, GET the prediction) so NON-PYTHON clients
can submit work; until PR 7 this server only exposed probes.  `HealthServer`
now carries both surfaces:

Probes (PR 2/4/5):

- ``GET /healthz``  — liveness: 200 while the engine's workers are running
  (or restarting under supervision), 503 once a worker is FAILED past its
  restart cap or the engine stopped.  Body: the full
  ``ClusterServing.health()`` document — the SAME document the manager
  snapshots to ``<pidfile>.health.json``.
- ``GET /readyz``   — readiness: 200 only when the engine can take traffic
  (workers alive, breakers not open, queue depth under the admission
  threshold, backend reachable, not draining, AOT warm-up set compiled).
  503 with ``{"ready": false, "reasons": [...]}`` otherwise; while the
  PR 11 warm-up runs, the reasons carry ``warming (k/n programs)`` and the
  body a ``warmup`` progress block, so the front door routes around a
  still-cold replica instead of eating its compile latency.
- ``GET /metrics``  — JSON counters (PR 2/3 document, unchanged); with
  ``?format=prom`` or a text/plain Accept header, the Prometheus text
  exposition v0.0.4 of the engine's registry (PR 4).

Ingestion gateway (PR 7 tentpole — any client, any language):

- ``POST /v1/enqueue`` — submit one record.  Content-Type negotiated:
  ``application/octet-stream`` is a BINARY FRAME (serving/wire.py layout —
  build it in any language: magic ``AZ`` + version + flags + u32 header
  length + header JSON + raw little-endian payload), validated at the edge
  (malformed -> 400, never enqueued); anything JSON-ish is the legacy
  record dict (``{"uri", "b64", "dtype", "shape"}``) for curl-from-anywhere
  ergonomics.  The shm lane is a SAME-HOST trusted-native-client
  transport: a frame or JSON record carrying a shm slot reference (or a
  raw ``payload``) is rejected 400 here — honoring a remote-supplied ref
  would make the engine attach any named shared-memory segment on the
  host and serve bytes derived from it.  The gateway issues a ``trace_id`` at ingest when the record
  carries none, and ``?timeout_s=S`` stamps the end-to-end ``deadline_ns``
  AT THE EDGE so deadline shedding covers HTTP traffic too.  Admission is
  enforced here: a full queue answers **429** (`Retry-After` hint), a
  draining queue **503** — the flood never reaches the backend unbounded.
  Reply: ``{"uri", "trace_id", "deadline_ns"?}``.
- ``GET /v1/result/<uri>`` — fetch the prediction.  ``?timeout_s=S`` long-
  polls (bounded by ``LONGPOLL_CAP_S``) with backoff until the result
  lands; a miss answers 404 ``{"ready": false}`` so pollers can
  distinguish "not yet" from a transport error.  Each parked long-poll
  pins one handler thread, so concurrent pollers are capped at
  ``LONGPOLL_MAX_INFLIGHT``: overflow degrades to one immediate lookup —
  200 on a hit, else **503** with ``Retry-After`` — instead of letting a
  client exhaust gateway threads/FDs with hanging polls.  Error results (quarantine
  / deadline-shed markers) return 200 with the ``{"error": ...}`` body —
  terminal state, not a gateway failure.  Generation deployments (PR 12)
  stream tokens-so-far: a ``{"partial": true, "tokens": [...]}`` result is
  NOT terminal — the long-poll keeps waiting for the final result and
  returns the freshest partial at the deadline, so pollers see progress
  between polls instead of ``{"ready": false}``.

Per-endpoint telemetry rides the engine's PR 4 registry:
``gateway_request_seconds{endpoint=}`` and
``gateway_request_bytes{endpoint=}`` histograms, scrape-ready next to the
serving stage metrics.

Every response carries an ``X-Replica-Id`` header (PR 5); with N replicas
under the manager supervisor each replica's gateway listens on
``http_port + i``, so the ingest surface scales (and fails over) with the
replicas themselves.  ``ServingParams.gateway=False`` strips the /v1 routes
for deployments that want probe-only ports.

Zero dependencies: `ThreadingHTTPServer` on a daemon thread, started by
``ClusterServing.start()`` when ``ServingParams.http_port`` is set (0 picks
an ephemeral port, exposed as ``HealthServer.port``) and stopped by
``shutdown()`` after the drain completes.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

logger = logging.getLogger(__name__)

# long-poll ceiling for GET /v1/result: bounds worker-thread occupancy per
# hanging client (ThreadingHTTPServer spawns one thread per request)
LONGPOLL_CAP_S = 30.0
# concurrent parked long-polls per gateway: ThreadingHTTPServer is
# unbounded, so without this a client opening many long-polls pins one
# thread each for up to LONGPOLL_CAP_S; overflow answers an immediate
# lookup (200 on hit, else 503 + Retry-After) instead of parking
LONGPOLL_MAX_INFLIGHT = 64
# largest accepted request body; a frame bigger than this answers 413
MAX_BODY_BYTES = 64 * 1024 * 1024


class HealthServer:
    """Probes + ingestion gateway over a serving engine."""

    def __init__(self, serving, host: str = "127.0.0.1", port: int = 0):
        self.serving = serving
        self.host = host
        self.port = port                    # actual port after start()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # admission for parked long-polls (see LONGPOLL_MAX_INFLIGHT)
        self._longpoll_slots = threading.BoundedSemaphore(
            LONGPOLL_MAX_INFLIGHT)
        # gateway telemetry (PR 7) in the engine's PR 4 registry; guarded —
        # exotic servings (tests wrapping a stub) may lack a registry
        self._lat = self._bytes = None
        registry = getattr(serving, "registry", None)
        if registry is not None:
            self._lat = registry.histogram(
                "gateway_request_seconds",
                "Gateway request latency, by endpoint",
                labels=("endpoint",))
            self._bytes = registry.histogram(
                "gateway_request_bytes",
                "Gateway request/response body bytes, by endpoint",
                labels=("endpoint",),
                buckets=(64, 256, 1024, 4096, 16384, 65536, 262144,
                         1048576, 4194304, 16777216))

    def _observe(self, endpoint: str, t0: float, nbytes: int) -> None:
        if self._lat is not None:
            self._lat.labels(endpoint=endpoint).record(
                time.monotonic() - t0)
            self._bytes.labels(endpoint=endpoint).record(nbytes)

    def start(self) -> "HealthServer":
        serving = self.serving
        gateway = self
        gateway_on = bool(getattr(
            getattr(serving, "params", None), "gateway", True))

        class _Handler(BaseHTTPRequestHandler):
            # socket timeout for request-line/header/BODY reads: a client
            # that declares Content-Length and under-sends must not pin a
            # handler thread forever (the long-poll loop sleeps server-side
            # and is bounded separately by LONGPOLL_CAP_S)
            timeout = 30

            def log_message(self, fmt, *args):  # noqa: A003 — silence stderr
                logger.debug("probe: " + fmt, *args)

            def _replica_header(self) -> None:
                # PR 5: every probe answer names the replica that served it,
                # so a load balancer / operator can attribute a flip without
                # parsing the body (readiness carries identity)
                replica = getattr(serving, "replica_id", None)
                if replica:
                    self.send_header("X-Replica-Id", str(replica))

            def _reply(self, status: int, doc, extra_headers=()) -> int:
                body = json.dumps(doc).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra_headers:
                    self.send_header(k, v)
                self._replica_header()
                self.end_headers()
                self.wfile.write(body)
                return len(body)

            def _reply_text(self, status: int, text: str,
                            content_type: str) -> None:
                body = text.encode()
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self._replica_header()
                self.end_headers()
                self.wfile.write(body)

            def _wants_prom(self, query: str) -> bool:
                from urllib.parse import parse_qs
                fmt = (parse_qs(query).get("format") or [None])[0]
                if fmt is not None:
                    return fmt == "prom"
                # content negotiation: a scraper asking for text/plain (and
                # not json) gets the exposition format; default stays JSON
                accept = self.headers.get("Accept", "") or ""
                return ("text/plain" in accept
                        and "application/json" not in accept)

            @staticmethod
            def _uri_ok(uri: str) -> bool:
                """Edge validation for client-controlled uris: FileQueue
                joins the uri into filesystem paths (results/<uri>.json,
                stream spool names), so a traversal-shaped uri must never
                reach the backend.  Native clients are trusted code; the
                gateway is the first surface exposing uri to REMOTE
                callers."""
                return (bool(uri) and len(uri) <= 256
                        and not any(c in uri for c in "/\\\x00")
                        and uri not in (".", ".."))

            @staticmethod
            def _deadline_ok(dl) -> bool:
                """A record's deadline_ns is int()ed by the engine's shed
                gate OUTSIDE the per-record quarantine: a non-numeric
                value from a remote client must stop at the edge."""
                if dl is None:
                    return True
                try:
                    int(dl)
                except (TypeError, ValueError, OverflowError):
                    # OverflowError: json.loads accepts Infinity/1e999
                    return False
                return True

            @staticmethod
            def _query_float(query: str, key: str) -> Optional[float]:
                import math
                from urllib.parse import parse_qs
                raw = (parse_qs(query).get(key) or [None])[0]
                if raw is None:
                    return None
                try:
                    val = float(raw)
                except ValueError:
                    return None
                # nan poisons every comparison downstream — a long-poll
                # deadline of nan never expires AND never parks (an
                # uncapped 10ms spin pinning a handler thread forever).
                # inf stays: the result path clamps it to LONGPOLL_CAP_S
                # ("wait as long as you allow"), and the enqueue path
                # guards the deadline int() itself.
                return val if not math.isnan(val) else None

            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                from urllib.parse import urlsplit
                parts = urlsplit(self.path)
                try:
                    if parts.path == "/healthz":
                        h = serving.health()
                        ok = bool(h.get("running"))
                        # PR 17: every shed/reject/not-ready answer in
                        # the serving surface carries a Retry-After hint
                        self._reply(200 if ok else 503, h,
                                    extra_headers=(
                                        () if ok
                                        else (("Retry-After", "1"),)))
                    elif parts.path == "/readyz":
                        r = serving.ready()
                        ok = bool(r.get("ready"))
                        self._reply(200 if ok else 503, r,
                                    extra_headers=(
                                        () if ok
                                        else (("Retry-After", "1"),)))
                    elif parts.path == "/metrics":
                        if self._wants_prom(parts.query):
                            from analytics_zoo_tpu.common.observability \
                                import MetricsRegistry
                            self._reply_text(200, serving.prom_metrics(),
                                             MetricsRegistry.CONTENT_TYPE)
                        else:
                            self._reply(200, serving.metrics())
                    elif gateway_on and \
                            parts.path.startswith("/v1/result/"):
                        self._get_result(parts)
                    else:
                        self._reply(404, {"error": f"no route {self.path}"})
                except Exception as e:  # noqa: BLE001 — probe must answer
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

            @staticmethod
            def _trace_state():
                """(tracer, sample_rate) — None tracer when the serving
                is a stub without one, or span recording is off."""
                tracer = getattr(serving, "tracer", None)
                params = getattr(serving, "params", None)
                if tracer is None or not getattr(params, "tracing", True):
                    return None, 0.0
                try:
                    rate = float(getattr(params, "trace_sample", 1.0))
                except (TypeError, ValueError):
                    rate = 1.0
                return tracer, rate

            def _result_poll_span(self, t0: float, uri: str, res) -> None:
                """PR 13: a terminal result fetched through the gateway
                records a ``result_poll`` span under the record's trace,
                so the reconstructed timeline covers the client's wait on
                THIS side of the wire too."""
                from analytics_zoo_tpu.common.observability import (
                    SpanContext, trace_sampled)
                tracer, rate = self._trace_state()
                if tracer is None or not isinstance(res, dict):
                    return
                tid = res.get("trace_id")
                if not tid:
                    return
                # verdict priority: the poll's OWN traceparent (clients
                # continuing an explicitly-unsampled context must stay
                # dark even when the poll lands on a replica that never
                # saw the enqueue — the LB re-route shape), then the
                # engine's per-trace memory, then the fleet-pure hash
                inbound = SpanContext.from_traceparent(
                    self.headers.get("traceparent"))
                if inbound is not None and inbound.trace_id == tid:
                    if not inbound.sampled:
                        return
                else:
                    meta = getattr(serving, "_trace_meta", {}).get(tid)
                    if meta is not None:
                        if not meta[1]:
                            return
                    elif not trace_sampled(tid, rate):
                        return
                # tenant attribution (PR 19): the engine stamps the
                # record's tenant into the result doc, so "whose poll"
                # is answerable from the trace alone
                attrs = {}
                if isinstance(res.get("tenant"), str):
                    attrs["tenant"] = res["tenant"]
                if isinstance(res.get("priority"), str):
                    attrs["priority"] = res["priority"]
                tracer.span("result_poll", t0, time.monotonic(),
                            trace_id=tid, uri=uri, attrs=attrs or None)

            def _get_result(self, parts) -> None:
                """GET /v1/result/<uri>[?timeout_s=S] — long-poll the
                result table with backoff; bounded by LONGPOLL_CAP_S, with
                concurrent parked pollers capped at LONGPOLL_MAX_INFLIGHT
                (overflow degrades to one immediate lookup)."""
                from urllib.parse import unquote
                t0 = time.monotonic()
                nbytes = 0
                parked = False
                # every exit — hit, miss, rejection, or failure — lands in
                # the endpoint histograms: rejected/failed traffic is
                # exactly what they exist to attribute
                try:
                    uri = unquote(parts.path[len("/v1/result/"):])
                    if not self._uri_ok(uri):
                        nbytes = self._reply(400, {"error": "invalid uri"})
                        return
                    timeout_s = self._query_float(parts.query,
                                                  "timeout_s") or 0.0
                    deadline = t0 + min(max(timeout_s, 0.0),
                                        LONGPOLL_CAP_S)
                    if deadline > t0:
                        parked = gateway._longpoll_slots.acquire(
                            blocking=False)
                        if not parked:
                            # long-poll slots exhausted: one immediate
                            # lookup, never a parked thread
                            res = serving.queue.get_result(uri)
                            if res is not None:
                                nbytes = self._reply(200, res)
                                if not (isinstance(res, dict)
                                        and res.get("partial")):
                                    # overload is exactly when trace-based
                                    # diagnosis matters: the fast path
                                    # records the leg too
                                    self._result_poll_span(t0, uri, res)
                            else:
                                nbytes = self._reply(
                                    503,
                                    {"error": "long-poll capacity "
                                              "exhausted", "uri": uri},
                                    extra_headers=(("Retry-After", "1"),))
                            return
                    poll = 0.01
                    partial = None
                    while True:
                        res = serving.queue.get_result(uri)
                        if res is not None:
                            if isinstance(res, dict) and res.get("partial"):
                                # streaming partial (PR 12 continuous
                                # batching): tokens-so-far, not terminal —
                                # keep polling for the final result and
                                # fall back to the freshest partial at the
                                # deadline so the long-poll returns
                                # progress instead of "not yet"
                                partial = res
                            else:
                                nbytes = self._reply(200, res)
                                self._result_poll_span(t0, uri, res)
                                return
                        now = time.monotonic()
                        if now >= deadline:
                            break
                        time.sleep(min(poll, deadline - now))
                        poll = min(poll * 1.5, 0.25)
                    if partial is not None:
                        nbytes = self._reply(200, partial)
                    else:
                        nbytes = self._reply(404,
                                             {"ready": False, "uri": uri})
                finally:
                    if parked:
                        gateway._longpoll_slots.release()
                    gateway._observe("result", t0, nbytes)

            def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
                from urllib.parse import urlsplit
                parts = urlsplit(self.path)
                if parts.path == "/debug/profile":
                    # on-demand device profiling (PR 15): PROBE surface
                    # only — the LB proxies /v1/* and nothing else, so
                    # /debug never faces remote gateway traffic; the
                    # params.profiling gate removes the route entirely
                    self._profile(parts)
                    return
                if not (gateway_on and parts.path == "/v1/enqueue"):
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                try:
                    self._enqueue(parts)
                except Exception as e:  # noqa: BLE001 — gateway must answer
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

            def _profile(self, parts) -> None:
                """POST /debug/profile?seconds=N — start one
                ``jax.profiler`` trace into the deployment's profile dir
                (202 + the path), 409 while one is already running, 404
                when ``params.profiling`` is off."""
                if not bool(getattr(getattr(serving, "params", None),
                                    "profiling", False)):
                    self._reply(404, {"error": "profiling disabled "
                                               "(params.profiling)"})
                    return
                start = getattr(serving, "start_profile", None)
                if not callable(start):
                    self._reply(404, {"error": "engine exposes no "
                                               "profiler"})
                    return
                seconds = self._query_float(parts.query, "seconds")
                if seconds is None:
                    seconds = 5.0
                if seconds <= 0:
                    self._reply(400, {"error": "seconds must be > 0"})
                    return
                try:
                    doc = start(seconds)
                except RuntimeError as e:
                    self._reply(409, {"error": str(e)},
                                extra_headers=(("Retry-After", "5"),))
                    return
                except Exception as e:  # noqa: BLE001 — profiler missing
                    self._reply(500,
                                {"error": f"{type(e).__name__}: {e}"})
                    return
                self._reply(202, doc)

            def _enqueue(self, parts) -> None:
                """POST /v1/enqueue[?timeout_s=S] — binary frame or JSON
                record, edge validation + admission + trace/deadline
                stamping.  PR 13: an inbound ``traceparent`` header (the
                LB's root span, or any W3C-compliant upstream) is
                CONTINUED — its trace_id becomes the record's, and the
                gateway's own span parents under it; either way the
                propagated context (traceparent naming the gateway span
                as the engine's parent + the ingest timestamp the
                queue-wait span is computed from) is stamped into the
                record / frame header."""
                from analytics_zoo_tpu.common.observability import (
                    SpanContext, new_span_id, new_trace_id, trace_sampled)
                from analytics_zoo_tpu.serving import wire as _wire
                from analytics_zoo_tpu.serving.queues import (QueueClosed,
                                                              QueueFull)
                t0 = time.monotonic()
                length = 0
                # every exit path — accept, reject, malformed, failure —
                # lands in the endpoint histograms (rejected traffic is
                # exactly what they exist to attribute)
                try:
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                    except ValueError:
                        length = 0
                    if length <= 0:
                        self._reply(411,
                                    {"error": "Content-Length required"})
                        return
                    if length > MAX_BODY_BYTES:
                        self._reply(413,
                                    {"error": f"body {length} bytes > "
                                              f"cap {MAX_BODY_BYTES}"})
                        return
                    body = self.rfile.read(length)
                    # tenant-aware admission (PR 17): identity + priority
                    # come from the HEADERS — this is the trust edge, the
                    # same one that owns trace_ctx — and the decision is
                    # made before any parse/stamp work is spent on a
                    # record that will be rejected.  Rejections answer
                    # 429 with a Retry-After COMPUTED from the tenant
                    # bucket's refill (not a constant), so a compliant
                    # client converges on its admitted rate.
                    tenant = (self.headers.get("X-Api-Key")
                              or self.headers.get("X-Tenant"))
                    prio_hdr = self.headers.get("X-Priority")
                    admit_fn = getattr(serving, "admit_record", None)
                    decision = admit_fn(tenant, prio_hdr) \
                        if callable(admit_fn) else None
                    # identity is stamped on EVERY record (PR 19), not
                    # just when the admission armor is on: with no
                    # controller the gateway normalizes the headers
                    # itself, so downstream attribution (metrics, spans,
                    # usage journal) never depends on admission config
                    if decision is not None:
                        rec_tenant = decision.tenant
                        rec_priority = decision.priority
                    else:
                        from analytics_zoo_tpu.serving.admission import (
                            normalize_priority, normalize_tenant)
                        rec_tenant = normalize_tenant(tenant)
                        rec_priority = normalize_priority(prio_hdr)
                    if decision is not None and not decision.admitted:
                        self._reply(
                            429,
                            {"error": "admission rejected "
                                      f"({decision.reason})",
                             "reason": decision.reason,
                             "tenant": decision.tenant,
                             "priority": decision.priority},
                            extra_headers=(
                                ("Retry-After",
                                 f"{decision.retry_after_s:.3f}"),))
                        return
                    import math
                    timeout_s = self._query_float(parts.query, "timeout_s")
                    # inf = "no budget": no deadline stamped (int(inf)
                    # would overflow; the result path clamps instead)
                    deadline_ns = (time.time_ns() + int(timeout_s * 1e9)
                                   if timeout_s and math.isfinite(timeout_s)
                                   else None)
                    ctype = (self.headers.get("Content-Type")
                             or "").lower()
                    binary = "octet-stream" in ctype \
                        or _wire.is_frame(body)
                    # continue an upstream trace (LB root span) when the
                    # header parses; a malformed traceparent from an
                    # untrusted client degrades to a fresh root
                    inbound = SpanContext.from_traceparent(
                        self.headers.get("traceparent"))
                    trace_id = inbound.trace_id if inbound is not None \
                        else new_trace_id()
                    tracer, sample_rate = self._trace_state()
                    gw_span = new_span_id()

                    def _sampled_for(tid):
                        # the inbound verdict is authoritative only for
                        # the trace it was computed FOR: a client-stamped
                        # frame id that displaced the LB's root id gets
                        # its own pure-hash verdict, keeping the
                        # fleet-consistency invariant at partial rates
                        if inbound is not None \
                                and tid == inbound.trace_id:
                            return inbound.sampled
                        return trace_sampled(tid, sample_rate)

                    def _mk_ctx(hdr):
                        # the context names the frame's FINAL trace_id
                        # (a client-stamped one wins over the gateway's)
                        tid = hdr.get("trace_id") or trace_id
                        return {"tp": SpanContext(
                                    tid, gw_span,
                                    _sampled_for(tid)).to_traceparent(),
                                "ts": time.time_ns()}

                    if binary:
                        try:
                            # edge validation: a malformed frame is
                            # rejected HERE with the reason, never
                            # enqueued to poison the stream; restamp
                            # issues the ingest trace_id / edge deadline
                            # / span context without clobbering
                            # client-set ones
                            # overwrite_trace_ctx: every frame arriving
                            # HERE is remote by definition (native
                            # producers enqueue directly) — a client-
                            # supplied context would forge the queue-wait
                            # ingest timestamp (and through it the SLO
                            # burn the fleet merges as MAX) and
                            # mis-parent every engine span
                            # the trust edge also owns tenant/priority
                            # (PR 17): a client-written tenant field in
                            # the frame would bill another tenant's
                            # bucket and jump the priority lanes
                            frame, header = \
                                _wire.restamp_frame_with_header(
                                    body, trace_id=trace_id,
                                    deadline_ns=deadline_ns,
                                    trace_ctx_fn=_mk_ctx,
                                    overwrite_trace_ctx=True,
                                    set_fields={
                                        "tenant": rec_tenant,
                                        "priority": rec_priority})
                        except _wire.FrameError as e:
                            self._reply(400, {"error": f"malformed "
                                                       f"frame: {e}"})
                            return
                        if "shm" in header:
                            # the shm lane is same-host trusted-client
                            # only: a remote ref would have the engine
                            # attach ANY named /dev/shm segment (and one
                            # spoofed geometry poisons the per-name
                            # attachment cache for legitimate producers)
                            self._reply(400,
                                        {"error": "shm frames are not "
                                                  "accepted over HTTP"})
                            return
                        if not isinstance(header["uri"], str):
                            # the frame carries the uri verbatim to the
                            # engine, which keys results by it: a non-str
                            # uri would serve under a key GET /v1/result
                            # can never look up
                            self._reply(400, {"error": "frame uri must "
                                                       "be a string"})
                            return
                        if not isinstance(header.get("trace_id"), str):
                            # a non-str client trace_id splits the trace
                            # at the LB (its sniffer requires str) and
                            # flows into results/spans as a junk key
                            self._reply(400,
                                        {"error": "frame trace_id must "
                                                  "be a string"})
                            return
                        record, uri = frame, header["uri"]
                        trace_id = header.get("trace_id", trace_id)
                        deadline_ns = header.get("deadline_ns")
                        if not self._deadline_ok(deadline_ns):
                            # the junk value is INSIDE the enqueued frame:
                            # the engine's shed gate int()s it outside the
                            # per-record quarantine, so it must not pass
                            self._reply(400,
                                        {"error": "frame deadline_ns "
                                                  "must be numeric"})
                            return
                    else:
                        try:
                            record = json.loads(body)
                        except ValueError as e:
                            self._reply(400,
                                        {"error": f"body is neither a "
                                                  f"binary frame nor "
                                                  f"JSON: {e}"})
                            return
                        if not isinstance(record, dict) or \
                                not record.get("uri"):
                            self._reply(400,
                                        {"error": "JSON record must be "
                                                  "an object with a "
                                                  "'uri'"})
                            return
                        if "shm" in record or "payload" in record:
                            # same edge stance as the frame path: 'shm'
                            # routes the engine into attaching arbitrary
                            # host segments, 'payload' is the internal
                            # frame-decoded form — neither is a remote-
                            # client surface
                            self._reply(400,
                                        {"error": "'shm'/'payload' "
                                                  "records are not "
                                                  "accepted over HTTP"})
                            return
                        # typed edge validation: the engine's read loop
                        # runs OUTSIDE the per-record quarantine, so a
                        # junk-typed field here would crash-loop the
                        # preprocess worker (restart -> redelivery ->
                        # crash again), not quarantine one record
                        for key in ("b64", "image"):
                            if key in record and \
                                    not isinstance(record[key], str):
                                self._reply(400,
                                            {"error": f"'{key}' must be "
                                                      f"a base64 string"})
                                return
                        if "trace_id" in record and \
                                not isinstance(record["trace_id"], str):
                            # same edge stance as the frame path: a
                            # non-str trace_id splits the trace at the
                            # LB's sniffer and pollutes spans/results
                            self._reply(400,
                                        {"error": "'trace_id' must be "
                                                  "a string"})
                            return
                        if "gen" in record and \
                                not isinstance(record["gen"], dict):
                            # generation options (PR 12): the scheduler
                            # clamps the VALUES, but the container type is
                            # checked here so a junk-typed field cannot
                            # reach the read loop
                            self._reply(400,
                                        {"error": "'gen' must be an "
                                                  "object"})
                            return
                        if not self._deadline_ok(
                                record.get("deadline_ns")):
                            self._reply(400,
                                        {"error": "deadline_ns must be "
                                                  "numeric"})
                            return
                        # engine-derived bookkeeping, never client input
                        record.pop("wire_bytes", None)
                        record.pop("wire_fmt", None)
                        # results are keyed by the queue rid (the uri):
                        # coerce to str so InProc dict lookups from
                        # GET /v1/result/<uri> find what the engine wrote
                        record["uri"] = str(record["uri"])
                        record.setdefault("trace_id", trace_id)
                        trace_id = record["trace_id"]
                        # the gateway is the trust edge for the span
                        # context: overwrite whatever the remote client
                        # sent (a junk ts would skew queue-wait; a forged
                        # parent would mis-thread the timeline)
                        record["trace_ctx"] = _mk_ctx(record)
                        # trust edge for identity (PR 17): the header
                        # verdict overwrites any body-carried fields
                        record["tenant"] = rec_tenant
                        record["priority"] = rec_priority
                        if deadline_ns is not None:
                            record.setdefault("deadline_ns", deadline_ns)
                        uri, deadline_ns = record["uri"], \
                            record.get("deadline_ns")
                    if not self._uri_ok(str(uri)):
                        # FileQueue joins the uri into filesystem paths;
                        # a traversal-shaped uri from an untrusted remote
                        # client must never reach the backend
                        self._reply(400, {"error": "invalid uri"})
                        return
                    try:
                        serving.queue.xadd(record)
                    except QueueClosed as e:
                        # draining: mirror /readyz — stop sending here
                        self._reply(503, {"error": str(e)},
                                    extra_headers=(("Retry-After", "5"),))
                    except QueueFull as e:
                        # admission at the edge: shed the flood with
                        # backoff advice instead of growing the queue
                        # unboundedly
                        self._reply(429, {"error": str(e)},
                                    extra_headers=(("Retry-After", "1"),))
                    else:
                        doc = {"uri": uri, "trace_id": trace_id}
                        if deadline_ns is not None:
                            doc["deadline_ns"] = int(deadline_ns)
                        self._reply(200, doc)
                        # gateway span (PR 13): this replica's ingest hop,
                        # parented under the LB root when one came in —
                        # its span id is the parent every engine stage
                        # span of this record hangs from.  trace_id here
                        # is the FINAL id (client-stamped wins), so the
                        # verdict matches what _mk_ctx propagated
                        if tracer is not None and _sampled_for(trace_id):
                            tracer.span(
                                "gateway", t0, time.monotonic(),
                                trace_id=trace_id, uri=uri,
                                span_id=gw_span,
                                parent_id=(inbound.span_id
                                           if inbound is not None
                                           else None),
                                attrs={"tenant": rec_tenant,
                                       "priority": rec_priority})
                finally:
                    gateway._observe("enqueue", t0, length)

        self._server = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="serving-probes", daemon=True)
        self._thread.start()
        logger.info(
            "serving http on http://%s:%d/{healthz,readyz,metrics%s}",
            self.host, self.port,
            ",v1/enqueue,v1/result" if gateway_on else "")
        return self

    def stop(self, timeout: float = 2.0) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
