"""Serving availability probes — stdlib HTTP endpoint (PR 2 tentpole).

The reference's Cluster Serving relied on the Spark UI + lifecycle scripts
for operational visibility; a TPU-native deployment sits behind a k8s-style
orchestrator that speaks HTTP probes.  `HealthServer` exposes the engine's
existing health surface on three routes:

- ``GET /healthz``  — liveness: 200 while the engine's workers are running
  (or restarting under supervision), 503 once a worker is FAILED past its
  restart cap or the engine stopped.  Body: the full
  ``ClusterServing.health()`` document — the SAME document the manager
  snapshots to ``<pidfile>.health.json``.
- ``GET /readyz``   — readiness: 200 only when the engine can take traffic
  (workers alive, breakers not open, queue depth under the admission
  threshold, backend reachable, not draining).  503 with
  ``{"ready": false, "reasons": [...]}`` otherwise — ``"draining"`` during
  graceful shutdown so load balancers stop routing before the process exits.
- ``GET /metrics``  — JSON counters: ``served``, ``quarantined``, ``shed``
  (deadline-exceeded), ``restarts``, ``queue_depth``, ``dead_letters``,
  ``breaker_trips``, plus (PR 3) ``stages`` — per-stage timing
  (read / preprocess / stage_wait / predict / write / e2e, each with
  count + p50/p99 ms) — and ``latency_ms`` (end-to-end p50/p99).
  With ``?format=prom`` — or an ``Accept`` header asking for
  ``text/plain`` and not JSON — the SAME registry renders as Prometheus
  text exposition format v0.0.4 (PR 4), scrape-ready:
  ``serving_stage_seconds_bucket{stage="predict",le="0.05"} ...``.  The
  default JSON document is unchanged, so PR 2/3 consumers keep working.

Every response carries an ``X-Replica-Id`` header (PR 5): with N serving
replicas behind one load balancer, a probe flip is attributable to the
replica that answered without parsing the body.

Zero dependencies: `ThreadingHTTPServer` on a daemon thread, started by
``ClusterServing.start()`` when ``ServingParams.http_port`` is set (0 picks
an ephemeral port, exposed as ``HealthServer.port``) and stopped by
``shutdown()`` after the drain completes.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

logger = logging.getLogger(__name__)


class HealthServer:
    """Liveness/readiness/metrics probes over a serving engine."""

    def __init__(self, serving, host: str = "127.0.0.1", port: int = 0):
        self.serving = serving
        self.host = host
        self.port = port                    # actual port after start()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HealthServer":
        serving = self.serving

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003 — silence stderr
                logger.debug("probe: " + fmt, *args)

            def _replica_header(self) -> None:
                # PR 5: every probe answer names the replica that served it,
                # so a load balancer / operator can attribute a flip without
                # parsing the body (readiness carries identity)
                replica = getattr(serving, "replica_id", None)
                if replica:
                    self.send_header("X-Replica-Id", str(replica))

            def _reply(self, status: int, doc) -> None:
                body = json.dumps(doc).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self._replica_header()
                self.end_headers()
                self.wfile.write(body)

            def _reply_text(self, status: int, text: str,
                            content_type: str) -> None:
                body = text.encode()
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self._replica_header()
                self.end_headers()
                self.wfile.write(body)

            def _wants_prom(self, query: str) -> bool:
                from urllib.parse import parse_qs
                fmt = (parse_qs(query).get("format") or [None])[0]
                if fmt is not None:
                    return fmt == "prom"
                # content negotiation: a scraper asking for text/plain (and
                # not json) gets the exposition format; default stays JSON
                accept = self.headers.get("Accept", "") or ""
                return ("text/plain" in accept
                        and "application/json" not in accept)

            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                from urllib.parse import urlsplit
                parts = urlsplit(self.path)
                try:
                    if parts.path == "/healthz":
                        h = serving.health()
                        self._reply(200 if h.get("running") else 503, h)
                    elif parts.path == "/readyz":
                        r = serving.ready()
                        self._reply(200 if r.get("ready") else 503, r)
                    elif parts.path == "/metrics":
                        if self._wants_prom(parts.query):
                            from analytics_zoo_tpu.common.observability \
                                import MetricsRegistry
                            self._reply_text(200, serving.prom_metrics(),
                                             MetricsRegistry.CONTENT_TYPE)
                        else:
                            self._reply(200, serving.metrics())
                    else:
                        self._reply(404, {"error": f"no route {self.path}"})
                except Exception as e:  # noqa: BLE001 — probe must answer
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        self._server = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="serving-probes", daemon=True)
        self._thread.start()
        logger.info("serving probes on http://%s:%d/{healthz,readyz,metrics}",
                    self.host, self.port)
        return self

    def stop(self, timeout: float = 2.0) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
