"""Cluster Serving engine — queue → batcher → TPU predict → result store.

Reference parity: `ClusterServing.main` (serving/ClusterServing.scala:34-352): a
streaming micro-batch loop reading the Redis stream, batching to `batch_size`,
pre-processing base64 images, broadcast-model predict, top-N post-processing, writing
the result table with back-pressure, XTRIM memory guard, and throughput scalars
(`Serving Throughput`, `Total Records Number`) to TensorBoard.

TPU-native: the "broadcast model" is just the jitted predict function; batching pads to
power-of-two buckets (InferenceModel) so the compile cache stays tiny; the micro-batch
loop is a plain thread, not a Spark Structured Streaming job.

Resilience (PR 1): the reference delegated failure recovery to Spark
Structured Streaming restarts; here the two worker loops run under
`SupervisedThread` (crash -> log -> backoff -> restart, capped), one
malformed record quarantines ONLY itself to the queue's dead-letter channel
(the client sees an `{"error": ...}` result instead of hanging), a predict
crash bisects the batch to isolate the poison input, and result writes go
through a `RetryPolicy` + `CircuitBreaker` instead of the old ad-hoc loop.
`ClusterServing.health()` reports worker/breaker/dead-letter state.
"""

from __future__ import annotations

import base64
import logging
import threading
import time
from queue import Full as _FULL
from typing import Callable, Dict, List, NamedTuple, Optional

import numpy as np

from analytics_zoo_tpu.common.resilience import (CircuitBreaker,
                                                 CircuitBreakerOpen,
                                                 RetryPolicy,
                                                 SupervisedThread)
from analytics_zoo_tpu.inference.inference_model import InferenceModel
from analytics_zoo_tpu.serving.queues import BaseQueue

logger = logging.getLogger(__name__)


class QuantizedTensor(NamedTuple):
    """A tensor kept in its compact integer dtype until it is ON the
    accelerator (round 5): do_predict transfers the int8/uint8 bytes and
    dequantizes (x * scale) inside the jitted program — 4x less
    host->device traffic than f32, which is the binding constraint when the
    device link (e.g. this environment's axon relay) is the bottleneck."""

    data: np.ndarray      # int8 / uint8
    scale: float


def default_preprocess(record: Dict):
    """base64 bytes -> decoded image float (PreProcessing.scala:1-53), a
    QuantizedTensor for int8-wire / uint8-image records, or raw tensor
    passthrough for `data` records."""
    if "image" in record:
        import cv2
        buf = np.frombuffer(base64.b64decode(record["image"]), np.uint8)
        img = cv2.imdecode(buf, cv2.IMREAD_COLOR)
        if record.get("u8"):
            if "resize" in record:
                h, w = record["resize"]
                img = cv2.resize(img, (w, h))
            return QuantizedTensor(np.asarray(img, np.uint8), 1.0)
        # float path: convert BEFORE resizing (float interpolation), keeping
        # pre-round-5 numerics byte-identical
        img = img.astype(np.float32)
        if "resize" in record:
            h, w = record["resize"]
            img = cv2.resize(img, (w, h))
        return img
    if "b64" in record:
        # raw-bytes tensor (client.enqueue_tensor wire format); explicit
        # little-endian dtype tag so cross-endian pairs stay correct, and a
        # copy so downstream in-place normalization works (frombuffer views
        # are read-only)
        arr = np.frombuffer(base64.b64decode(record["b64"]),
                            np.dtype(record.get("dtype", "<f4")))
        if "shape" in record:
            arr = arr.reshape([int(s) for s in record["shape"]])
        if "scale" in record:
            # int8 wire: stay int8 until on device.  Gated on the declared
            # dtype (ADVICE r5): a float record carrying a stray `scale`
            # must be dequantized on host, not truncated by astype(int8).
            if record.get("dtype") == "<i1":
                return QuantizedTensor(arr.astype(np.int8),
                                       float(record["scale"]))
            return arr.astype(np.float32) * float(record["scale"])
        return arr.astype(np.float32)
    if "data" in record:
        arr = np.asarray(record["data"], np.float32)
        if "shape" in record:
            arr = arr.reshape(record["shape"])
        return arr
    raise ValueError(f"record has neither image nor data: {list(record)}")


def default_postprocess(probs: np.ndarray, top_n: int = 5) -> List:
    """top-N (class, prob) pairs (PostProcessing.scala:1-117)."""
    idx = np.argsort(-probs)[:top_n]
    return [[int(i), float(probs[i])] for i in idx]


class ServingParams:
    """config.yaml surface (scripts/cluster-serving/config.yaml parity)."""

    def __init__(self, batch_size: int = 4, top_n: int = 5,
                 poll_timeout_s: float = 0.05, stream_max_len: int = 100000,
                 filter_threshold: Optional[float] = None,
                 write_retries: int = 5, write_backoff_s: float = 0.05,
                 pipeline_depth: int = 2,
                 max_worker_restarts: int = 5,
                 worker_backoff_s: float = 0.05,
                 breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 0.5):
        self.batch_size = batch_size
        self.top_n = top_n
        self.poll_timeout_s = poll_timeout_s
        self.stream_max_len = stream_max_len
        self.filter_threshold = filter_threshold
        # result-write backpressure (ClusterServing.scala:276-307 analog)
        self.write_retries = write_retries
        self.write_backoff_s = write_backoff_s
        # staged micro-batches between the host preprocess thread and the
        # device predict thread; bounds memory AND provides backpressure
        self.pipeline_depth = pipeline_depth
        # worker supervision + queue-write circuit breaker (PR 1 resilience)
        self.max_worker_restarts = max_worker_restarts
        self.worker_backoff_s = worker_backoff_s
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown_s = breaker_cooldown_s

    @classmethod
    def from_dict(cls, p: Dict) -> "ServingParams":
        """The one params-dict parser (config.yaml `params:` section) —
        manager.serving_params and from_yaml both delegate here so no
        surface silently drops keys."""
        return cls(
            batch_size=int(p.get("batch_size", 4)),
            top_n=int(p.get("top_n", 5)),
            poll_timeout_s=float(p.get("poll_timeout_s", 0.05)),
            stream_max_len=int(p.get("stream_max_len", 100000)),
            filter_threshold=p.get("filter_threshold"),
            write_retries=int(p.get("write_retries", 5)),
            write_backoff_s=float(p.get("write_backoff_s", 0.05)),
            pipeline_depth=int(p.get("pipeline_depth", 2)),
            max_worker_restarts=int(p.get("max_worker_restarts", 5)),
            worker_backoff_s=float(p.get("worker_backoff_s", 0.05)),
            breaker_threshold=int(p.get("breaker_threshold", 5)),
            breaker_cooldown_s=float(p.get("breaker_cooldown_s", 0.5)))

    @staticmethod
    def from_yaml(path: str) -> "ServingParams":
        import yaml
        with open(path) as f:
            cfg = yaml.safe_load(f) or {}
        return ServingParams.from_dict(cfg.get("params", {}))


class ClusterServing:
    def __init__(self, model: InferenceModel, queue: BaseQueue,
                 params: Optional[ServingParams] = None,
                 preprocess: Callable = default_preprocess,
                 postprocess: Optional[Callable] = None,
                 tensorboard_dir: Optional[str] = None):
        self.model = model
        self.queue = queue
        self.params = params or ServingParams()
        self.preprocess = preprocess
        self.postprocess = postprocess or (
            lambda p: default_postprocess(p, self.params.top_n))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.total_records = 0
        self.dead_lettered = 0
        p = self.params
        self._write_retry = RetryPolicy(max_retries=p.write_retries,
                                        base_delay_s=p.write_backoff_s)
        self._breaker = CircuitBreaker(failure_threshold=p.breaker_threshold,
                                       cooldown_s=p.breaker_cooldown_s,
                                       name="result-write")
        # separate breaker for dead-letter writes: sharing the result-write
        # breaker would let a succeeding put_error reset the put_result
        # failure streak (and vice versa) — with the store fully down, this
        # one trips too and bounds the per-record cost of quarantining
        self._dead_breaker = CircuitBreaker(
            failure_threshold=p.breaker_threshold,
            cooldown_s=p.breaker_cooldown_s, name="dead-letter-write")
        self._pre_sup: Optional[SupervisedThread] = None
        self._predict_sup: Optional[SupervisedThread] = None
        self._tb = None
        if tensorboard_dir:
            from analytics_zoo_tpu.utils.tbwriter import FileWriter
            self._tb = FileWriter(tensorboard_dir)

    # -- result write with backpressure (ClusterServing.scala:276-307) -------
    def _put_result(self, rid, value):
        """Retry with backoff (blocking: upstream reads stall), behind a
        circuit breaker — a dead result store fails fast instead of making
        every batch grind through the full retry schedule."""
        self._breaker.call(self._write_retry.call,
                           self.queue.put_result, rid, value)

    def _quarantine(self, rid, stage: str, exc: BaseException,
                    record: Optional[Dict] = None):
        """Per-record fault isolation: the poisoned record gets an error
        RESULT (client unblocks and sees the failure) plus a dead-letter
        entry; the rest of its micro-batch proceeds untouched."""
        self.dead_lettered += 1
        msg = f"{stage}: {type(exc).__name__}: {exc}"
        logger.warning("serving: quarantining record %r (%s)", rid, msg)
        try:
            self._dead_breaker.call(self.queue.put_error, rid, msg,
                                    record=record)
        except CircuitBreakerOpen:
            # store is down: shed quietly instead of blocking per record on
            # the dead backend (the counter above still records the loss)
            logger.warning("serving: dead-letter write for %r skipped "
                           "(breaker open)", rid)
        except Exception:  # noqa: BLE001 — best-effort: queue may be down
            logger.exception("serving: dead-letter write for %r failed", rid)

    def _stack_group(self, ids, items):
        """Stack one same-shape group into a staged (ids, tensors, scales)
        micro-batch."""
        if all(isinstance(it, QuantizedTensor) for it in items):
            # compact-dtype batch: ship the int8/uint8 bytes to the device,
            # dequantize there (per-row scales)
            tensors = np.stack([it.data for it in items])
            scales = np.asarray([it.scale for it in items], np.float32)
            return ids, tensors, scales
        # mixed float/quantized batches dequantize the stragglers on host
        tensors = np.stack([
            it.data.astype(np.float32) * it.scale
            if isinstance(it, QuantizedTensor) else it for it in items])
        return ids, tensors, None

    def _read_and_preprocess(self):
        """Read one micro-batch and preprocess it record-by-record, returning
        a LIST of staged (ids, tensors, scales) groups — one per input shape.
        A malformed record (bad base64, undecodable image, byte/shape
        mismatch) quarantines alone; records with a different-but-valid shape
        form their own group (multi-shape clients are legitimate — the pow-2
        bucketing in InferenceModel compiles per signature anyway) instead of
        poisoning np.stack or being rejected for losing a batch vote."""
        batch = self.queue.read_batch(self.params.batch_size,
                                      self.params.poll_timeout_s)
        if not batch:
            return None
        groups: Dict[tuple, List] = {}
        for rid, rec in batch:
            try:
                item = self.preprocess(rec)
            except Exception as e:  # noqa: BLE001 — malformed record
                self._quarantine(rid, "preprocess", e, record=rec)
                continue
            shape = np.shape(item.data if isinstance(item, QuantizedTensor)
                             else item)
            groups.setdefault(shape, []).append((rid, item))
        if not groups:
            return None
        return [self._stack_group([rid for rid, _ in pairs],
                                  [it for _, it in pairs])
                for pairs in groups.values()]

    def _predict_isolated(self, ids, tensors, scales):
        """Predict with graceful degradation: on failure, bisect the batch to
        isolate the poison input — sane rows still get answers, only the
        culprit is dead-lettered (log2(n) extra predict calls, worst case)."""
        try:
            return [(ids, self.model.do_predict(tensors, scales=scales))]
        except Exception as e:  # noqa: BLE001 — device/input failure
            if len(ids) == 1:
                self._quarantine(ids[0], "predict", e)
                return []
            mid = len(ids) // 2
            lo = self._predict_isolated(
                ids[:mid], tensors[:mid],
                None if scales is None else scales[:mid])
            hi = self._predict_isolated(
                ids[mid:], tensors[mid:],
                None if scales is None else scales[mid:])
            return lo + hi

    def _predict_and_write(self, ids, tensors, scales=None) -> int:
        t0 = time.time()
        n = 0
        for chunk_ids, probs in self._predict_isolated(ids, tensors, scales):
            for rid, row in zip(chunk_ids, probs):
                try:
                    value = {"value": self.postprocess(np.asarray(row))}
                except Exception as e:  # noqa: BLE001 — per-record isolation
                    self._quarantine(rid, "postprocess", e)
                    continue
                try:
                    self._put_result(rid, value)
                except Exception as e:  # noqa: BLE001 — write path down
                    # deliberate shed-don't-block tradeoff: when the result
                    # store is down past the retry budget the computed value
                    # is dead-lettered (client sees the error and can
                    # re-enqueue) instead of stalling the predict worker
                    # behind an unbounded blocking retry
                    self._quarantine(rid, "put_result", e)
                    continue
                n += 1
        self.total_records += n
        dt = max(time.time() - t0, 1e-9)
        if self._tb is not None:
            self._tb.add_scalar("Serving Throughput", n / dt,
                                self.total_records)
            self._tb.add_scalar("Total Records Number", self.total_records,
                                self.total_records)
        self.queue.trim(self.params.stream_max_len)
        return n

    # -- one micro-batch (synchronous path, used by tests/clients) -----------
    def serve_once(self) -> int:
        staged = self._read_and_preprocess()
        if not staged:
            return 0
        return sum(self._predict_and_write(*group) for group in staged)

    # -- lifecycle (cluster-serving-start/stop scripts parity) ----------------
    def start(self):
        """Pipelined loop: a host thread reads+preprocesses micro-batches into
        a bounded buffer while the predict thread runs the device — host
        preprocessing overlaps device compute (round-2 weak #5); the bounded
        buffer gives natural backpressure when predict falls behind.

        Both workers run SUPERVISED (PR 1): an escaping exception no longer
        kills the loop silently — it is logged, the worker restarts with
        backoff up to `params.max_worker_restarts`, and `health()` reports
        state/restarts/last error."""
        import queue as _q
        p = self.params
        self._stop.clear()
        self._staged = _q.Queue(maxsize=p.pipeline_depth)
        self._pre_sup = SupervisedThread(
            self._pre_loop, name="serving-preprocess",
            max_restarts=p.max_worker_restarts,
            backoff_s=p.worker_backoff_s, stop_event=self._stop)
        self._predict_sup = SupervisedThread(
            self._predict_loop, name="serving-predict",
            max_restarts=p.max_worker_restarts,
            backoff_s=p.worker_backoff_s, stop_event=self._stop)
        self._pre_sup.start()
        self._predict_sup.start()
        # compat aliases: the raw threads, for callers that poked at them
        self._pre_thread = self._pre_sup._thread
        self._thread = self._predict_sup._thread
        return self

    def _pre_loop(self):
        sup = self._pre_sup
        while not self._stop.is_set():
            if sup is not None:
                sup.heartbeat()
            staged = self._read_and_preprocess()
            if not staged:
                time.sleep(0.005)
                continue
            for group in staged:
                while not self._stop.is_set():
                    try:
                        self._staged.put(group, timeout=0.1)
                        break
                    except _FULL:
                        continue       # buffer full: backpressure

    def _predict_loop(self):
        import queue as _q
        sup = self._predict_sup
        while not self._stop.is_set():
            if sup is not None:
                sup.heartbeat()
            try:
                ids, tensors, scales = self._staged.get(timeout=0.1)
            except _q.Empty:
                continue
            self._predict_and_write(ids, tensors, scales)

    def health(self) -> Dict:
        """Serving health surface (manager `status` / ops): worker states,
        restart counts, breaker state, record/dead-letter counters."""
        workers = {}
        for sup in (self._pre_sup, self._predict_sup):
            if sup is not None:
                workers[sup.name] = sup.health()
        running = bool(workers) and all(
            w["state"] in (SupervisedThread.STARTING,
                           SupervisedThread.RUNNING,
                           SupervisedThread.RESTARTING)
            for w in workers.values())
        return {"running": running,
                "total_records": self.total_records,
                "dead_lettered": self.dead_lettered,
                "breaker": self._breaker.health(),
                "dead_letter_breaker": self._dead_breaker.health(),
                "workers": workers}

    def shutdown(self):
        # the compat aliases (_pre_thread/_thread) point at the SAME thread
        # objects the supervisors own — joining the supervisors covers them
        self._stop.set()
        for sup in (self._pre_sup, self._predict_sup):
            if sup is not None:
                sup.join(timeout=5)
        if self._tb is not None:
            self._tb.flush()
