"""Cluster Serving engine — queue → batcher → TPU predict → result store.

Reference parity: `ClusterServing.main` (serving/ClusterServing.scala:34-352): a
streaming micro-batch loop reading the Redis stream, batching to `batch_size`,
pre-processing base64 images, broadcast-model predict, top-N post-processing, writing
the result table with back-pressure, XTRIM memory guard, and throughput scalars
(`Serving Throughput`, `Total Records Number`) to TensorBoard.

TPU-native: the "broadcast model" is just the jitted predict function; batching pads to
power-of-two buckets (InferenceModel) so the compile cache stays tiny; the micro-batch
loop is a plain thread, not a Spark Structured Streaming job.

Resilience (PR 1): the reference delegated failure recovery to Spark
Structured Streaming restarts; here the two worker loops run under
`SupervisedThread` (crash -> log -> backoff -> restart, capped), one
malformed record quarantines ONLY itself to the queue's dead-letter channel
(the client sees an `{"error": ...}` result instead of hanging), a predict
crash bisects the batch to isolate the poison input, and result writes go
through a `RetryPolicy` + `CircuitBreaker` instead of the old ad-hoc loop.
`ClusterServing.health()` reports worker/breaker/dead-letter state.
"""

from __future__ import annotations

import base64
import logging
import threading
import time
from queue import Full as _FULL
from typing import Callable, Dict, List, NamedTuple, Optional

import numpy as np

from analytics_zoo_tpu.common.resilience import (CircuitBreaker,
                                                 CircuitBreakerOpen,
                                                 RetryPolicy,
                                                 SupervisedThread,
                                                 wait_until)
from analytics_zoo_tpu.inference.inference_model import InferenceModel
from analytics_zoo_tpu.serving.queues import BaseQueue

logger = logging.getLogger(__name__)


class QuantizedTensor(NamedTuple):
    """A tensor kept in its compact integer dtype until it is ON the
    accelerator (round 5): do_predict transfers the int8/uint8 bytes and
    dequantizes (x * scale) inside the jitted program — 4x less
    host->device traffic than f32, which is the binding constraint when the
    device link (e.g. this environment's axon relay) is the bottleneck."""

    data: np.ndarray      # int8 / uint8
    scale: float


def default_preprocess(record: Dict):
    """base64 bytes -> decoded image float (PreProcessing.scala:1-53), a
    QuantizedTensor for int8-wire / uint8-image records, or raw tensor
    passthrough for `data` records."""
    if "image" in record:
        import cv2
        buf = np.frombuffer(base64.b64decode(record["image"]), np.uint8)
        img = cv2.imdecode(buf, cv2.IMREAD_COLOR)
        if record.get("u8"):
            if "resize" in record:
                h, w = record["resize"]
                img = cv2.resize(img, (w, h))
            return QuantizedTensor(np.asarray(img, np.uint8), 1.0)
        # float path: convert BEFORE resizing (float interpolation), keeping
        # pre-round-5 numerics byte-identical
        img = img.astype(np.float32)
        if "resize" in record:
            h, w = record["resize"]
            img = cv2.resize(img, (w, h))
        return img
    if "b64" in record:
        # raw-bytes tensor (client.enqueue_tensor wire format); explicit
        # little-endian dtype tag so cross-endian pairs stay correct, and a
        # copy so downstream in-place normalization works (frombuffer views
        # are read-only)
        arr = np.frombuffer(base64.b64decode(record["b64"]),
                            np.dtype(record.get("dtype", "<f4")))
        if "shape" in record:
            arr = arr.reshape([int(s) for s in record["shape"]])
        if "scale" in record:
            # int8 wire: stay int8 until on device.  Gated on the declared
            # dtype (ADVICE r5): a float record carrying a stray `scale`
            # must be dequantized on host, not truncated by astype(int8).
            if record.get("dtype") == "<i1":
                return QuantizedTensor(arr.astype(np.int8),
                                       float(record["scale"]))
            return arr.astype(np.float32) * float(record["scale"])
        return arr.astype(np.float32)
    if "data" in record:
        arr = np.asarray(record["data"], np.float32)
        if "shape" in record:
            arr = arr.reshape(record["shape"])
        return arr
    raise ValueError(f"record has neither image nor data: {list(record)}")


def default_postprocess(probs: np.ndarray, top_n: int = 5) -> List:
    """top-N (class, prob) pairs (PostProcessing.scala:1-117)."""
    idx = np.argsort(-probs)[:top_n]
    return [[int(i), float(probs[i])] for i in idx]


class ServingParams:
    """config.yaml surface (scripts/cluster-serving/config.yaml parity)."""

    def __init__(self, batch_size: int = 4, top_n: int = 5,
                 poll_timeout_s: float = 0.05, stream_max_len: int = 100000,
                 filter_threshold: Optional[float] = None,
                 write_retries: int = 5, write_backoff_s: float = 0.05,
                 pipeline_depth: int = 2,
                 max_worker_restarts: int = 5,
                 worker_backoff_s: float = 0.05,
                 breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 0.5,
                 http_port: Optional[int] = None,
                 http_host: str = "127.0.0.1",
                 drain_s: Optional[float] = None,
                 ready_queue_depth: Optional[int] = None):
        self.batch_size = batch_size
        self.top_n = top_n
        self.poll_timeout_s = poll_timeout_s
        self.stream_max_len = stream_max_len
        self.filter_threshold = filter_threshold
        # result-write backpressure (ClusterServing.scala:276-307 analog)
        self.write_retries = write_retries
        self.write_backoff_s = write_backoff_s
        # staged micro-batches between the host preprocess thread and the
        # device predict thread; bounds memory AND provides backpressure
        self.pipeline_depth = pipeline_depth
        # worker supervision + queue-write circuit breaker (PR 1 resilience)
        self.max_worker_restarts = max_worker_restarts
        self.worker_backoff_s = worker_backoff_s
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown_s = breaker_cooldown_s
        # availability layer (PR 2): HTTP probes (/healthz /readyz /metrics;
        # None = off, 0 = ephemeral port), graceful-drain budget used by the
        # manager's SIGTERM handler, and the /readyz queue-depth threshold
        # (None falls back to the queue's own max_depth admission cap)
        self.http_port = http_port
        self.http_host = http_host
        self.drain_s = drain_s
        self.ready_queue_depth = ready_queue_depth

    @classmethod
    def from_dict(cls, p: Dict) -> "ServingParams":
        """The one params-dict parser (config.yaml `params:` section) —
        manager.serving_params and from_yaml both delegate here so no
        surface silently drops keys."""
        return cls(
            batch_size=int(p.get("batch_size", 4)),
            top_n=int(p.get("top_n", 5)),
            poll_timeout_s=float(p.get("poll_timeout_s", 0.05)),
            stream_max_len=int(p.get("stream_max_len", 100000)),
            filter_threshold=p.get("filter_threshold"),
            write_retries=int(p.get("write_retries", 5)),
            write_backoff_s=float(p.get("write_backoff_s", 0.05)),
            pipeline_depth=int(p.get("pipeline_depth", 2)),
            max_worker_restarts=int(p.get("max_worker_restarts", 5)),
            worker_backoff_s=float(p.get("worker_backoff_s", 0.05)),
            breaker_threshold=int(p.get("breaker_threshold", 5)),
            breaker_cooldown_s=float(p.get("breaker_cooldown_s", 0.5)),
            http_port=(None if p.get("http_port") is None
                       else int(p["http_port"])),
            http_host=str(p.get("http_host", "127.0.0.1")),
            drain_s=(None if p.get("drain_s") is None
                     else float(p["drain_s"])),
            ready_queue_depth=(None if p.get("ready_queue_depth") is None
                               else int(p["ready_queue_depth"])))

    @staticmethod
    def from_yaml(path: str) -> "ServingParams":
        import yaml
        with open(path) as f:
            cfg = yaml.safe_load(f) or {}
        return ServingParams.from_dict(cfg.get("params", {}))


class ClusterServing:
    def __init__(self, model: InferenceModel, queue: BaseQueue,
                 params: Optional[ServingParams] = None,
                 preprocess: Callable = default_preprocess,
                 postprocess: Optional[Callable] = None,
                 tensorboard_dir: Optional[str] = None):
        self.model = model
        self.queue = queue
        self.params = params or ServingParams()
        self.preprocess = preprocess
        self.postprocess = postprocess or (
            lambda p: default_postprocess(p, self.params.top_n))
        self._stop = threading.Event()
        self._draining = threading.Event()   # graceful drain in progress
        self._thread: Optional[threading.Thread] = None
        self.total_records = 0
        self.dead_lettered = 0
        self.shed = 0                        # deadline-exceeded rejections
        self._http = None                    # HealthServer when http_port set
        p = self.params
        self._write_retry = RetryPolicy(max_retries=p.write_retries,
                                        base_delay_s=p.write_backoff_s)
        self._breaker = CircuitBreaker(failure_threshold=p.breaker_threshold,
                                       cooldown_s=p.breaker_cooldown_s,
                                       name="result-write")
        # separate breaker for dead-letter writes: sharing the result-write
        # breaker would let a succeeding put_error reset the put_result
        # failure streak (and vice versa) — with the store fully down, this
        # one trips too and bounds the per-record cost of quarantining
        self._dead_breaker = CircuitBreaker(
            failure_threshold=p.breaker_threshold,
            cooldown_s=p.breaker_cooldown_s, name="dead-letter-write")
        self._pre_sup: Optional[SupervisedThread] = None
        self._predict_sup: Optional[SupervisedThread] = None
        self._tb = None
        if tensorboard_dir:
            from analytics_zoo_tpu.utils.tbwriter import FileWriter
            self._tb = FileWriter(tensorboard_dir)

    # -- result write with backpressure (ClusterServing.scala:276-307) -------
    def _put_result(self, rid, value):
        """Retry with backoff (blocking: upstream reads stall), behind a
        circuit breaker — a dead result store fails fast instead of making
        every batch grind through the full retry schedule."""
        self._breaker.call(self._write_retry.call,
                           self.queue.put_result, rid, value)

    def _quarantine(self, rid, stage: str, exc: BaseException,
                    record: Optional[Dict] = None):
        """Per-record fault isolation: the poisoned record gets an error
        RESULT (client unblocks and sees the failure) plus a dead-letter
        entry; the rest of its micro-batch proceeds untouched."""
        self.dead_lettered += 1
        msg = f"{stage}: {type(exc).__name__}: {exc}"
        logger.warning("serving: quarantining record %r (%s)", rid, msg)
        try:
            self._dead_breaker.call(self.queue.put_error, rid, msg,
                                    record=record)
        except CircuitBreakerOpen:
            # store is down: shed quietly instead of blocking per record on
            # the dead backend (the counter above still records the loss)
            logger.warning("serving: dead-letter write for %r skipped "
                           "(breaker open)", rid)
        except Exception:  # noqa: BLE001 — best-effort: queue may be down
            logger.exception("serving: dead-letter write for %r failed", rid)

    # -- end-to-end deadlines (PR 2 availability) ----------------------------
    def _shed_expired(self, rid, rec: Optional[Dict],
                      deadline_ns: Optional[int] = None) -> bool:
        """True when the record's enqueue-stamped `deadline_ns` has passed:
        the client gets a `deadline-exceeded` error result and the record
        never occupies a predict slot."""
        dl = deadline_ns if deadline_ns is not None \
            else (rec or {}).get("deadline_ns")
        if dl is None or time.time_ns() <= int(dl):
            return False
        self.shed += 1
        logger.info("serving: shedding expired record %r", rid)
        try:
            self._put_result(rid, {"error": "deadline-exceeded: budget "
                                            "elapsed before predict"})
        except Exception:  # noqa: BLE001 — store down: client's own
            pass           # deadline still unblocks it
        return True

    def _stack_group(self, ids, items, deadlines):
        """Stack one same-shape group into a staged
        (ids, tensors, scales, deadlines) micro-batch."""
        if all(isinstance(it, QuantizedTensor) for it in items):
            # compact-dtype batch: ship the int8/uint8 bytes to the device,
            # dequantize there (per-row scales)
            tensors = np.stack([it.data for it in items])
            scales = np.asarray([it.scale for it in items], np.float32)
            return ids, tensors, scales, deadlines
        # mixed float/quantized batches dequantize the stragglers on host
        tensors = np.stack([
            it.data.astype(np.float32) * it.scale
            if isinstance(it, QuantizedTensor) else it for it in items])
        return ids, tensors, None, deadlines

    def _read_and_preprocess(self):
        """Read one micro-batch and preprocess it record-by-record, returning
        a LIST of staged (ids, tensors, scales) groups — one per input shape.
        A malformed record (bad base64, undecodable image, byte/shape
        mismatch) quarantines alone; records with a different-but-valid shape
        form their own group (multi-shape clients are legitimate — the pow-2
        bucketing in InferenceModel compiles per signature anyway) instead of
        poisoning np.stack or being rejected for losing a batch vote."""
        batch = self.queue.read_batch(self.params.batch_size,
                                      self.params.poll_timeout_s)
        if not batch:
            return None       # stream empty (drain may exit on this)
        groups: Dict[tuple, List] = {}
        for rid, rec in batch:
            if self._shed_expired(rid, rec):
                continue
            try:
                item = self.preprocess(rec)
            except Exception as e:  # noqa: BLE001 — malformed record
                self._quarantine(rid, "preprocess", e, record=rec)
                continue
            shape = np.shape(item.data if isinstance(item, QuantizedTensor)
                             else item)
            groups.setdefault(shape, []).append(
                (rid, item, rec.get("deadline_ns")))
        if not groups:
            # records WERE read but all shed/quarantined: distinct from an
            # empty stream so a draining _pre_loop keeps reading the backlog
            return []
        return [self._stack_group([rid for rid, _, _ in triples],
                                  [it for _, it, _ in triples],
                                  [dl for _, _, dl in triples])
                for triples in groups.values()]

    def _predict_isolated(self, ids, tensors, scales):
        """Predict with graceful degradation: on failure, bisect the batch to
        isolate the poison input — sane rows still get answers, only the
        culprit is dead-lettered (log2(n) extra predict calls, worst case)."""
        try:
            return [(ids, self.model.do_predict(tensors, scales=scales))]
        except Exception as e:  # noqa: BLE001 — device/input failure
            if len(ids) == 1:
                self._quarantine(ids[0], "predict", e)
                return []
            mid = len(ids) // 2
            lo = self._predict_isolated(
                ids[:mid], tensors[:mid],
                None if scales is None else scales[:mid])
            hi = self._predict_isolated(
                ids[mid:], tensors[mid:],
                None if scales is None else scales[mid:])
            return lo + hi

    def _predict_and_write(self, ids, tensors, scales=None,
                           deadlines=None) -> int:
        # second deadline gate: a record can expire while staged behind a
        # slow predict — shed it here so the batch never wastes device time
        # on rows nobody is waiting for
        if deadlines is not None and any(d is not None for d in deadlines):
            keep = [i for i, (rid, dl) in enumerate(zip(ids, deadlines))
                    if not self._shed_expired(rid, None, deadline_ns=dl)]
            if not keep:
                return 0
            if len(keep) < len(ids):
                ids = [ids[i] for i in keep]
                tensors = tensors[keep]
                if scales is not None:
                    scales = scales[keep]
        t0 = time.time()
        n = 0
        for chunk_ids, probs in self._predict_isolated(ids, tensors, scales):
            for rid, row in zip(chunk_ids, probs):
                try:
                    value = {"value": self.postprocess(np.asarray(row))}
                except Exception as e:  # noqa: BLE001 — per-record isolation
                    self._quarantine(rid, "postprocess", e)
                    continue
                try:
                    self._put_result(rid, value)
                except Exception as e:  # noqa: BLE001 — write path down
                    # deliberate shed-don't-block tradeoff: when the result
                    # store is down past the retry budget the computed value
                    # is dead-lettered (client sees the error and can
                    # re-enqueue) instead of stalling the predict worker
                    # behind an unbounded blocking retry
                    self._quarantine(rid, "put_result", e)
                    continue
                n += 1
        self.total_records += n
        dt = max(time.time() - t0, 1e-9)
        if self._tb is not None:
            self._tb.add_scalar("Serving Throughput", n / dt,
                                self.total_records)
            self._tb.add_scalar("Total Records Number", self.total_records,
                                self.total_records)
        self.queue.trim(self.params.stream_max_len)
        return n

    # -- one micro-batch (synchronous path, used by tests/clients) -----------
    def serve_once(self) -> int:
        staged = self._read_and_preprocess()
        if not staged:
            return 0
        return sum(self._predict_and_write(*group) for group in staged)

    # -- lifecycle (cluster-serving-start/stop scripts parity) ----------------
    def start(self):
        """Pipelined loop: a host thread reads+preprocesses micro-batches into
        a bounded buffer while the predict thread runs the device — host
        preprocessing overlaps device compute (round-2 weak #5); the bounded
        buffer gives natural backpressure when predict falls behind.

        Both workers run SUPERVISED (PR 1): an escaping exception no longer
        kills the loop silently — it is logged, the worker restarts with
        backoff up to `params.max_worker_restarts`, and `health()` reports
        state/restarts/last error."""
        import queue as _q
        p = self.params
        self._stop.clear()
        self._draining.clear()
        try:
            # a prior drained shutdown closed admission; serving again means
            # taking traffic again
            self.queue.open_admission()
        except Exception:  # noqa: BLE001 — backend down: workers will report
            pass
        # bind the probe server FIRST: a port conflict must fail start()
        # before any worker thread begins consuming the queue
        if p.http_port is not None and self._http is None:
            from analytics_zoo_tpu.serving.http import HealthServer
            self._http = HealthServer(self, host=p.http_host,
                                      port=p.http_port).start()
        self._staged = _q.Queue(maxsize=p.pipeline_depth)
        self._pre_sup = SupervisedThread(
            self._pre_loop, name="serving-preprocess",
            max_restarts=p.max_worker_restarts,
            backoff_s=p.worker_backoff_s, stop_event=self._stop)
        self._predict_sup = SupervisedThread(
            self._predict_loop, name="serving-predict",
            max_restarts=p.max_worker_restarts,
            backoff_s=p.worker_backoff_s, stop_event=self._stop)
        self._pre_sup.start()
        self._predict_sup.start()
        # compat aliases: the raw threads, for callers that poked at them
        self._pre_thread = self._pre_sup._thread
        self._thread = self._predict_sup._thread
        return self

    def _pre_loop(self):
        sup = self._pre_sup
        while not self._stop.is_set():
            if sup is not None:
                sup.heartbeat()
            staged = self._read_and_preprocess()
            if not staged:
                # None = stream empty; [] = batch read but fully shed/
                # quarantined — only the former may end a drain, and only
                # when the backend is actually reachable: an outage ALSO
                # reads as an empty batch, but its backlog is still out
                # there, so keep polling until it heals or the drain budget
                # hard-stops us
                if staged is None and self._draining.is_set():
                    try:
                        if self.queue.read_path_healthy():
                            return     # drain: stream empty, clean exit
                    except Exception:  # noqa: BLE001 — state unknown
                        pass
                time.sleep(0.005)
                continue
            for group in staged:
                while not self._stop.is_set():
                    try:
                        self._staged.put(group, timeout=0.1)
                        break
                    except _FULL:
                        continue       # buffer full: backpressure

    def _predict_loop(self):
        import queue as _q
        sup = self._predict_sup
        while not self._stop.is_set():
            if sup is not None:
                sup.heartbeat()
            try:
                group = self._staged.get(timeout=0.1)
            except _q.Empty:
                # drain exit: ONLY once the pre worker is dead AND the buffer
                # is (still) empty — is_alive first, so a group staged just
                # before the pre worker exited is seen by the empty() check
                if self._draining.is_set() and self._pre_sup is not None \
                        and not self._pre_sup.is_alive() \
                        and self._staged.empty():
                    return             # drain: upstream done + buffer empty
                continue
            self._predict_and_write(*group)

    def health(self) -> Dict:
        """Serving health surface (manager `status` / ops, `/healthz`):
        worker states, restart counts, breaker state, record/dead-letter/
        shed counters, queue health, and the readiness verdict — the one
        document every surface (health.json snapshot, health CLI, HTTP
        probes) serves."""
        workers = {}
        for sup in (self._pre_sup, self._predict_sup):
            if sup is not None:
                workers[sup.name] = sup.health()
        running = bool(workers) and all(
            w["state"] in (SupervisedThread.STARTING,
                           SupervisedThread.RUNNING,
                           SupervisedThread.RESTARTING)
            for w in workers.values())
        try:
            queue_health = self.queue.health()
        except Exception as e:  # noqa: BLE001 — backend down ≠ probe down
            queue_health = {"backend": type(self.queue).__name__,
                            "reachable": False,
                            "error": f"{type(e).__name__}: {e}"}
        h = {"running": running,
             "draining": self._draining.is_set(),
             "total_records": self.total_records,
             "dead_lettered": self.dead_lettered,
             "shed": self.shed,
             "breaker": self._breaker.health(),
             "dead_letter_breaker": self._dead_breaker.health(),
             "workers": workers,
             "queue": queue_health}
        h["ready"] = self._readiness(h)
        return h

    def _readiness(self, h: Dict) -> Dict:
        """/readyz verdict derived from an already-computed health doc."""
        reasons = []
        if h["draining"]:
            reasons.append("draining")
        if not h["running"]:
            reasons.append("workers-not-running")
        if h["breaker"]["state"] == CircuitBreaker.OPEN:
            reasons.append("result-write-breaker-open")
        q = h["queue"]
        if not q.get("reachable", True):
            reasons.append("backend-unreachable")
        rb = q.get("read_breaker")
        if rb is not None and rb["state"] == CircuitBreaker.OPEN:
            reasons.append("read-breaker-open")
        cap = self.params.ready_queue_depth
        if cap is None:
            cap = q.get("max_depth")
        depth = q.get("depth", -1)
        if cap is not None and depth >= 0 and depth >= cap:
            reasons.append(f"queue-depth {depth} >= {cap}")
        return {"ready": not reasons, "reasons": reasons}

    def ready(self) -> Dict:
        """Readiness probe document (`/readyz`)."""
        return self.health()["ready"]

    def metrics(self) -> Dict:
        """Flat JSON counters (`/metrics`)."""
        h = self.health()
        return {"served": h["total_records"],
                "quarantined": h["dead_lettered"],
                "shed": h["shed"],
                "restarts": sum(w["restart_count"]
                                for w in h["workers"].values()),
                "queue_depth": h["queue"].get("depth", -1),
                "dead_letters": h["queue"].get("dead_letters", -1),
                "breaker_trips": h["breaker"]["trip_count"]}

    def shutdown(self, drain_s: Optional[float] = None):
        """Stop serving.  With ``drain_s`` (graceful drain, PR 2): close
        admission on the queue, flip `/readyz` to ``draining`` so probes
        stop routing traffic, let the workers finish the stream backlog and
        flush every in-flight result, then join — falling back to a hard
        stop when the budget runs out.  Without it: immediate stop (the
        PR 1 behaviour)."""
        if drain_s is None:
            drain_s = 0.0
        started = self._pre_sup is not None or self._predict_sup is not None
        if drain_s > 0 and started:
            self._draining.set()
            try:
                self.queue.close_admission()
            except Exception:  # noqa: BLE001 — backend down: drain anyway
                pass
            wait_until(lambda: not any(
                s is not None and s.is_alive()
                for s in (self._pre_sup, self._predict_sup)), drain_s)
        # the compat aliases (_pre_thread/_thread) point at the SAME thread
        # objects the supervisors own — joining the supervisors covers them
        self._stop.set()
        for sup in (self._pre_sup, self._predict_sup):
            if sup is not None:
                sup.join(timeout=5)
        if self._http is not None:
            self._http.stop()
            self._http = None
        if self._tb is not None:
            self._tb.flush()
