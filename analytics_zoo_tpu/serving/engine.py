"""Cluster Serving engine — queue → batcher → TPU predict → result store.

Reference parity: `ClusterServing.main` (serving/ClusterServing.scala:34-352): a
streaming micro-batch loop reading the Redis stream, batching to `batch_size`,
pre-processing base64 images, broadcast-model predict, top-N post-processing, writing
the result table with back-pressure, XTRIM memory guard, and throughput scalars
(`Serving Throughput`, `Total Records Number`) to TensorBoard.

TPU-native: the "broadcast model" is just the jitted predict function; batching pads to
power-of-two buckets (InferenceModel) so the compile cache stays tiny; the micro-batch
loop is a plain thread, not a Spark Structured Streaming job.

Resilience (PR 1): the reference delegated failure recovery to Spark
Structured Streaming restarts; here the worker loops run under
`SupervisedThread` (crash -> log -> backoff -> restart, capped), one
malformed record quarantines ONLY itself to the queue's dead-letter channel
(the client sees an `{"error": ...}` result instead of hanging), a predict
crash bisects the batch to isolate the poison input, and result writes go
through a `RetryPolicy` + `CircuitBreaker` instead of the old ad-hoc loop.
`ClusterServing.health()` reports worker/breaker/dead-letter state.

Throughput data plane (PR 3): the reference leaned on Spark Structured
Streaming for micro-batch coalescing and parallel executors; the rebuilt
loop gets the same effects natively:

- **adaptive micro-batching** — `_read_coalesced` fills device-sized
  batches (`max_batch`) under load, waiting at most `max_wait_ms` once the
  first record of a partial batch has arrived; an idle stream still returns
  within `poll_timeout_s`, so latency stays low when traffic is light.
- **parallel preprocess** — `preprocess_workers > 1` fans the per-record
  decode (base64 + cv2, the measured host bottleneck) across a thread pool;
  per-record quarantine and shape re-grouping semantics are unchanged.
- **async device pipeline** — the predict worker DISPATCHES batches
  (`InferenceModel.dispatch`, no host readback) and hands the in-flight
  handle to a downstream write worker; up to `inflight_batches` batches
  overlap device compute with both preprocess and result writing.
- **batched result writes** — one `queue.put_results(pairs)` round-trip per
  micro-batch (Redis pipeline-style `hset` mapping / FileQueue batch spool /
  InProc bulk), falling back to per-record writes under the existing
  RetryPolicy + CircuitBreaker when a batch write fails; `trim()` runs on an
  amortized `trim_interval_s` schedule instead of once per batch.
- **per-stage metrics** — read/preprocess/stage-wait/predict/write timers
  plus end-to-end (read -> result written) p50/p99 latency, exposed through
  `metrics()`/`/metrics` and carried on the `health()` document, so the
  bottleneck is measured rather than inferred.

Unified telemetry (PR 4): the bespoke `StageStats` reservoirs are replaced
by `common/observability.py` registry primitives — every stage timer is a
labeled `Histogram` (`serving_stage_seconds{stage=...}`), quarantine/shed/
record counts are `Counter`s, queue depth / restarts / breaker trips are
callback `Gauge`s — and the whole registry renders as Prometheus text
exposition via `/metrics?format=prom` (the JSON document is unchanged).  A
`Tracer` records one span per pipeline stage per record, keyed by the
`trace_id` the client stamped at enqueue (riding the wire next to
`deadline_ns`); quarantined and shed records get a span carrying the error,
so a single slow or poisoned record is diagnosable by trace_id
(`ClusterServing.export_trace()` dumps Chrome trace-event JSON that
`tools/trace_view.py` summarizes).

Horizontal replicas (PR 5): the engine is now one of N crash-tolerant
replicas over a shared queue.  Reads CLAIM records under a lease instead of
destroying them; the claim is released (`queue.ack`) only after the record's
result — value, quarantine error, or deadline-shed marker — is written, so
a SIGKILLed replica's in-flight records sit orphaned in the queue's pending
store instead of vanishing.  A periodic RECLAIM sweep
(`params.lease_s` / `params.reclaim_interval_s`) re-claims entries idle past
the lease and feeds them back through the normal pipeline: `trace_id` and
`deadline_ns` ride inside the record, so redelivered records shed at the
deadline gates and correlate in traces exactly like first deliveries.
Redelivered records that ALREADY have a result (the dead replica wrote it
but died before acking) are suppressed — acked without a second predict —
keeping the client contract at exactly one result per record on top of
at-least-once delivery.  Each engine carries a `replica_id` (health doc,
`X-Replica-Id` probe header, `serving_heartbeat_age_seconds{replica=}`
gauge); `serving_reclaimed_total{backend=}` and
`serving_duplicate_results_total` land in the same registry.

Sharded multi-chip serving (PR 6): with `params.sharding != "off"` the
engine shards its InferenceModel over a `data` x `model` device mesh at
construction (`InferenceModel.shard`): params are placed once, every padded
batch is committed with a batch-axis NamedSharding, and the SAME pipeline
(dispatch -> writer `.result()`, drain, bisect, int8 wire with per-row
scales) runs over all chips — the predict stage is the only thing that got
wider.  `auto` batch-shards small models and megatron tensor-shards large
transformer stacks; buckets round up to a multiple of the mesh batch axis
so padded batches split evenly.
"""

from __future__ import annotations

import base64
import itertools
import logging
import os
import threading
import time
from queue import Full as _FULL
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.common.observability import (MetricsRegistry,
                                                    SloTracker, SpanContext,
                                                    Tracer, new_trace_id,
                                                    trace_sampled)
from analytics_zoo_tpu.common.resilience import (CircuitBreaker,
                                                 CircuitBreakerOpen,
                                                 RetryPolicy,
                                                 SupervisedThread,
                                                 wait_until)
from analytics_zoo_tpu.inference.inference_model import InferenceModel
from analytics_zoo_tpu.serving import wire as _wire
from analytics_zoo_tpu.serving.queues import BaseQueue

logger = logging.getLogger(__name__)


class QuantizedTensor(NamedTuple):
    """A tensor kept in its compact integer dtype until it is ON the
    accelerator (round 5): do_predict transfers the int8/uint8 bytes and
    dequantizes (x * scale) inside the jitted program — 4x less
    host->device traffic than f32, which is the binding constraint when the
    device link (e.g. this environment's axon relay) is the bottleneck."""

    data: np.ndarray      # int8 / uint8
    scale: float


def _wire_fmt_label(record: Dict) -> str:
    """Metric label for a record's wire format.  The field is producer-
    controlled (raw xadd bypasses the gateway's stripping), so anything
    but the known binary tags folds into the json label: an unhashable
    value would dead-letter a valid record at the labels() call, and
    distinct strings would mint unbounded permanent metric series."""
    fmt = record.get("wire_fmt")
    return fmt if fmt in (_wire.FMT_BIN, _wire.FMT_SHM) else _wire.FMT_JSON


def _decode_tensor_record(record: Dict):
    """Binary-wire decode (PR 7 tentpole): materialize a frame-decoded
    record — inline ``payload`` memoryview or shared-memory slot reference
    — with ``np.frombuffer`` over the existing buffer.  ONE copy total (the
    float32 normalization every path needs, since frombuffer views are
    read-only) instead of the legacy path's base64 decode + reshape copies.
    A shm slot is re-verified AFTER the copy: a producer lapping the ring
    mid-read raises ``FrameError`` -> per-record quarantine, never torn
    bytes served as data."""
    view, shm_ref = _wire.resolve_payload(record)
    dtype = np.dtype(record.get("dtype", "<f4"))
    arr = np.frombuffer(view, dtype)
    if "shape" in record:
        arr = arr.reshape([int(s) for s in record["shape"]])
    if "scale" in record and record.get("dtype") == "<i1":
        out = QuantizedTensor(arr.astype(np.int8),
                              float(record["scale"]))
    elif "scale" in record:
        out = arr.astype(np.float32) * float(record["scale"])
    else:
        out = arr.astype(np.float32)
    _wire.COPY_STATS.record("normalize", arr.nbytes)
    if shm_ref is not None:
        # the copy above is the LAST touch of the slot: verify the
        # generation now so an overwrite during the read is detected
        _wire.attach_ring(shm_ref).verify(shm_ref)
    return out


def default_preprocess(record: Dict):
    """base64 bytes -> decoded image float (PreProcessing.scala:1-53), a
    QuantizedTensor for int8-wire / uint8-image records, raw tensor
    passthrough for `data` records, or — PR 7 — binary-frame records
    (``payload`` buffer / ``shm`` slot reference) via
    ``_decode_tensor_record``."""
    if "payload" in record or "shm" in record:
        return _decode_tensor_record(record)
    if "image" in record:
        import cv2
        buf = np.frombuffer(base64.b64decode(record["image"]), np.uint8)
        img = cv2.imdecode(buf, cv2.IMREAD_COLOR)
        if record.get("u8"):
            if "resize" in record:
                h, w = record["resize"]
                img = cv2.resize(img, (w, h))
            return QuantizedTensor(np.asarray(img, np.uint8), 1.0)
        # float path: convert BEFORE resizing (float interpolation), keeping
        # pre-round-5 numerics byte-identical
        img = img.astype(np.float32)
        if "resize" in record:
            h, w = record["resize"]
            img = cv2.resize(img, (w, h))
        return img
    if "b64" in record:
        # raw-bytes tensor (client.enqueue_tensor wire format); explicit
        # little-endian dtype tag so cross-endian pairs stay correct, and a
        # copy so downstream in-place normalization works (frombuffer views
        # are read-only)
        raw = base64.b64decode(record["b64"])
        _wire.COPY_STATS.record("b64_decode", len(raw))
        arr = np.frombuffer(raw, np.dtype(record.get("dtype", "<f4")))
        if "shape" in record:
            arr = arr.reshape([int(s) for s in record["shape"]])
        if "scale" in record:
            # int8 wire: stay int8 until on device.  Gated on the declared
            # dtype (ADVICE r5): a float record carrying a stray `scale`
            # must be dequantized on host, not truncated by astype(int8).
            if record.get("dtype") == "<i1":
                out = QuantizedTensor(arr.astype(np.int8),
                                      float(record["scale"]))
            else:
                out = arr.astype(np.float32) * float(record["scale"])
        else:
            out = arr.astype(np.float32)
        _wire.COPY_STATS.record("normalize", arr.nbytes)
        return out
    if "data" in record:
        arr = np.asarray(record["data"], np.float32)
        if "shape" in record:
            arr = arr.reshape(record["shape"])
        return arr
    raise ValueError(f"record has neither image nor data: {list(record)}")


def default_postprocess(probs: np.ndarray, top_n: int = 5) -> List:
    """top-N (class, prob) pairs (PostProcessing.scala:1-117).

    O(n) selection: `np.argpartition` pulls the top slice, then only that
    slice is sorted — at classification widths (1k-20k classes) this beats
    the previous full `np.argsort` (O(n log n)) per record on the serving
    write path."""
    n = probs.shape[-1]
    if top_n >= n:
        idx = np.argsort(-probs)
    else:
        part = np.argpartition(-probs, top_n)[:top_n]
        idx = part[np.argsort(-probs[part])]
    return [[int(i), float(probs[i])] for i in idx]


# StageStats (PR 3) is gone: the per-stage reservoirs are now labeled
# observability.Histogram children (`serving_stage_seconds{stage=...}`)
# whose .snapshot() emits the same {count,total_s,mean_ms,p50_ms,p99_ms}
# document, plus Prometheus _bucket/_sum/_count series for free.


class _Staged(NamedTuple):
    """One same-shape micro-batch staged between preprocess and predict.
    Field order is part of the internal API: `_predict_stage(*staged)`."""

    ids: List
    tensors: np.ndarray
    scales: Optional[np.ndarray]
    deadlines: Optional[List]
    traces: Optional[List]        # per-record trace_id (wire-stamped)
    t_read: Optional[float]       # monotonic: read_batch returned
    t_ready: Optional[float]      # monotonic: preprocess/grouping done
    metas: Optional[List] = None  # per-record `gen` options (PR 12), None
    #                               for the predict plane


class _InFlight(NamedTuple):
    """One dispatched batch between the predict and write workers.  Keeps
    the host-side tensors so a device failure surfacing at readback can
    still bisect-quarantine the poison row."""

    ids: List
    tensors: np.ndarray
    scales: Optional[np.ndarray]
    handle: "_ResultHandle"
    traces: Optional[List]
    t_read: Optional[float]
    t_dispatch: float
    tenants: Optional[List] = None  # per-row tenant (PR 19 attribution);
    #                                 None entries = legacy/unattributed


class _ResultHandle:
    """Deferred prediction result: `.result()` blocks on (and returns) the
    host value, re-raising any dispatch/compute failure there so the write
    stage owns the bisect fallback."""

    def result(self):
        raise NotImplementedError


class _LazyResult(_ResultHandle):
    """Synchronous fallback handle: the predict call itself is deferred to
    `.result()` (used when `do_predict` is instance-patched — chaos tests
    and user shims must stay on the hot path — or the model has no async
    `dispatch` entry point)."""

    def __init__(self, fn: Callable):
        self._fn = fn

    def result(self):
        return self._fn()


class _FailedDispatch(_ResultHandle):
    """A dispatch that raised synchronously (e.g. a shape-mismatch trace
    error): surfaces the exception at `.result()` like any other failure."""

    def __init__(self, exc: BaseException):
        self._exc = exc

    def result(self):
        raise self._exc


def resolve_quantize_spec(q) -> Optional[Dict]:
    """Normalize the `ServingParams.quantize` surface to a spec dict
    {"bits", "group_size", "percentile", "calib"} (or None = off).
    Accepts None/False, "int8"/"int4", 8/4, or a dict with those keys."""
    if not q:
        return None
    if isinstance(q, dict):
        spec = dict(q)
    elif q in ("int8", "int4", 8, 4, "8", "4", True):
        spec = {"bits": 8 if q in ("int8", 8, "8", True) else 4}
    else:
        raise ValueError(
            f"quantize={q!r}: expected int8|int4|8|4 or a spec dict")
    bits = int(spec.get("bits", 8))
    if bits not in (8, 4):
        raise ValueError(f"quantize.bits={bits!r}: expected 8 or 4")
    return {"bits": bits,
            "group_size": int(spec.get("group_size", 64)),
            "percentile": (None if spec.get("percentile") is None
                           else float(spec["percentile"])),
            "calib": spec.get("calib")}


def apply_quantize(model, spec) -> bool:
    """Quantize an InferenceModel per a (resolved) `quantize` spec — the
    ONE application path shared by ClusterServing construction and
    `manager warmup`, so the store the manager exports and the graph a
    replica serves are the same program family.  Returns True when the
    model was quantized here, False when it already was (a quantized
    mmap store restored at load — re-quantizing int8 leaves would stack
    errors).  An int8 spec on an unquantized model REQUIRES calibration
    data (`calib`: .npy one batch / .npz batch-per-entry): activation
    scales cannot be conjured, so this fails construction loudly."""
    from analytics_zoo_tpu.inference.quantize import quantized_bits
    spec = resolve_quantize_spec(spec)
    if spec is None:
        return False
    have = quantized_bits(getattr(model, "_params", None) or {})
    if have:
        if have != spec["bits"]:
            logger.warning(
                "serving: model already quantized at %d bits; ignoring "
                "the quantize=%d config (re-load float weights to "
                "re-quantize)", have, spec["bits"])
        return False
    calib = None
    if spec["calib"]:
        import numpy as _np
        loaded = _np.load(spec["calib"], allow_pickle=False)
        calib = [loaded[k] for k in loaded.files] \
            if hasattr(loaded, "files") else loaded
    if spec["bits"] == 8 and calib is None:
        raise ValueError(
            "quantize: int8 needs activation calibration — provide "
            "quantize.calib (.npy/.npz batch file), quantize offline via "
            "do_quantize(FeatureSet, bits=8), or serve a quantized "
            "weight store")
    model.do_quantize(calib, force=True, bits=spec["bits"],
                      group_size=spec["group_size"],
                      percentile=spec["percentile"])
    logger.info("serving: model quantized to int%d at construction "
                "(group_size=%d, percentile=%s, calib=%s)", spec["bits"],
                spec["group_size"], spec["percentile"], spec["calib"])
    return True


class ServingParams:
    """config.yaml surface (scripts/cluster-serving/config.yaml parity)."""

    def __init__(self, batch_size: int = 4, top_n: int = 5,
                 poll_timeout_s: float = 0.05, stream_max_len: int = 100000,
                 filter_threshold: Optional[float] = None,
                 write_retries: int = 5, write_backoff_s: float = 0.05,
                 pipeline_depth: int = 2,
                 max_worker_restarts: int = 5,
                 worker_backoff_s: float = 0.05,
                 breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 0.5,
                 http_port: Optional[int] = None,
                 http_host: str = "127.0.0.1",
                 drain_s: Optional[float] = None,
                 ready_queue_depth: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 max_wait_ms: float = 5.0,
                 preprocess_workers: int = 1,
                 inflight_batches: int = 2,
                 trim_interval_s: float = 5.0,
                 tracing: bool = True,
                 replica_id: Optional[str] = None,
                 lease_s: float = 30.0,
                 reclaim_interval_s: Optional[float] = None,
                 max_deliveries: int = 5,
                 mesh_shape=None,
                 sharding: str = "off",
                 gateway: bool = True,
                 warmup=False,
                 compile_cache_dir: Optional[str] = None,
                 generation=None,
                 trace_sample: float = 1.0,
                 serving_slo=None,
                 quantize=None,
                 flight_recorder: bool = True,
                 recorder_ring: Optional[int] = None,
                 profiling: bool = True,
                 model_version: Optional[str] = None,
                 faults=None,
                 admission=None,
                 brownout=None,
                 metering=None):
        self.batch_size = batch_size
        self.top_n = top_n
        self.poll_timeout_s = poll_timeout_s
        self.stream_max_len = stream_max_len
        self.filter_threshold = filter_threshold
        # result-write backpressure (ClusterServing.scala:276-307 analog)
        self.write_retries = write_retries
        self.write_backoff_s = write_backoff_s
        # staged micro-batches between the host preprocess thread and the
        # device predict thread; bounds memory AND provides backpressure
        self.pipeline_depth = pipeline_depth
        # worker supervision + queue-write circuit breaker (PR 1 resilience)
        self.max_worker_restarts = max_worker_restarts
        self.worker_backoff_s = worker_backoff_s
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown_s = breaker_cooldown_s
        # availability layer (PR 2): HTTP probes (/healthz /readyz /metrics;
        # None = off, 0 = ephemeral port), graceful-drain budget used by the
        # manager's SIGTERM handler, and the /readyz queue-depth threshold
        # (None falls back to the queue's own max_depth admission cap)
        self.http_port = http_port
        self.http_host = http_host
        self.drain_s = drain_s
        self.ready_queue_depth = ready_queue_depth
        # throughput data plane (PR 3): adaptive batcher ceiling (None =
        # batch_size, i.e. the pre-PR-3 fixed read) + coalescing budget,
        # preprocess fan-out, device pipeline depth, amortized trim period.
        # inflight_batches bounds the dispatched-handle QUEUE between the
        # predict and write workers; up to two more batches are transiently
        # resident (one mid-readback in the writer, one held by the predict
        # worker awaiting a slot) — size device memory for inflight + 2
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.preprocess_workers = preprocess_workers
        self.inflight_batches = inflight_batches
        self.trim_interval_s = trim_interval_s
        # per-record span recording (PR 4).  On by default — the ring buffer
        # is bounded — but the span dicts + tracer lock are per-record hot-
        # path cost, so latency-critical deployments can switch it off
        # (metrics histograms stay on; only traces go dark)
        self.tracing = bool(tracing)
        # horizontal replicas (PR 5): stable identity for this engine (None
        # = derived from pid), how long a claimed record may sit idle before
        # another replica may reclaim it (must exceed the worst-case single-
        # record service time; <= 0 disables reclaiming), and how often the
        # reclaim sweep runs (None = lease_s / 2)
        self.replica_id = replica_id
        self.lease_s = lease_s
        self.reclaim_interval_s = reclaim_interval_s
        # poison-pill parking (PR 10): a record delivered more than this
        # many times (first delivery counts) is parked to the dead-letter
        # queue with a `max-deliveries-exceeded` error instead of looping
        # through reclaim -> crash -> reclaim forever.  <= 0 disables.
        self.max_deliveries = int(max_deliveries)
        # sharded multi-chip serving (PR 6): route predict through a pjit'd
        # program over the ICI mesh.  `sharding`: off (single-chip, the
        # default) | auto (batch-shard small models, tensor-shard large) |
        # batch | tensor.  `mesh_shape`: None = all devices, int N = first
        # N, or a (data, model) tuple for hybrid layouts.
        self.mesh_shape = mesh_shape
        self.sharding = str(sharding or "off")
        # ingestion gateway (PR 7): serve POST /v1/enqueue + GET /v1/result
        # on the probe port.  Off = probe-only port (deployments that front
        # ingest elsewhere)
        self.gateway = bool(gateway)
        # zero cold start (PR 11).  `warmup`: AOT-compile the full
        # (bucket, scales-variant) program set at start() — False (off,
        # the pre-PR-11 behaviour), True (input spec inferred from the
        # topology's declared input shape), or a spec dict
        # {"shape": [d0, ...], "dtype": "<f4", "scales": "auto|both|off",
        #  "max_batch": N} for models that declare nothing.  /readyz
        # reports `warming (k/n programs)` until the set is compiled.
        # `compile_cache_dir`: persistent XLA compilation cache directory
        # shared by every replica of the deployment (the manager derives
        # `<pidfile>.xla_cache` when unset) — the second replica of a
        # topology loads executables from disk instead of compiling.
        self.warmup = warmup if isinstance(warmup, dict) else bool(warmup)
        self.compile_cache_dir = compile_cache_dir
        # continuous batching (PR 12).  `generation`: None (off, the
        # batch-in/batch-out predict plane) | True (defaults) | a config
        # dict — see serving/generate.GenerationParams for the keys
        # (max_active_slots, max_tokens, eos_id, start_id, max_prompt_len,
        # bucket_lens, prefill_buckets, stream_interval).  When set, the
        # predict+write stages are replaced by the token-level scheduler:
        # requests join/leave the in-flight decode batch at step
        # boundaries, results stream through OutputQueue partials, and the
        # model must expose init_decode/decode_step.
        self.generation = generation if isinstance(generation, dict) \
            else ({} if generation else None)
        # fleet-wide distributed tracing (PR 13).  `trace_sample`: HEAD
        # sampling rate in [0, 1] — the keep/drop verdict is a pure
        # function of the trace_id (common/observability.trace_sampled),
        # so the LB, gateway and every replica agree without coordination.
        # Generation workloads emit per-boundary decode spans, so the
        # sampling knob exists BEFORE per-token span volume does.  Error
        # spans (quarantine/shed) are always recorded regardless of rate.
        try:
            self.trace_sample = min(max(float(trace_sample), 0.0), 1.0)
        except (TypeError, ValueError):
            self.trace_sample = 1.0
        # SLO attribution (PR 13): {"latency_ms": 500, "window_s": 60,
        # "target": 0.99} drives serving_slo_violations_total{stage=} and
        # the serving_slo_burn_rate gauge.  None = off.
        self.serving_slo = serving_slo if isinstance(serving_slo, dict) \
            else None
        # fused-dequant quantized predict (PR 14).  `quantize`: None/off
        # (float serve, the default) | "int8"/8 | "int4"/4 | a config dict
        # {"bits": 8|4, "group_size": 64, "percentile": 99.9,
        #  "calib": "/path/to/batch.npy|.npz"} — applied at ClusterServing
        # construction (before sharding) when the model is not already
        # quantized.  int4 is weight-only (no calibration needed); int8
        # needs activation scales, so an unquantized model REQUIRES the
        # `calib` file (fail-fast at construction, like a bad mesh) —
        # calibrate offline with do_quantize(FeatureSet) for real data, or
        # let `manager warmup` quantize + export the mmap store so replica
        # forks serve quantized without re-quantizing.
        self.quantize = resolve_quantize_spec(quantize)
        # incident flight recorder (PR 15).  `flight_recorder`: record
        # typed events (state transitions, retunes, reclaims, quarantines,
        # sheds, warm-up phases, scheduler boundaries) into the bounded
        # process ring that `manager incident` bundles — per-EVENT cost is
        # one dict + deque append, so it stays on by default; off compiles
        # the hop down to a no-op like tracing=False.  `recorder_ring`
        # re-bounds the ring (default 4096 events); size it to cover the
        # diagnosis window between manager drains (1 s) at your event
        # rate.  `profiling`: serve POST /debug/profile?seconds=N on the
        # probe port (jax.profiler trace into the deployment dir) — probe
        # surface only, the LB never proxies /debug; false removes the
        # route entirely.
        self.flight_recorder = bool(flight_recorder)
        self.recorder_ring = (None if recorder_ring is None
                              else max(16, int(recorder_ring)))
        self.profiling = bool(profiling)
        # zero-drop rollout (PR 16).  `model_version`: the registry
        # version this replica serves — normally injected by the
        # supervisor's spawn spec, not set in config.yaml.  Rides the
        # health doc, /healthz and every result payload so a mixed-version
        # fleet mid-rollout is observable end to end.  `faults`:
        # deterministic fault-injection points gated on model_version
        # (serving/faults.py) — strictly opt-in chaos for rollout tests
        # and the `serving_bench --rollout` A/B; None (the default) wires
        # nothing into the hot path.
        self.model_version = (None if model_version is None
                              else str(model_version))
        self.faults = faults if isinstance(faults, dict) else None
        # overload armor (PR 17).  `admission`: tenant-aware token-bucket
        # admission at the gateway trust edge (serving/admission.py —
        # enabled, rate, burst, tenants, depth_fractions); None = the
        # pre-PR-17 fleet-wide max_depth 429 only.  `brownout`: the
        # hysteresis degradation ladder driven by the SLO burn rate
        # (serving/brownout.py — enter, exit_ratio, dwell_s, hold_s,
        # batch_max_tokens); needs `serving_slo` for its input signal.
        self.admission = admission if isinstance(admission, dict) else None
        self.brownout = brownout if isinstance(brownout, dict) else None
        # usage metering & attribution (PR 19).  `metering`: None/True =
        # on with defaults ({tenant=,model=} labelled series, per-interval
        # usage journal deltas drained by the manager, per-tenant SLO
        # views); a dict configures it ({"enabled": bool, "max_tenants":
        # N, "slo_objectives": {tenant: {latency_ms, ...}}}); False turns
        # the labelled surface off (the pre-PR-19 unlabelled series — the
        # metering-off arm of `serving_bench --metering-overhead`).
        if isinstance(metering, dict):
            self.metering = metering
        elif metering is None:
            self.metering = {}
        else:
            self.metering = {} if metering else {"enabled": False}

    @classmethod
    def from_dict(cls, p: Dict) -> "ServingParams":
        """The one params-dict parser (config.yaml `params:` section) —
        manager.serving_params and from_yaml both delegate here so no
        surface silently drops keys."""
        return cls(
            batch_size=int(p.get("batch_size", 4)),
            top_n=int(p.get("top_n", 5)),
            poll_timeout_s=float(p.get("poll_timeout_s", 0.05)),
            stream_max_len=int(p.get("stream_max_len", 100000)),
            filter_threshold=p.get("filter_threshold"),
            write_retries=int(p.get("write_retries", 5)),
            write_backoff_s=float(p.get("write_backoff_s", 0.05)),
            pipeline_depth=int(p.get("pipeline_depth", 2)),
            max_worker_restarts=int(p.get("max_worker_restarts", 5)),
            worker_backoff_s=float(p.get("worker_backoff_s", 0.05)),
            breaker_threshold=int(p.get("breaker_threshold", 5)),
            breaker_cooldown_s=float(p.get("breaker_cooldown_s", 0.5)),
            http_port=(None if p.get("http_port") is None
                       else int(p["http_port"])),
            http_host=str(p.get("http_host", "127.0.0.1")),
            drain_s=(None if p.get("drain_s") is None
                     else float(p["drain_s"])),
            ready_queue_depth=(None if p.get("ready_queue_depth") is None
                               else int(p["ready_queue_depth"])),
            max_batch=(None if p.get("max_batch") is None
                       else int(p["max_batch"])),
            max_wait_ms=float(p.get("max_wait_ms", 5.0)),
            preprocess_workers=int(p.get("preprocess_workers", 1)),
            inflight_batches=int(p.get("inflight_batches", 2)),
            trim_interval_s=float(p.get("trim_interval_s", 5.0)),
            tracing=bool(p.get("tracing", True)),
            replica_id=(None if p.get("replica_id") is None
                        else str(p["replica_id"])),
            lease_s=float(p.get("lease_s", 30.0)),
            reclaim_interval_s=(None if p.get("reclaim_interval_s") is None
                                else float(p["reclaim_interval_s"])),
            max_deliveries=int(p.get("max_deliveries", 5)),
            mesh_shape=(None if p.get("mesh_shape") is None
                        else tuple(int(v) for v in p["mesh_shape"])
                        if isinstance(p["mesh_shape"], (list, tuple))
                        else int(p["mesh_shape"])),
            sharding=str(p.get("sharding", "off")),
            gateway=bool(p.get("gateway", True)),
            warmup=p.get("warmup", False),
            compile_cache_dir=p.get("compile_cache_dir"),
            generation=p.get("generation"),
            trace_sample=p.get("trace_sample", 1.0),
            serving_slo=p.get("serving_slo"),
            quantize=p.get("quantize"),
            flight_recorder=bool(p.get("flight_recorder", True)),
            recorder_ring=(None if p.get("recorder_ring") is None
                           else int(p["recorder_ring"])),
            profiling=bool(p.get("profiling", True)),
            model_version=p.get("model_version"),
            faults=p.get("faults"),
            admission=p.get("admission"),
            brownout=p.get("brownout"),
            metering=p.get("metering"))

    @staticmethod
    def from_yaml(path: str) -> "ServingParams":
        import yaml
        with open(path) as f:
            cfg = yaml.safe_load(f) or {}
        return ServingParams.from_dict(cfg.get("params", {}))


class ClusterServing:
    def __init__(self, model: InferenceModel, queue: BaseQueue,
                 params: Optional[ServingParams] = None,
                 preprocess: Callable = default_preprocess,
                 postprocess: Optional[Callable] = None,
                 tensorboard_dir: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None):
        self.model = model
        self.queue = queue
        self.params = params or ServingParams()
        # fused-dequant quantized predict (PR 14): quantize BEFORE the
        # mesh placement so the quantized leaves are what the plan shards
        # — a bad spec (int8 with no calibration) fails construction, not
        # a mid-stream request.  A model restored from a quantized weight
        # store skips this (already quantized).
        if self.params.quantize and isinstance(model, InferenceModel):
            apply_quantize(model, self.params.quantize)
        self._qbits: Optional[int] = None    # lazily cached health() value
        # sharded multi-chip serving (PR 6): place the model over the mesh
        # BEFORE any worker can dispatch — a bad mesh config fails
        # construction, not a mid-stream request.  Idempotent for a model
        # shared across engines (bench --replicas).
        if self.params.sharding != "off" and isinstance(model, InferenceModel):
            model.shard(mesh=self.params.mesh_shape,
                        sharding=self.params.sharding)
        self.preprocess = preprocess
        self.postprocess = postprocess or (
            lambda p: default_postprocess(p, self.params.top_n))
        self._stop = threading.Event()
        self._draining = threading.Event()   # graceful drain in progress
        # decommission drain (PR 10): this replica stops CLAIMING new work
        # and flushes what it holds, while the shared queue stays open for
        # the surviving replicas — the scale-down shape.  The PR 2 whole-
        # deployment drain (admission closed) is the close_admission=True
        # path of shutdown().
        self._retiring = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.total_records = 0
        self.dead_lettered = 0
        self.shed = 0                        # deadline-exceeded rejections
        # horizontal replicas (PR 5): identity + reclaim/redelivery state
        self.replica_id = self.params.replica_id or \
            f"replica-{os.getpid()}-{new_trace_id()[:6]}"
        self.reclaimed = 0                   # orphans re-claimed by us
        self.duplicates = 0                  # redeliveries suppressed
        self._last_reclaim = 0.0             # monotonic; 0 = sweep at start
        self._redelivered: Dict[str, int] = {}   # rid -> delivery count
        # rid -> monotonic claim ts for records currently in OUR pipeline:
        # the reclaim sweep must not treat its own slow in-flight work (a
        # cold jit compile, a long batch) as another replica's orphans —
        # self-reclaim would double-serve them.  Entries clear on ack.
        self._inflight: Dict[str, float] = {}
        self._hb_ts = time.monotonic()       # read-loop heartbeat stamp
        # zero cold start (PR 11): AOT warm-up progress (published on
        # /readyz + the health doc) and the construction-to-first-result
        # clock the cold-start metric reports
        self._t_construct = time.monotonic()
        self._cold_start_s: Optional[float] = None
        self._warm_state: Dict = {"state": "off", "total": 0,
                                  "compiled": 0, "failed": 0,
                                  "seconds": None}
        self._warm_thread: Optional[threading.Thread] = None
        # the queue handle's claims are made under our replica identity
        try:
            self.queue.consumer = self.replica_id
        except Exception:  # noqa: BLE001 — exotic custom backend
            pass
        self._http = None                    # HealthServer when http_port set
        # unified telemetry (PR 4): per-ENGINE registry by default so
        # counters and stage percentiles stay attributable when several
        # engines share a process (tests, embedded serving); pass
        # observability.get_registry() to pool process-wide
        self.registry = registry or MetricsRegistry()
        self.tracer = tracer or Tracer()
        # fleet tracing (PR 13): every span this replica records names it,
        # so the fleet-merged timeline attributes work per process
        if self.tracer.replica_id is None:
            self.tracer.replica_id = self.replica_id
        # per-trace propagated context: trace_id -> (parent span id,
        # sampled flag) parsed from the record's trace_ctx at read.  The
        # span wrapper consults it so EVERY stage span parents under the
        # gateway/LB span without threading context through the pipeline
        # tuples.  Bounded (trimmed oldest-half past the cap).
        self._trace_meta: Dict[str, Tuple[Optional[str], bool]] = {}
        # rid -> queue-wait seconds measured at claim (SLO attribution)
        self._qwait: Dict[str, float] = {}
        # span recording is per-record hot-path work; params.tracing=False
        # compiles the switch down to a no-op callable.  With tracing on,
        # the wrapper applies head sampling (pure function of trace_id —
        # fleet-consistent) and the parent lookup; error spans always
        # record so a sampled-out poisoned record stays diagnosable.
        self._span = (self._record_span if self.params.tracing
                      else (lambda *a, **kw: None))
        # SLO attribution (PR 13): judge each completed record against the
        # configured latency objective, charging the dominant stage
        self._slo = SloTracker.from_config(self.registry,
                                           self.params.serving_slo)
        # incident flight recorder (PR 15): the PROCESS ring — one per
        # process by design, so the AOT compile listeners, the gateway
        # and the engine all land on the one timeline the manager drains
        # to <pidfile>.events.jsonl.  Events carry this replica's id so
        # several engines sharing a test process stay attributable.
        # flight_recorder=False compiles the hop to a no-op (the ring
        # itself stays — other subsystems may still record).
        from analytics_zoo_tpu.common.observability import get_recorder
        self.recorder = get_recorder()
        if self.params.recorder_ring:
            self.recorder.resize(self.params.recorder_ring)
        self._event = (self._record_event if self.params.flight_recorder
                       else (lambda *a, **kw: None))
        # zero-drop rollout (PR 16): version identity + fault injection.
        # The injector is built even when inert (describe() rides the
        # health doc), but fault points only wire into the hot path when
        # armed for THIS replica's version — a predict fault instance-
        # patches do_predict, which `_dispatch_batch`'s custom-predict
        # fallback keeps on the real quarantine/bisect path.
        from analytics_zoo_tpu.serving.faults import FaultInjector
        self.model_version = self.params.model_version
        self._faults = FaultInjector(self.params.faults,
                                     self.model_version)
        if self._faults.predict_active and \
                isinstance(model, InferenceModel):
            model.do_predict = self._faults.wrap_predict(model.do_predict)
        # overload armor (PR 17): the brownout degradation ladder (driven
        # by the SLO burn rate the read loop feeds it) and the tenant-
        # aware admission gate the gateway consults per request.  Both
        # are config-gated — None wires nothing into the hot path.
        self._brownout = None
        self._brownout_next = 0.0            # next ladder tick (throttled)
        if self.params.brownout is not None:
            from analytics_zoo_tpu.serving.brownout import BrownoutLadder
            self._brownout = BrownoutLadder(
                self.params.brownout,
                recorder=(self.recorder if self.params.flight_recorder
                          else None),
                registry=self.registry, replica_id=self.replica_id)
        self._admission = None
        if self.params.admission is not None:
            from analytics_zoo_tpu.serving.admission import (
                AdmissionController)
            self._admission = AdmissionController(
                self.params.admission, registry=self.registry,
                queue_depth_fn=self._admission_depth,
                max_depth=getattr(queue, "max_depth", None),
                brownout_stage_fn=(lambda: self.brownout_stage),
                faults=self._faults)
        # smoothed per-batch predict service time — the early-drop gate's
        # "can this record still make its deadline" estimate (None until
        # the first batch lands: never drop on a guess)
        self._predict_ewma_s: Optional[float] = None
        # scheduler-side armor (priority-ordered claim/shed + deadline
        # early drop) rides the same opt-in as the config blocks, so a
        # deployment without them keeps the exact pre-PR-17 claim path
        self._armor = (self.params.admission is not None
                       or self.params.brownout is not None)
        # on-demand device profiling (PR 15): one jax.profiler trace at a
        # time, written under profile_dir (the manager points it at
        # <pidfile>.profiles)
        self.profile_dir: Optional[str] = None
        self._profile_lock = threading.Lock()
        self._profile_active = False
        self._t_start = time.monotonic()     # re-stamped by start()
        self._snapshot_seq = itertools.count(1)
        p = self.params
        self._write_retry = RetryPolicy(max_retries=p.write_retries,
                                        base_delay_s=p.write_backoff_s)
        self._breaker = CircuitBreaker(failure_threshold=p.breaker_threshold,
                                       cooldown_s=p.breaker_cooldown_s,
                                       name="result-write")
        # separate breaker for dead-letter writes: sharing the result-write
        # breaker would let a succeeding put_error reset the put_result
        # failure streak (and vice versa) — with the store fully down, this
        # one trips too and bounds the per-record cost of quarantining
        self._dead_breaker = CircuitBreaker(
            failure_threshold=p.breaker_threshold,
            cooldown_s=p.breaker_cooldown_s, name="dead-letter-write")
        self._pre_sup: Optional[SupervisedThread] = None
        self._predict_sup: Optional[SupervisedThread] = None
        self._write_sup: Optional[SupervisedThread] = None
        self._pre_pool = None                # lazy preprocess thread pool
        self._pre_pool_size = 0              # workers in the live pool
        # live retune (PR 10 autoscaler): validated knob targets staged by
        # retune() and APPLIED at the preprocess loop's batch boundary —
        # the one thread that owns the batcher/pool — so a mid-batch nudge
        # can never tear the pipeline
        self._knob_lock = threading.Lock()
        self._pending_knobs: Dict[str, float] = {}
        self._last_trim = time.monotonic()   # amortized trim schedule
        # per-stage timers + end-to-end (read -> result written) latency,
        # now registry histograms: same .record()/.snapshot() surface as the
        # old StageStats, plus Prometheus exposition
        reg = self.registry
        stage_hist = reg.histogram(
            "serving_stage_seconds",
            "Per-stage latency of the serving pipeline", labels=("stage",))
        self._stages = {
            name: stage_hist.labels(stage=name) for name in
            ("read", "preprocess", "stage_wait", "predict", "write")}
        self._e2e = reg.histogram(
            "serving_e2e_seconds",
            "Per-record latency from read_batch return to result written")
        # usage metering & attribution (PR 19): the meter owns the
        # {tenant=,model=} labelled series (serving_records_total,
        # serving_generated_tokens_total, serving_sheds_total,
        # serving_device_seconds_total, serving_request_seconds), the
        # per-interval usage-journal deltas the manager drains next to
        # spans/events, and the per-tenant SLO burn views.  With
        # metering {"enabled": False} it registers the pre-PR-19
        # unlabelled records/tokens series instead (the off arm of
        # `serving_bench --metering-overhead`).
        from analytics_zoo_tpu.serving.metering import UsageMeter
        _adm_tenants = ()
        if isinstance(self.params.admission, dict) and \
                isinstance(self.params.admission.get("tenants"), dict):
            _adm_tenants = tuple(self.params.admission["tenants"])
        self.meter = UsageMeter(
            reg, model=self.model_version,
            cfg=self.params.metering,
            tenants_configured=_adm_tenants,
            slo_defaults=self.params.serving_slo)
        self._m_quarantined = reg.counter(
            "serving_quarantined_total", "Records dead-lettered, by stage",
            labels=("stage",))
        self._m_shed = reg.counter(
            "serving_shed_total", "Deadline-exceeded records shed")
        # binary wire telemetry (PR 7): bytes observed per record format,
        # materialized at zero so mixed-traffic dashboards see every series
        # from day one, plus a per-record decode histogram labeled by
        # format so mixed-traffic decode cost is attributable (the
        # aggregate serving_stage_seconds{stage="preprocess"} document is
        # unchanged for PR 3/4 consumers)
        self._m_wire_bytes = reg.counter(
            "serving_wire_bytes_total",
            "Wire bytes observed at read, by record format",
            labels=("format",))
        for fmt in (_wire.FMT_JSON, _wire.FMT_BIN, _wire.FMT_SHM):
            self._m_wire_bytes.labels(format=fmt).inc(0)
        self._pre_fmt_hist = reg.histogram(
            "serving_preprocess_seconds",
            "Per-record preprocess (decode) latency, by wire format",
            labels=("format",))
        # replica telemetry (PR 5), materialized at zero so the series are
        # scrapeable from day one, not only after the first failover
        self._m_reclaimed = reg.counter(
            "serving_reclaimed_total",
            "Orphaned records re-claimed from dead replicas, by backend",
            labels=("backend",)).labels(backend=type(queue).__name__)
        self._m_reclaimed.inc(0)
        self._m_duplicates = reg.counter(
            "serving_duplicate_results_total",
            "Redelivered records suppressed because a result already "
            "existed")
        self._m_duplicates.inc(0)
        self._hb_gauge = reg.gauge(
            "serving_heartbeat_age_seconds",
            "Seconds since this replica's read loop last made progress",
            labels=("replica",))
        self._hb_gauge.labels(replica=self.replica_id).set_function(
            self._heartbeat_age)
        # callback gauges are registered additively (engines pooling into
        # one registry each contribute to the sum) and deregistered on
        # shutdown so a stopped engine neither skews the scrape nor stays
        # reachable from a shared registry
        self._gauge_fns = [
            (reg.gauge("serving_queue_depth", "Records waiting in the stream",
                       fn=self._queue_depth_metric), self._queue_depth_metric),
            (reg.gauge("serving_dead_letters", "Dead-letter backlog",
                       fn=self._dead_letter_metric), self._dead_letter_metric),
            (reg.gauge("serving_worker_restarts",
                       "Supervised-worker restarts across all stages",
                       fn=self._restarts_metric), self._restarts_metric),
        ]
        trips = lambda: self._breaker.trip_count  # noqa: E731
        self._gauge_fns.append(
            (reg.gauge("serving_breaker_trips", "Result-write breaker trips",
                       fn=trips), trips))
        # cold-start observability (PR 11): how long this replica took to
        # become useful, split into its phases — `load` is the model's
        # weight-load wall (stamped by do_load*; mmap'd store loads are
        # near-zero), `compile` the AOT warm-up pass.  The autoscaler reads
        # these off the health doc to log scale-up actuation lag.
        self._g_warm = reg.gauge(
            "serving_warmup_seconds",
            "Replica warm-up wall seconds, by phase", labels=("phase",))
        self._g_cold = reg.gauge(
            "replica_cold_start_seconds",
            "Engine construction to first result written, this replica")
        load_s = getattr(model, "load_seconds", None)
        if load_s is not None:
            self._g_warm.labels(phase="load").set(float(load_s))
        # inference-side latency/batch histograms (InferenceModel) ride this
        # engine's registry so one scrape covers the whole data plane (see
        # InferenceModel.bind_registry for the re-binding/pinning rules)
        if isinstance(model, InferenceModel):
            model.bind_registry(self.registry)
        # continuous batching (PR 12): the token-level scheduler replaces
        # the predict+write stages when `params.generation` is set.  Built
        # at construction so a model lacking the step-wise decode API
        # fails fast, not mid-stream.
        self._batcher = None
        self._gen_params = None
        if self.params.generation is not None:
            from analytics_zoo_tpu.serving.generate import (
                ContinuousBatcher, GenerationParams)
            self._gen_params = GenerationParams.from_dict(
                self.params.generation)
            self._batcher = ContinuousBatcher(model, self._gen_params)
            self._m_decode_steps = reg.counter(
                "serving_decode_steps_total",
                "Decode-step boundaries executed by the token scheduler")
            self._m_decode_steps.inc(0)
            self._m_ttft = reg.histogram(
                "serving_time_to_first_token_seconds",
                "Request admission to first generated token")
            self._g_tps = reg.gauge(
                "serving_tokens_per_second",
                "Generated tokens per second over the last rate window")
            self._g_tps.set(0.0)       # materialized: scrapable pre-traffic
            slots_fn = (lambda b=self._batcher: float(b.active))
            self._gauge_fns.append(
                (reg.gauge("serving_active_slots",
                           "Decode slots currently serving a request",
                           fn=slots_fn), slots_fn))
            self._last_steps = 0
            self._tps_window = (time.monotonic(), 0)   # (t0, tokens0)
            # generation continuity (PR 20): where checkpoints spool
            # (set post-construction by the manager, like profile_dir —
            # None disables checkpointing even with an interval set) and
            # the resume counters, materialized at zero so the chaos
            # acceptance can assert exact deltas
            self.snapshot_path = None
            self._last_resumed = 0
            self._m_resumed = reg.counter(
                "serving_generations_resumed_total",
                "Generations resumed from a dead owner's checkpoint")
            self._m_resumed.inc(0)
            self._m_resume_wasted = reg.counter(
                "serving_resume_wasted_tokens_total",
                "Generated tokens re-computed because a generation "
                "restarted without (or beyond) a usable checkpoint")
            self._m_resume_wasted.inc(0)
            # paged KV pool (PR 18): occupancy / free-block / prefix-hit
            # gauges so admission stalls are visible before the typed
            # kv_pool_exhausted flight-recorder event fires
            pool = getattr(self._batcher, "_pool", None)
            if pool is not None:
                free_fn = (lambda p=pool: float(p.free_blocks))
                self._gauge_fns.append(
                    (reg.gauge("serving_kv_pool_free_blocks",
                               "Free blocks in the paged KV pool",
                               fn=free_fn), free_fn))
                occ_fn = (lambda p=pool:
                          float(p.used_blocks) / max(1, p.n_blocks))
                self._gauge_fns.append(
                    (reg.gauge("serving_kv_pool_occupancy",
                               "Used fraction of the paged KV pool",
                               fn=occ_fn), occ_fn))
                prefix = getattr(self._batcher, "_prefix", None)
                if prefix is not None:
                    hits_fn = (lambda x=prefix: float(x.hits))
                    self._gauge_fns.append(
                        (reg.gauge("serving_kv_prefix_hits_total",
                                   "Prefix-cache hits at admission",
                                   fn=hits_fn), hits_fn))
        # resource accounting (PR 15): decompose device memory into
        # weights (PR 14 stored-dtype bytes) / kv_state (PR 12 lane
        # buffers) / executables (PR 11 AOT cache) — live gauges + the
        # health doc `resources` block the fleet aggregation sums
        from analytics_zoo_tpu.inference.resources import ResourceLedger
        from analytics_zoo_tpu.common.observability import process_stats
        self._ledger = ResourceLedger(model, batcher=self._batcher)
        hbm = reg.gauge("serving_hbm_bytes",
                        "Device memory by component: weights (stored "
                        "dtype), kv_state (generation lane buffers), "
                        "executables (AOT generated code)",
                        labels=("component",))
        for comp in ResourceLedger.COMPONENTS:
            fn = (lambda c=comp: self._ledger.hbm_bytes(c))
            child = hbm.labels(component=comp)
            child.add_function(fn)
            self._gauge_fns.append((child, fn))
        # per-process resource gauges (PR 15 satellite): RSS / CPU / FDs /
        # threads — per PROCESS, so engines pooling one registry in a
        # test process sum to the same process figure N times; real
        # deployments run one engine per process and the fleet merge sums
        # across processes
        for name, help_, key in (
                ("process_resident_memory_bytes",
                 "Resident set size of this serving process", "rss_bytes"),
                ("process_cpu_seconds_total",
                 "User+system CPU seconds consumed by this process",
                 "cpu_seconds"),
                ("process_open_fds",
                 "Open file descriptors in this process", "open_fds"),
                ("process_threads_total",
                 "Live threads in this process", "threads")):
            fn = (lambda k=key: float(process_stats().get(k) or 0))
            g = reg.gauge(name, help_, fn=fn)
            self._gauge_fns.append((g, fn))
        self._tb = None
        if tensorboard_dir:
            from analytics_zoo_tpu.utils.tbwriter import FileWriter
            self._tb = FileWriter(tensorboard_dir)

    # -- callback-gauge samplers (guarded: a dead backend yields NaN) --------
    def _queue_depth_metric(self) -> float:
        try:
            return float(self.queue.depth())
        except Exception:  # noqa: BLE001 — backend down
            return float("nan")

    def _dead_letter_metric(self) -> float:
        try:
            return float(self.queue.dead_letter_count())
        except Exception:  # noqa: BLE001
            return float("nan")

    def _restarts_metric(self) -> float:
        return float(sum(
            s.health()["restart_count"]
            for s in (self._pre_sup, self._predict_sup, self._write_sup)
            if s is not None))

    def _heartbeat_age(self) -> float:
        return time.monotonic() - self._hb_ts

    # -- overload armor (PR 17) ----------------------------------------------
    def _admission_depth(self) -> Optional[int]:
        """Queue depth for the admission gate's class caps; None (no
        signal, admit) when the backend is unreachable — a dead backend
        is the breaker's problem, not a reason to 429."""
        try:
            return int(self.queue.depth())
        except Exception:  # noqa: BLE001 — backend down
            return None

    @property
    def brownout_stage(self) -> int:
        return self._brownout.stage if self._brownout is not None else 0

    def admit_record(self, tenant=None, priority=None):
        """The gateway's per-request admission consult.  Returns an
        ``admission.Decision``, or None when no controller is configured
        (the gateway falls through to the legacy fleet-wide 429)."""
        if self._admission is None:
            return None
        d = self._admission.admit(tenant, priority)
        if not d.admitted:
            # rejections belong on the incident timeline next to the
            # brownout transitions they usually accompany
            self._event("admission_reject", reason=d.reason,
                        tenant=d.tenant, priority=d.priority)
        return d

    def _brownout_tick(self) -> None:
        """Feed the ladder the current SLO burn rate (throttled to 4 Hz —
        the ladder's dwell/hold windows are seconds, per-claim sampling
        would only add gauge reads to the hot loop)."""
        if self._brownout is None or self._slo is None:
            return
        now = time.monotonic()
        if now < self._brownout_next:
            return
        self._brownout_next = now + 0.25
        try:
            burn = self._slo.snapshot().get("burn_rate", 0.0)
        except Exception:  # noqa: BLE001 — ladder input, not load-bearing
            return
        self._brownout.observe(burn, now)

    def _note_predict_time(self, seconds: float) -> None:
        """EWMA of per-batch predict wall time (alpha 0.2) — the early
        drop gate's service-time estimate."""
        if seconds <= 0:
            return
        prev = self._predict_ewma_s
        self._predict_ewma_s = seconds if prev is None \
            else 0.8 * prev + 0.2 * seconds

    def _pressure_level(self) -> int:
        """Engine-side shed aggressiveness (0/1/2) from the staged-buffer
        backlog, the queue-depth fraction, and the brownout stage — see
        ``admission.pressure_level``."""
        from analytics_zoo_tpu.serving.admission import pressure_level
        staged = getattr(self, "_staged", None)
        staged_frac = 0.0
        if staged is not None:
            cap = max(1, staged.maxsize or 1)
            staged_frac = staged.qsize() / cap
        depth_frac = 0.0
        max_depth = getattr(self.queue, "max_depth", None)
        if max_depth:
            depth = self._admission_depth()
            if depth is not None:
                depth_frac = depth / float(max_depth)
        return pressure_level(staged_frac, depth_frac, self.brownout_stage)

    # -- incident flight recorder (PR 15) ------------------------------------
    def _record_event(self, kind: str, **attrs) -> None:
        """The engine's event hop: stamp replica identity, never raise —
        forensics must not be able to take serving down."""
        try:
            self.recorder.record(kind, replica=self.replica_id, **attrs)
        except Exception:  # noqa: BLE001 — diagnostic, not load-bearing
            pass

    # -- on-demand device profiling (PR 15) ----------------------------------
    PROFILE_MIN_S, PROFILE_MAX_S = 0.05, 300.0

    def start_profile(self, seconds: float,
                      out_dir: Optional[str] = None) -> Dict:
        """Arm one ``jax.profiler`` trace for ``seconds`` into the
        deployment's profile dir (the manager points ``profile_dir`` at
        ``<pidfile>.profiles``).  ONE trace at a time — a second request
        while one is armed raises ``RuntimeError`` (the gateway maps it
        to 409).  The start/sleep/stop cycle runs entirely on a daemon
        thread: ``jax.profiler.start_trace`` can take SECONDS to bring
        the profiler server up (measured ~15 s in sandboxed containers),
        and a probe-port handler must never block that long — the 202
        reply means "armed", the trace lands in ``path`` when done (the
        ``profile_done`` flight-recorder event marks completion)."""
        import tempfile
        seconds = min(max(float(seconds), self.PROFILE_MIN_S),
                      self.PROFILE_MAX_S)
        base = out_dir or self.profile_dir or os.path.join(
            tempfile.gettempdir(), f"serving-profile-{self.replica_id}")
        path = os.path.join(
            base, time.strftime("%Y%m%d-%H%M%S") + f"-{os.getpid()}")
        with self._profile_lock:
            if self._profile_active:
                raise RuntimeError(
                    "a profiling trace is already armed/running — one "
                    "at a time per process")
            os.makedirs(path, exist_ok=True)
            self._profile_active = True

        def _run():
            try:
                import jax
                jax.profiler.start_trace(path)
                time.sleep(seconds)
                jax.profiler.stop_trace()
                self._event("profile_done", path=path, seconds=seconds)
            except Exception as e:  # noqa: BLE001 — the trace failing
                # must not leave the engine permanently "busy"
                logger.exception("serving: profiling trace failed")
                self._event("profile_error",
                            error=f"{type(e).__name__}: {e}"[:200])
                try:
                    import jax
                    jax.profiler.stop_trace()
                except Exception:  # noqa: BLE001 — was never started
                    pass
            finally:
                with self._profile_lock:
                    self._profile_active = False

        threading.Thread(target=_run, name="serving-profile",
                         daemon=True).start()
        self._event("profile_start", path=path, seconds=seconds)
        logger.info("serving: profiling armed for %.2fs into %s",
                    seconds, path)
        return {"profiling": True, "path": path,
                "seconds": seconds, "replica_id": self.replica_id}

    # -- distributed tracing (PR 13) -----------------------------------------
    _TRACE_META_CAP = 8192

    def _record_span(self, stage, t0, t1, trace_id=None, uri=None,
                     error=None, parent_id=None, attrs=None):
        """The engine's span hop: head sampling + cross-process parenting.
        Error spans bypass sampling — a quarantine in a sampled-out trace
        must still be diagnosable (and lands in the tracer's error side
        buffer either way)."""
        meta = self._trace_meta.get(trace_id) if trace_id else None
        if error is None:
            if meta is not None:
                if not meta[1]:
                    return None
            elif not trace_sampled(trace_id, self.params.trace_sample):
                return None
        if parent_id is None and meta is not None:
            parent_id = meta[0]
        return self.tracer.span(stage, t0, t1, trace_id=trace_id, uri=uri,
                                error=error, parent_id=parent_id,
                                attrs=attrs)

    def _note_trace_ctx(self, rid, rec: Dict, t_claim: float) -> None:
        """Fold a record's propagated ``trace_ctx`` into this replica:
        remember (parent span id, sampled) for the span wrapper, and
        record the QUEUE-WAIT span — gateway/client ingest to this claim,
        measured as one wall-clock delta so no cross-process clock pair is
        needed inside the engine.  Absent/malformed context (legacy
        producers, old frames) degrades to no parent and no queue-wait
        span, never an error."""
        tc = rec.get("trace_ctx")
        if not isinstance(tc, dict):
            return
        tid = rec.get("trace_id")
        ctx = SpanContext.from_traceparent(tc.get("tp"))
        if ctx is not None:
            if tid is None:
                tid = rec["trace_id"] = ctx.trace_id
            if len(self._trace_meta) >= self._TRACE_META_CAP:
                for k in list(self._trace_meta)[
                        : self._TRACE_META_CAP // 2]:
                    self._trace_meta.pop(k, None)
            self._trace_meta[tid] = (ctx.span_id, ctx.sampled)
        ts = tc.get("ts")
        if isinstance(ts, (int, float)) and 0 < ts < float("inf"):
            wait_s = max((time.time_ns() - ts) / 1e9, 0.0)
            # clamp pathological skew (a producer clock far ahead/behind
            # would paint a day-long queue-wait bar across the timeline)
            wait_s = min(wait_s, 3600.0)
            self._qwait[rid] = wait_s
            if len(self._qwait) > self._TRACE_META_CAP:
                for k in list(self._qwait)[: self._TRACE_META_CAP // 2]:
                    self._qwait.pop(k, None)
            self._span("queue_wait", t_claim - wait_s, t_claim,
                       trace_id=tid, uri=rid)

    def _slo_observe(self, rid, e2e_s: float,
                     stages: Optional[Dict] = None,
                     tenant: Optional[str] = None) -> float:
        """Feed one completed record to the SLO tracker (no-op when no
        ``serving_slo`` block is configured) and the per-tenant burn
        view.  Queue-wait measured at claim is folded in both as a
        stage and into the judged latency, so "we missed the SLO
        queueing" is attributable.  Returns the folded e2e so the
        caller can charge ``serving_request_seconds`` batched per
        (tenant, flush) — the histogram hop is the only per-record
        metering cost left on the write worker, so it's amortized."""
        qwait = self._qwait.pop(rid, None)
        stages = dict(stages or {})
        if qwait is not None:
            stages["queue_wait"] = qwait
            e2e_s = float(e2e_s) + qwait
        # per-tenant burn views share the fleet objective unless the
        # metering block names per-tenant objectives (no objective
        # anywhere = no view; the meter no-ops)
        self.meter.slo_observe(tenant, e2e_s, stages)
        if self._slo is not None:
            self._slo.observe(e2e_s, stages)
        return float(e2e_s)

    # -- lease lifecycle (PR 5 horizontal replicas) --------------------------
    def _ack(self, rids: List[str]) -> None:
        """Release the claim on fully-handled records (result/quarantine/
        shed marker written).  A failed ack is NOT an error path: the
        records stay pending, some replica reclaims them after the lease,
        and duplicate suppression keeps the result set exact."""
        if not rids:
            return
        for rid in rids:
            self._inflight.pop(rid, None)
        try:
            self.queue.ack(list(rids))
        except Exception as e:  # noqa: BLE001 — backend down mid-ack
            logger.warning(
                "serving: ack failed for %d record(s) (%s: %s); they will "
                "be redelivered after the lease", len(rids),
                type(e).__name__, e)

    def _maybe_reclaim(self) -> List[Tuple[str, Dict]]:
        """Periodic reclaim sweep: re-claim records whose lease expired on
        a dead (or wedged) replica and feed the survivors into the normal
        pipeline.  Redelivered records that already HAVE a result — the
        previous owner wrote it but died before acking — are suppressed:
        acked here, counted, never re-predicted."""
        p = self.params
        if p.lease_s is None or p.lease_s <= 0:
            return []
        interval = p.reclaim_interval_s if p.reclaim_interval_s is not None \
            else max(p.lease_s / 2.0, 0.05)
        now = time.monotonic()
        if now - self._last_reclaim < interval:
            return []
        self._last_reclaim = now
        try:
            entries = self.queue.reclaim(
                p.lease_s, max_items=p.max_batch or p.batch_size)
        except Exception as e:  # noqa: BLE001 — backend down: next sweep
            logger.warning("serving: reclaim sweep failed (%s: %s)",
                           type(e).__name__, e)
            return []
        if not entries:
            return []
        # self-reclaim guard: records currently in OUR pipeline (a cold jit
        # compile, a long batch) can outlive the lease too — re-serving
        # them here would double-predict our own in-flight work.  The
        # queue-side reclaim already refreshed their lease under our
        # consumer name, which is exactly a lease extension; just don't
        # feed them back in.  Entries older than the stale bound are
        # assumed abandoned (a worker crashed mid-pipeline and the
        # supervisor restarted it) and become reclaimable again.
        stale_s = max(p.lease_s * 10.0, p.lease_s + 60.0)
        for rid, ts in list(self._inflight.items()):
            if now - ts > stale_s:
                self._inflight.pop(rid, None)
        own = [e for e in entries if e[0] in self._inflight]
        entries = [e for e in entries if e[0] not in self._inflight]
        if own:
            logger.debug(
                "serving: replica %s lease-extended %d of its own "
                "in-flight record(s) instead of self-reclaiming",
                self.replica_id, len(own))
        if not entries:
            return []
        self.reclaimed += len(entries)
        self._m_reclaimed.inc(len(entries))
        try:
            existing = self.queue.get_results(
                [rid for rid, _, _ in entries])
        except Exception:  # noqa: BLE001 — store down: skip suppression,
            existing = {}  # idempotent writes keep the result set exact
        out: List[Tuple[str, Dict]] = []
        t = time.monotonic()
        for rid, rec, deliveries in entries:
            tid = rec.get("trace_id") if isinstance(rec, dict) else None
            self._span("reclaim", t, t, trace_id=tid, uri=rid)
            prior = existing.get(rid)
            partial_n = 0
            if isinstance(prior, dict) and prior.get("partial"):
                # a PARTIAL streaming result (PR 12) is not a terminal
                # state: the previous owner died mid-generation, so the
                # record must be re-served, not suppressed — the fresh
                # terminal result overwrites the stale partial.  Its
                # token count survives as the wasted-work floor the
                # resume path (PR 20) tries to recover.
                try:
                    partial_n = int(prior.get("n") or 0)
                except (TypeError, ValueError):
                    partial_n = 0
                prior = None
            if prior is not None:
                self.duplicates += 1
                self._m_duplicates.inc()
                self._ack([rid])
                continue
            if isinstance(rec, dict):
                # claim lineage rides the record: a quarantine of this
                # record dead-letters WITH its delivery count, and the
                # result write stamps it for the client
                rec["deliveries"] = deliveries
            if 0 < p.max_deliveries < deliveries:
                # poison-pill parking (PR 10): a record that keeps getting
                # redelivered — e.g. it crashes every replica that claims
                # it, or its terminal write keeps failing — must not loop
                # through reclaim forever, burning a predict slot per lease.
                # Park it in the dead-letter queue (error result + entry,
                # claim released) where `manager replay` can resurrect it
                # after a fix.
                self._quarantine(
                    rid, "reclaim",
                    RuntimeError(
                        f"max-deliveries-exceeded: delivery "
                        f"{deliveries} > max_deliveries="
                        f"{p.max_deliveries}"),
                    record=rec if isinstance(rec, dict) else None,
                    trace_id=tid)
                continue
            self._redelivered[rid] = deliveries
            if self._batcher is not None and isinstance(rec, dict):
                # generation continuity (PR 20): attach the dead owner's
                # checkpointed resume state, or meter the restart cost
                resume = self._load_resume(rid, rec, partial_n)
                if resume is not None:
                    rec["_resume"] = resume
            out.append((rid, rec))
        if len(self._redelivered) > 4096:
            # fire-and-forget bound: entries are popped at write/quarantine/
            # shed; a pathological stream of never-completing redeliveries
            # must not grow the map without limit.  Records still in OUR
            # pipeline keep their entry — evicting them would strip the
            # "deliveries" lineage off results/dead-letters mid-flight.
            for rid in list(self._redelivered):
                if len(self._redelivered) <= 2048:
                    break
                if rid not in self._inflight:
                    self._redelivered.pop(rid, None)
        if out:
            logger.info(
                "serving: replica %s reclaimed %d orphaned record(s) "
                "(lease %.3gs, %d suppressed as duplicates)",
                self.replica_id, len(out), p.lease_s,
                len(entries) - len(out))
            self._event("reclaim", count=len(out),
                        suppressed=len(entries) - len(out))
        return out

    # -- generation continuity (PR 20) ---------------------------------------
    def _load_resume(self, rid: str, rec: Dict,
                     partial_n: int) -> Optional[Dict]:
        """Recover the dead owner's checkpointed decode state for one
        reclaimed generation record: follow the lease annotation to its
        snapshot spool, pick the deepest checkpoint of the matching
        epoch, and verify its integrity stamp.  Any failure falls back
        LOUDLY to restart-from-0 (`gen_resume_failed` event) and meters
        the streamed progress the restart throws away; a success emits
        `gen_resume` and meters only the partial tail past the last
        checkpoint."""
        gp = self._gen_params
        if gp is None or not gp.resume:
            # resume disabled: the restart re-computes every token the
            # dead owner already streamed — metered so the chaos bench's
            # restart arm measures its true waste
            if partial_n > 0:
                self._m_resume_wasted.inc(partial_n)
            return None
        try:
            ann = self.queue.annotation(rid)
        except Exception:  # noqa: BLE001 — backend hiccup: restart
            ann = None
        if not isinstance(ann, dict) or not ann.get("spool"):
            if partial_n > 0:
                self._m_resume_wasted.inc(partial_n)
                self._event("gen_resume_failed", rid=rid,
                            reason="no-annotation", wasted=partial_n)
            return None
        from analytics_zoo_tpu.serving import tracecollect
        spool = str(ann["spool"])
        epoch = int(ann.get("epoch") or 0)
        best = None
        try:
            paths = [path for path in (spool, spool + ".1")
                     if os.path.exists(path)]
            for snap in tracecollect.load_snapshots(paths):
                if snap.get("rid") != rid \
                        or int(snap.get("epoch") or 0) != epoch:
                    continue
                if best is None \
                        or int(snap.get("n") or 0) > int(best["n"] or 0):
                    best = snap
        except Exception:  # noqa: BLE001 — unreadable spool: restart
            best = None
        reason = None
        if best is None:
            reason = "no-snapshot"
        else:
            try:
                crc = int(best.get("crc"))
            except (TypeError, ValueError):
                crc = None
            if crc != tracecollect.snapshot_checksum(best):
                reason = "checksum-mismatch"
        tid = rec.get("trace_id")
        if reason is not None:
            self._m_resume_wasted.inc(partial_n)
            self._event("gen_resume_failed", rid=rid, trace_id=tid,
                        reason=reason, wasted=partial_n)
            return None
        n = int(best.get("n") or 0)
        wasted = max(0, partial_n - n)
        if wasted:
            self._m_resume_wasted.inc(wasted)
        self._event("gen_resume", rid=rid, trace_id=tid, epoch=epoch,
                    resumed_tokens=n, wasted=wasted,
                    from_replica=ann.get("replica"))
        return {"tokens": [int(t) for t in best.get("tokens") or []],
                "epoch": epoch + 1}

    # -- result write with backpressure (ClusterServing.scala:276-307) -------
    def _put_result(self, rid, value):
        """Retry with backoff (blocking: upstream reads stall), behind a
        circuit breaker — a dead result store fails fast instead of making
        every batch grind through the full retry schedule."""
        self._breaker.call(self._write_retry.call,
                           self.queue.put_result, rid, value)

    def _flush_results(self, pairs: List[Tuple[str, Dict]],
                       tmap: Optional[Dict] = None,
                       tenmap: Optional[Dict] = None) -> int:
        """Write one micro-batch of results in a single backend round-trip
        (`queue.put_results`), behind the same RetryPolicy + CircuitBreaker
        as single writes.  When the batch write fails (mid-way or wholesale),
        fall back to per-record writes: `put_result` is idempotent per key,
        so re-writing an already-committed pair cannot duplicate a result,
        and only the records that individually fail are quarantined.

        Records-served attribution (PR 19) is charged HERE — the one
        choke point both planes flush through — so exactly the records
        whose results were committed are billed, per tenant, on both the
        batched and the degraded per-record path."""
        if not pairs:
            return 0
        tenmap = tenmap or {}
        try:
            self._breaker.call(self._write_retry.call,
                               self.queue.put_results, pairs)
            # results durable: release the claims (at-least-once becomes
            # exactly-one-result here)
            self._ack([rid for rid, _ in pairs])
            # one charge per tenant per flush, not per record: the meter
            # hop is on the write worker's critical path
            by_tenant: Dict[Optional[str], int] = {}
            for rid, _ in pairs:
                ten = tenmap.get(rid)
                by_tenant[ten] = by_tenant.get(ten, 0) + 1
            for ten, n in by_tenant.items():
                self.meter.records(ten, n)
            return len(pairs)
        except Exception as e:  # noqa: BLE001 — batch path down: degrade
            if not isinstance(e, CircuitBreakerOpen):
                logger.warning(
                    "serving: batched result write failed (%s: %s); "
                    "falling back to per-record writes",
                    type(e).__name__, e)
            n = 0
            written: List[str] = []
            for rid, value in pairs:
                try:
                    self._put_result(rid, value)
                    written.append(rid)
                    n += 1
                    self.meter.records(tenmap.get(rid))
                except Exception as rec_exc:  # noqa: BLE001 — record down
                    # deliberate shed-don't-block tradeoff: when the result
                    # store is down past the retry budget the computed value
                    # is dead-lettered (client sees the error and can
                    # re-enqueue) instead of stalling the write worker
                    # behind an unbounded blocking retry
                    self._quarantine(rid, "put_result", rec_exc,
                                     trace_id=(tmap or {}).get(rid),
                                     tenant=tenmap.get(rid))
            self._ack(written)
            return n

    def _quarantine(self, rid, stage: str, exc: BaseException,
                    record: Optional[Dict] = None,
                    trace_id: Optional[str] = None,
                    tenant: Optional[str] = None):
        """Per-record fault isolation: the poisoned record gets an error
        RESULT (client unblocks and sees the failure) plus a dead-letter
        entry; the rest of its micro-batch proceeds untouched.  The span
        carries the error (and the record's trace_id when known), so the
        quarantine is diagnosable from the trace alone."""
        self.dead_lettered += 1
        self._m_quarantined.labels(stage=stage).inc()
        if tenant is None and record is not None:
            tenant = record.get("tenant")
        self.meter.sheds(tenant)       # attribution (PR 19): who lost it
        msg = f"{stage}: {type(exc).__name__}: {exc}"
        if trace_id is None and record is not None:
            trace_id = record.get("trace_id")
        now = time.monotonic()
        self._span(stage, now, now, trace_id=trace_id, uri=rid,
                         error=msg,
                         attrs=({"tenant": tenant} if tenant else None))
        logger.warning("serving: quarantining record %r (%s)", rid, msg)
        self._event("quarantine", rid=str(rid), stage=stage,
                    error=msg[:200], trace_id=trace_id, tenant=tenant)
        handled = False
        try:
            self._dead_breaker.call(self.queue.put_error, rid, msg,
                                    record=record, trace_id=trace_id)
            handled = True
        except CircuitBreakerOpen:
            # store is down: don't block per record on the dead backend
            logger.warning("serving: dead-letter write for %r skipped "
                           "(breaker open)", rid)
        except Exception:  # noqa: BLE001 — best-effort: queue may be down
            logger.exception("serving: dead-letter write for %r failed", rid)
        self._redelivered.pop(rid, None)
        if handled:
            # the quarantine is HANDLED (error result + dead-letter entry
            # are its terminal state, durably written): release the claim
            # so no replica churns it back through the pipeline forever
            self._ack([rid])
        else:
            # terminal write failed: the claim stays pending so the record
            # is REDELIVERED after the lease instead of silently lost (the
            # pre-lease contract shed it here).  It is no longer in OUR
            # pipeline, so drop the self-reclaim guard — any replica,
            # including this one, may retry it against a recovered store.
            self._inflight.pop(rid, None)

    # -- end-to-end deadlines (PR 2 availability) ----------------------------
    def _shed_expired(self, rid, rec: Optional[Dict],
                      deadline_ns: Optional[int] = None,
                      stage: str = "read",
                      trace_id: Optional[str] = None,
                      tenant: Optional[str] = None) -> bool:
        """True when the record's enqueue-stamped `deadline_ns` has passed:
        the client gets a `deadline-exceeded` error result and the record
        never occupies a predict slot.  The shed is recorded as a zero-width
        span at the gate's stage, error attached, so an expired record still
        shows up in its trace."""
        dl = deadline_ns if deadline_ns is not None \
            else (rec or {}).get("deadline_ns")
        if dl is None:
            return False
        try:
            expired = time.time_ns() > int(dl)
        except (TypeError, ValueError, OverflowError) as e:
            # this gate runs OUTSIDE the per-record quarantine: a junk
            # deadline from a raw-xadd producer would otherwise kill the
            # read worker, which restarts, redelivers the leased record,
            # and dies again — crash-loop, not fault isolation.  (The
            # gateway 400s these at the edge; this covers every other
            # producer.)  True = the record leaves the pipeline.
            self._quarantine(rid, stage, e, record=rec, trace_id=trace_id)
            return True
        if not expired:
            return False
        if rec is not None:
            if trace_id is None:
                trace_id = rec.get("trace_id")
            if tenant is None and isinstance(rec.get("tenant"), str):
                tenant = rec.get("tenant")
        self._shed_terminal(rid, stage=stage, trace_id=trace_id,
                            tenant=tenant)
        return True

    def _shed_terminal(self, rid, stage: str = "read",
                       trace_id: Optional[str] = None,
                       error: str = "deadline-exceeded: budget elapsed "
                                    "before predict",
                       extra: Optional[Dict] = None,
                       tenant: Optional[str] = None) -> None:
        """Terminal shed bookkeeping: error marker written (best-effort),
        claim released, counters/span recorded.  Shared by the deadline
        gates and the generation scheduler's step-boundary sheds;
        ``extra`` rides the marker (a mid-generation shed's partial
        tokens must survive the overwrite of the streamed partial)."""
        self.shed += 1
        self._m_shed.inc()
        self.meter.sheds(tenant)       # attribution (PR 19): who lost it
        now = time.monotonic()
        self._span(stage, now, now, trace_id=trace_id, uri=rid,
                         error=error,
                         attrs=({"tenant": tenant} if tenant else None))
        logger.info("serving: shedding expired record %r", rid)
        self._event("shed", rid=str(rid), stage=stage, trace_id=trace_id,
                    tenant=tenant)
        result = {"error": error}
        if extra:
            result.update(extra)
        if trace_id is not None:
            result["trace_id"] = trace_id
        try:
            self._put_result(rid, result)
        except Exception:  # noqa: BLE001 — store down: client's own
            pass           # deadline still unblocks it
        # shed = terminal (the budget is gone for every replica alike):
        # release the claim even when the marker write failed
        self._redelivered.pop(rid, None)
        self._ack([rid])

    def _claim_shed(self, rid, rec, to_shed) -> bool:
        """PR 17 claim gates, armored deployments only.  True when the
        record left the pipeline: either its priority class is being
        shed under the current pressure level, or the deadline early
        drop judged it unmeetable — remaining budget shorter than the
        estimated wait through the staged backlog at the smoothed
        per-batch service time (no estimate yet = never drop)."""
        from analytics_zoo_tpu.serving.admission import (
            deadline_unmeetable, normalize_priority)
        if not isinstance(rec, dict):
            return False
        trace_id = rec.get("trace_id")
        tenant = rec.get("tenant") \
            if isinstance(rec.get("tenant"), str) else None
        if to_shed:
            prio = normalize_priority(rec.get("priority"))
            if prio in to_shed:
                self._shed_terminal(
                    rid, stage="claim", trace_id=trace_id,
                    error=f"shed: {prio} class dropped under overload "
                          f"pressure", tenant=tenant)
                return True
        dl = rec.get("deadline_ns")
        if dl is not None and self._predict_ewma_s:
            try:
                remaining_s = (int(dl) - time.time_ns()) / 1e9
            except (TypeError, ValueError, OverflowError):
                return False     # junk deadline: _shed_expired's business
            backlog = 0
            for q in (getattr(self, "_staged", None),
                      getattr(self, "_writeq", None)):
                if q is not None:
                    backlog += q.qsize()
            if deadline_unmeetable(remaining_s, backlog,
                                   self._predict_ewma_s):
                self._shed_terminal(
                    rid, stage="claim", trace_id=trace_id,
                    error="deadline-unmeetable: estimated queue wait "
                          "exceeds the remaining budget", tenant=tenant)
                return True
        return False

    # -- adaptive micro-batching (PR 3 tentpole) -----------------------------
    def _read_coalesced(self):
        """Coalescing read: pull up to ``max_batch`` records, and once a
        PARTIAL batch has arrived keep reading for at most ``max_wait_ms``
        to fill a device-sized batch (the Structured-Streaming micro-batch
        coalescing analog).  An idle stream still returns empty within
        ``poll_timeout_s`` — the wait budget only starts when there is a
        first record to amortize it against."""
        p = self.params
        max_batch = p.max_batch or p.batch_size
        batch = self.queue.read_batch(max_batch, p.poll_timeout_s)
        if not batch or len(batch) >= max_batch or p.max_wait_ms <= 0:
            return batch
        deadline = time.monotonic() + p.max_wait_ms / 1000.0
        while len(batch) < max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            more = self.queue.read_batch(max_batch - len(batch),
                                         min(remaining, p.poll_timeout_s))
            if more:
                batch.extend(more)
        return batch

    def _stack_group(self, ids, items, deadlines, traces=None, t_read=None,
                     metas=None):
        """Stack one same-shape group into a staged
        (ids, tensors, scales, deadlines, traces) micro-batch."""
        t_ready = time.monotonic()
        if all(isinstance(it, QuantizedTensor) for it in items):
            # compact-dtype batch: ship the int8/uint8 bytes to the device,
            # dequantize there (per-row scales)
            tensors = np.stack([it.data for it in items])
            scales = np.asarray([it.scale for it in items], np.float32)
            return _Staged(ids, tensors, scales, deadlines, traces,
                           t_read, t_ready, metas)
        # mixed float/quantized batches dequantize the stragglers on host
        tensors = np.stack([
            it.data.astype(np.float32) * it.scale
            if isinstance(it, QuantizedTensor) else it for it in items])
        return _Staged(ids, tensors, None, deadlines, traces,
                       t_read, t_ready, metas)

    def _preprocess_pool(self):
        """Lazy thread pool for ``preprocess_workers > 1`` (base64 + cv2
        decode release the GIL, so a pool scales on multi-core hosts);
        ``None`` means inline preprocessing (the pre-PR-3 behaviour)."""
        if self.params.preprocess_workers <= 1:
            return None
        if self._pre_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pre_pool_size = self.params.preprocess_workers
            self._pre_pool = ThreadPoolExecutor(
                max_workers=self._pre_pool_size,
                thread_name_prefix="serving-pre")
        return self._pre_pool

    # -- live retune (PR 10 closed-loop autoscaling) -------------------------
    MAX_PREPROCESS_WORKERS = 32

    def retune(self, max_batch: Optional[int] = None,
               max_wait_ms: Optional[float] = None,
               preprocess_workers: Optional[int] = None,
               inflight_batches: Optional[int] = None) -> Dict:
        """Stage a live data-plane retune (the autoscaler's FAST actuator
        tier).  Values are validated/clamped HERE — ``max_batch`` to the
        pow-2 bucket ladder within [mesh batch axis, model max_batch],
        ``inflight_batches`` to the model's concurrency contract,
        ``preprocess_workers`` to [1, MAX_PREPROCESS_WORKERS] — and applied
        by the preprocess worker at its next batch boundary, so a mid-batch
        nudge can never tear the pipeline (pool swap and write-queue resize
        happen between micro-batches, on the threads that own them).
        Returns the clamped targets that will take effect.  Safe to call
        before ``start()`` (targets land in params directly at start)."""
        from analytics_zoo_tpu.inference.inference_model import _pow2_floor
        staged: Dict[str, float] = {}
        if max_batch is not None:
            mb = _pow2_floor(max(1, int(max_batch)))
            multiple = getattr(self.model, "_batch_multiple", 1) or 1
            cap = getattr(self.model, "max_batch", None)
            mb = max(mb, int(multiple))      # pow-2 >= multiple divides it
            if cap is not None:
                mb = min(mb, int(cap))
            staged["max_batch"] = mb
        if max_wait_ms is not None:
            staged["max_wait_ms"] = max(0.0, float(max_wait_ms))
        if preprocess_workers is not None:
            staged["preprocess_workers"] = min(
                max(1, int(preprocess_workers)), self.MAX_PREPROCESS_WORKERS)
        if inflight_batches is not None:
            inflight = max(1, int(inflight_batches))
            model_cap = getattr(self.model, "concurrent_num", None)
            if model_cap is not None:
                inflight = min(inflight, int(model_cap))
            staged["inflight_batches"] = inflight
        with self._knob_lock:
            self._pending_knobs.update(staged)
        return staged

    def knobs(self) -> Dict:
        """Current data-plane knob targets (pending retunes win over the
        applied params) — the autoscaler's view of where the fast tier is."""
        p = self.params
        doc = {"max_batch": p.max_batch or p.batch_size,
               "max_wait_ms": p.max_wait_ms,
               "preprocess_workers": p.preprocess_workers,
               "inflight_batches": p.inflight_batches,
               "max_batch_ceiling": int(getattr(self.model, "max_batch",
                                                1024) or 1024),
               "inflight_ceiling": int(getattr(self.model, "concurrent_num",
                                               None) or 64)}
        with self._knob_lock:
            doc.update(self._pending_knobs)
        return doc

    def _apply_pending_knobs(self) -> None:
        """Apply staged retunes.  Runs on the preprocess worker between
        micro-batches: `params.max_batch`/`max_wait_ms` are read per batch
        by `_read_coalesced`, the pool swap happens while no decode is in
        flight, and the write-queue resize mutates `maxsize` under the
        queue's own mutex (blocked putters poll on a 0.1 s timeout, so a
        grown queue is picked up promptly either way)."""
        with self._knob_lock:
            if not self._pending_knobs:
                return
            staged, self._pending_knobs = self._pending_knobs, {}
        p = self.params
        if "max_batch" in staged:
            p.max_batch = int(staged["max_batch"])
        if "max_wait_ms" in staged:
            p.max_wait_ms = float(staged["max_wait_ms"])
        if "preprocess_workers" in staged:
            p.preprocess_workers = int(staged["preprocess_workers"])
            if self._pre_pool is not None and \
                    self._pre_pool_size != p.preprocess_workers:
                # no decode in flight at the batch boundary: the old pool
                # has nothing queued, so the swap is clean
                self._pre_pool.shutdown(wait=False)
                self._pre_pool = None
        if "inflight_batches" in staged:
            p.inflight_batches = int(staged["inflight_batches"])
            q = getattr(self, "_writeq", None)
            if q is not None:
                with q.mutex:
                    q.maxsize = p.inflight_batches
                    q.not_full.notify_all()
        logger.info("serving: replica %s retuned %s", self.replica_id,
                    staged)
        self._event("retune", **{k: float(v) for k, v in staged.items()})

    def _read_and_preprocess(self):
        """Read one micro-batch and preprocess it record-by-record, returning
        a LIST of staged (ids, tensors, scales) groups — one per input shape.
        A malformed record (bad base64, undecodable image, byte/shape
        mismatch) quarantines alone; records with a different-but-valid shape
        form their own group (multi-shape clients are legitimate — the pow-2
        bucketing in InferenceModel compiles per signature anyway) instead of
        poisoning np.stack or being rejected for losing a batch vote.

        With ``preprocess_workers > 1`` the per-record decode fans out across
        the pool; results are gathered in submission order, so quarantine
        attribution and shape grouping are identical to the inline path.

        PR 5: the periodic reclaim sweep runs here, so records orphaned by
        a dead replica enter the pipeline ahead of fresh stream reads and
        go through the exact same shed/quarantine/trace machinery."""
        t0 = time.monotonic()
        self._hb_ts = t0      # replica heartbeat: the read loop is alive
        self._apply_pending_knobs()
        # brownout ladder tick (PR 17): feed the SLO burn rate in, so the
        # stage the gateway/scheduler consult tracks the live window
        self._brownout_tick()
        if self._faults.claim_active:
            # claim_stall fault (PR 17): a deterministic backlog-builder
            # for overload chaos — the read loop stalls BEFORE claiming
            stall = self._faults.take_claim_stall()
            if stall > 0.0:
                self._event("claim_stall", state=f"{stall:g}s")
                self._stop.wait(stall)
        if self._retiring.is_set():
            # decommissioning: claim NOTHING new (no reads, no reclaims) so
            # the pipeline flushes and the drain exit fires; the backlog
            # belongs to the surviving replicas
            return None
        batch = self._maybe_reclaim()
        batch += self._read_coalesced()
        t_read = time.monotonic()
        if not batch:
            return None       # stream empty (drain may exit on this)
        self._stages["read"].record(t_read - t0)
        bytes_by_tenant: Dict[Optional[str], int] = {}
        for rid, rec in batch:
            # claim registry for the self-reclaim guard: while a record is
            # in OUR pipeline the reclaim sweep must not mistake it for a
            # dead replica's orphan (cleared on ack)
            self._inflight[rid] = t_read
            # propagated span context (PR 13): parent/sampled for this
            # trace + the queue-wait span from the stamped ingest time
            self._note_trace_ctx(rid, rec, t_read)
            # every record that enters the pipeline gets a trace: producers
            # that bypass the client (raw xadd) are stamped at read instead
            rec.setdefault("trace_id", new_trace_id())
            # per-format wire-byte accounting (PR 7): frames carry their
            # exact length; legacy records are dominated by the b64 string.
            # Type-guarded — this loop runs outside the per-record
            # quarantine, and raw-xadd producers control these fields
            nbytes = rec.get("wire_bytes")
            if not (isinstance(nbytes, (int, float))
                    and 0 <= nbytes < float("inf")):
                # non-numeric, negative, inf, or NaN (NaN fails 0 <=):
                # inc()ing any of those poisons the monotonic counter for
                # the process lifetime
                raw = rec.get("b64") or rec.get("image") or ""
                nbytes = len(raw) \
                    if isinstance(raw, (str, bytes, bytearray)) else 0
            self._m_wire_bytes.labels(
                format=_wire_fmt_label(rec)).inc(nbytes)
            # usage attribution (PR 19): ingress bytes charged to the
            # tenant the gateway stamped (legacy records -> "unknown"),
            # accumulated locally and charged once per read batch
            ten = rec.get("tenant")
            ten = ten if isinstance(ten, str) else None
            bytes_by_tenant[ten] = bytes_by_tenant.get(ten, 0) + nbytes
            self._span("read", t0, t_read,
                             trace_id=rec["trace_id"], uri=rid)
        for ten, nb in bytes_by_tenant.items():
            self.meter.wire_bytes(ten, nb)
        # priority-ordered claim and shed (PR 17): interactive records
        # stage first; under pressure the lowest classes are shed before
        # they spend a predict slot, and a record that can no longer make
        # its deadline through the current backlog is dropped at claim
        # instead of timing out mid-pipeline.  Opt-in (self._armor) — an
        # unarmored deployment keeps the exact legacy claim path.
        from analytics_zoo_tpu.serving.admission import (
            PRIORITIES, normalize_priority, normalize_tenant, shed_classes)
        if self._armor:
            rank = {p: i for i, p in enumerate(PRIORITIES)}
            batch = sorted(
                batch, key=lambda kv: rank[normalize_priority(
                    kv[1].get("priority")
                    if isinstance(kv[1], dict) else None)])
            to_shed = shed_classes(self._pressure_level())
        else:
            to_shed = ()
        kept = []
        for rid, rec in batch:
            if self._shed_expired(rid, rec):
                continue
            if self._armor and self._claim_shed(rid, rec, to_shed):
                continue
            kept.append((rid, rec))

        def pre_one(rec):
            """Per-record timed decode, so one slow record is visible in
            its own preprocess span rather than smeared across the batch."""
            p0 = time.monotonic()
            out = self.preprocess(rec)
            return out, p0, time.monotonic()

        pool = self._preprocess_pool()
        items: List = []      # (rid, item, deadline_ns, trace_id)
        if pool is None:
            gathered = [(rid, rec, None) for rid, rec in kept]
        else:
            gathered = [(rid, rec, pool.submit(pre_one, rec))
                        for rid, rec in kept]
        for rid, rec, fut in gathered:
            try:
                item, p0, p1 = fut.result() if fut is not None \
                    else pre_one(rec)
                self._pre_fmt_hist.labels(
                    format=_wire_fmt_label(rec)).record(p1 - p0)
                self._span("preprocess", p0, p1,
                                 trace_id=rec.get("trace_id"), uri=rid)
                # per-record generation options (PR 12): `gen` rides the
                # record untyped — the scheduler validates/clamps values
                meta = rec.get("gen")
                meta = meta if isinstance(meta, dict) else None
                # identity hoist (PR 19): tenant must outlive the record
                # dict — batch formation, result docs, device-second
                # apportioning and generation-token charging all read it
                # off the meta.  None (not "unknown") for legacy records,
                # so the meter owns the fold in exactly one place.
                ten = rec.get("tenant")
                meta = dict(meta or {})
                meta["_tenant"] = normalize_tenant(ten) \
                    if isinstance(ten, str) and ten else None
                if self._armor:
                    # the brownout clamp (_submit_group) needs the class
                    # after the record dict is gone: ride it on the meta
                    meta["_priority"] = normalize_priority(
                        rec.get("priority"))
                if isinstance(rec.get("_resume"), dict):
                    # resume state stapled on by _maybe_reclaim (PR 20)
                    # must survive to _submit_group, like the identity
                    meta["_resume"] = rec["_resume"]
                items.append((rid, item, rec.get("deadline_ns"),
                              rec.get("trace_id"), meta))
            except Exception as e:  # noqa: BLE001 — malformed record
                self._quarantine(rid, "preprocess", e, record=rec)
        if kept:
            # one sample per micro-batch (like the other stage timers);
            # per-RECORD weighting is reserved for the e2e latency reservoir
            self._stages["preprocess"].record(time.monotonic() - t_read)
        groups: Dict[tuple, List] = {}
        for rid, item, dl, tid, meta in items:
            shape = np.shape(item.data if isinstance(item, QuantizedTensor)
                             else item)
            groups.setdefault(shape, []).append((rid, item, dl, tid, meta))
        if not groups:
            # records WERE read but all shed/quarantined: distinct from an
            # empty stream so a draining _pre_loop keeps reading the backlog
            return []
        return [self._stack_group([rid for rid, *_ in quints],
                                  [it for _, it, *_ in quints],
                                  [dl for _, _, dl, _, _ in quints],
                                  traces=[tid for *_, tid, _ in quints],
                                  t_read=t_read,
                                  metas=[m for *_, m in quints])
                for quints in groups.values()]

    def _predict_isolated(self, ids, tensors, scales, tmap=None):
        """Predict with graceful degradation: on failure, bisect the batch to
        isolate the poison input — sane rows still get answers, only the
        culprit is dead-lettered (log2(n) extra predict calls, worst case)."""
        try:
            return [(ids, self.model.do_predict(tensors, scales=scales))]
        except Exception as e:  # noqa: BLE001 — device/input failure
            return self._bisect_halves(ids, tensors, scales, e, tmap=tmap)

    def _bisect_halves(self, ids, tensors, scales, exc: BaseException,
                       tmap=None):
        """The bisect step shared by `_predict_isolated` and the write
        stage's readback-failure fallback: a single poisoned row is
        quarantined; a larger batch recurses on its halves.  ``tmap``
        (rid -> trace_id) keeps quarantine spans correlatable."""
        if len(ids) == 1:
            self._quarantine(ids[0], "predict", exc,
                             trace_id=(tmap or {}).get(ids[0]))
            return []
        mid = len(ids) // 2
        lo = self._predict_isolated(
            ids[:mid], tensors[:mid],
            None if scales is None else scales[:mid], tmap=tmap)
        hi = self._predict_isolated(
            ids[mid:], tensors[mid:],
            None if scales is None else scales[mid:], tmap=tmap)
        return lo + hi

    # -- async device pipeline (PR 3 tentpole) --------------------------------
    def _dispatch_batch(self, tensors, scales) -> _ResultHandle:
        """Dispatch one batch to the device WITHOUT blocking on the host
        readback (`InferenceModel.dispatch`): the write worker calls
        `.result()` downstream, so device compute overlaps both the next
        batch's preprocessing and the previous batch's result writes.

        A customized ``do_predict`` — instance-patched (chaos tests wrap it)
        OR overridden on a subclass (user shims) — must stay on the hot
        path unless the subclass customized ``dispatch`` alongside it, and
        bridge models may lack ``dispatch`` entirely: all of those fall
        back to a lazy synchronous call whose work (and failure) surfaces
        at `.result()` on the write stage."""
        model = self.model
        custom_predict = (
            "do_predict" in vars(model)
            or getattr(type(model), "do_predict", None)
            is not InferenceModel.do_predict)
        custom_dispatch = (
            "dispatch" in vars(model)
            or getattr(type(model), "dispatch", None)
            is not InferenceModel.dispatch)
        if not hasattr(model, "dispatch") or \
                (custom_predict and not custom_dispatch):
            return _LazyResult(
                lambda: model.do_predict(tensors, scales=scales))
        try:
            return model.dispatch(tensors, scales=scales)
        except Exception as e:  # noqa: BLE001 — trace/shape error at dispatch
            return _FailedDispatch(e)

    def _predict_stage(self, ids, tensors, scales=None, deadlines=None,
                       traces=None, t_read=None,
                       t_ready=None, metas=None) -> Optional[_InFlight]:
        """Deadline gate 2 + async dispatch.  Returns the in-flight handle
        for the write stage, or None when every record was shed."""
        # per-row tenant identity hoisted at preprocess rides the metas;
        # it must survive the gate-2 filter aligned with ids
        tenants = [m.get("_tenant") if isinstance(m, dict) else None
                   for m in (metas or [None] * len(ids))]
        # second deadline gate: a record can expire while staged behind a
        # slow predict — shed it here so the batch never wastes device time
        # on rows nobody is waiting for
        if deadlines is not None and any(d is not None for d in deadlines):
            keep = [i for i, (rid, dl) in enumerate(zip(ids, deadlines))
                    if not self._shed_expired(
                        rid, None, deadline_ns=dl, stage="stage_wait",
                        trace_id=traces[i] if traces else None,
                        tenant=tenants[i])]
            if not keep:
                return None
            if len(keep) < len(ids):
                ids = [ids[i] for i in keep]
                tensors = tensors[keep]
                if scales is not None:
                    scales = scales[keep]
                if traces is not None:
                    traces = [traces[i] for i in keep]
                tenants = [tenants[i] for i in keep]
        t0 = time.monotonic()
        if t_ready is not None:
            self._stages["stage_wait"].record(t0 - t_ready)
            for rid, tid in zip(ids, traces or [None] * len(ids)):
                self._span("stage_wait", t_ready, t0,
                                 trace_id=tid, uri=rid)
        handle = self._dispatch_batch(tensors, scales)
        return _InFlight(ids, tensors, scales, handle, traces, t_read, t0,
                         tenants)

    def _write_stage(self, inflight: _InFlight) -> int:
        """Block on the dispatched batch's host readback, postprocess per
        record, and flush the whole micro-batch of results in one batched
        write.  A readback failure falls straight into the bisect halves
        (the full batch was already tried once by the dispatch), preserving
        the log2(n) poison-isolation cost."""
        ids, tensors, scales = inflight.ids, inflight.tensors, inflight.scales
        tmap = dict(zip(ids, inflight.traces or []))
        tenmap = dict(zip(ids, inflight.tenants or []))
        try:
            chunks = [(ids, inflight.handle.result())]
        except Exception as e:  # noqa: BLE001 — device/input failure
            chunks = self._bisect_halves(ids, tensors, scales, e, tmap=tmap)
        t_done = time.monotonic()
        predict_wall = t_done - inflight.t_dispatch
        self._stages["predict"].record(predict_wall)
        self._note_predict_time(predict_wall)
        # device-second attribution (PR 19): the batch's measured dispatch
        # wall time is apportioned by row count over the rows that were
        # ACTUALLY dispatched — quarantined rows still burned the device,
        # so their tenant is still charged (conservation: Σ == wall)
        rows_by_tenant: Dict[Optional[str], int] = {}
        for rid in ids:
            ten = tenmap.get(rid)
            rows_by_tenant[ten] = rows_by_tenant.get(ten, 0) + 1
        self.meter.device_seconds(rows_by_tenant, predict_wall)
        pairs: List[Tuple[str, Dict]] = []
        for chunk_ids, probs in chunks:
            for rid, row in zip(chunk_ids, probs):
                ten = tenmap.get(rid)
                self._span("predict", inflight.t_dispatch, t_done,
                                 trace_id=tmap.get(rid), uri=rid,
                                 attrs=({"tenant": ten} if ten else None))
                try:
                    value = {"value": self.postprocess(np.asarray(row))}
                    if ten is not None:
                        # attribution rides the result doc so the gateway's
                        # result_poll span can tag the tenant without a
                        # side-channel lookup
                        value["tenant"] = ten
                    if self.model_version is not None:
                        # version identity (PR 16): clients can tell WHICH
                        # published version answered — mid-rollout, a
                        # mixed-version fleet answers with a mixed stream
                        value["model_version"] = self.model_version
                    if tmap.get(rid) is not None:
                        # PR 13: the trace rides the SUCCESS result too
                        # (error markers and generation finishes already
                        # carried it) — the gateway's result_poll span and
                        # the LB's lb_result span join the trace through
                        # it, closing the client-facing end of the
                        # reconstructed timeline
                        value["trace_id"] = tmap[rid]
                    deliveries = self._redelivered.pop(rid, None)
                    if deliveries:
                        # at-least-once made visible: the client can tell a
                        # failover-recovered result from a first delivery
                        value["deliveries"] = deliveries
                    pairs.append((rid, value))
                except Exception as e:  # noqa: BLE001 — per-record isolation
                    self._quarantine(rid, "postprocess", e,
                                     trace_id=tmap.get(rid), tenant=ten)
        n = self._flush_results(pairs, tmap=tmap, tenmap=tenmap)
        now = time.monotonic()
        if pairs:
            self._stages["write"].record(now - t_done)
            for rid, _ in pairs:
                self._span("write", t_done, now,
                                 trace_id=tmap.get(rid), uri=rid)
        if n and inflight.t_read is not None:
            self._e2e.record(now - inflight.t_read, n=n)
            # SLO attribution (PR 13): per-record stage decomposition —
            # queue_wait (folded in by _slo_observe), host pipeline
            # (preprocess + stage wait), device predict, result write
            t_read = inflight.t_read
            e2e_by_tenant: Dict[Optional[str], List[float]] = {}
            for rid, _ in pairs:
                ten = tenmap.get(rid)
                e2e = self._slo_observe(rid, now - t_read, {
                    "pipeline": max(inflight.t_dispatch - t_read, 0.0),
                    "predict": max(t_done - inflight.t_dispatch, 0.0),
                    "write": max(now - t_done, 0.0)},
                    tenant=ten)
                e2e_by_tenant.setdefault(ten, []).append(e2e)
            for ten, vals in e2e_by_tenant.items():
                self.meter.request_seconds_many(ten, vals)
        if n and self._cold_start_s is None:
            # construction-to-serving-capable, the number the autoscaler's
            # actuation lag is made of.  Stamped by whichever comes first:
            # the first result written (a backlog was waiting — the bench's
            # spawn-to-first-result) or warm-up completion (an idle boot
            # must not count time spent waiting for traffic as cold start)
            self._cold_start_s = now - self._t_construct
            self._g_cold.set(self._cold_start_s)
        self.total_records += n
        dt = max(now - inflight.t_dispatch, 1e-9)
        if self._tb is not None:
            self._tb.add_scalar("Serving Throughput", n / dt,
                                self.total_records)
            self._tb.add_scalar("Total Records Number", self.total_records,
                                self.total_records)
        self._maybe_trim()
        return n

    def _maybe_trim(self):
        """Amortized memory guard: the XTRIM analog used to cost one backend
        round-trip per micro-batch; now it runs at most once per
        ``trim_interval_s`` (<= 0 restores the every-batch behaviour)."""
        interval = self.params.trim_interval_s
        if interval > 0:
            now = time.monotonic()
            if now - self._last_trim < interval:
                return
            self._last_trim = now
        self.queue.trim(self.params.stream_max_len)

    def _predict_and_write(self, ids, tensors, scales=None,
                           deadlines=None, traces=None, t_read=None,
                           t_ready=None, metas=None) -> int:
        """Synchronous predict+write for one staged group (serve_once and
        the write-stage fallbacks); the pipelined loop runs the same two
        stages on separate workers."""
        inflight = self._predict_stage(ids, tensors, scales=scales,
                                       deadlines=deadlines, traces=traces,
                                       t_read=t_read, t_ready=t_ready,
                                       metas=metas)
        if inflight is None:
            return 0
        return self._write_stage(inflight)

    # -- one micro-batch (synchronous path, used by tests/clients) -----------
    def serve_once(self) -> int:
        staged = self._read_and_preprocess()
        if self._batcher is not None:
            # generation mode: run the scheduler to quiescence — reads one
            # micro-batch, then steps until every admitted request reached
            # a terminal state (tests and embedded callers)
            for group in staged or ():
                self._submit_group(group)
            before = self.total_records
            while not self._batcher.idle and not self._stop.is_set():
                self._gen_tick()
            return self.total_records - before
        if not staged:
            return 0
        return sum(self._predict_and_write(*group) for group in staged)

    # -- lifecycle (cluster-serving-start/stop scripts parity) ----------------
    def start(self):
        """Pipelined loop, three supervised stages (PR 3 data plane):

        - ``serving-preprocess`` reads coalesced micro-batches and fans the
          per-record decode across the preprocess pool;
        - ``serving-predict`` gates deadlines and DISPATCHES batches to the
          device without blocking on readback (up to ``inflight_batches``
          in flight);
        - ``serving-write`` blocks on each readback, postprocesses, and
          flushes results in one batched write per micro-batch.

        Host preprocess, device compute, and result writing all overlap; the
        two bounded hand-off buffers give natural backpressure when a
        downstream stage falls behind.

        All workers run SUPERVISED (PR 1): an escaping exception no longer
        kills the loop silently — it is logged, the worker restarts with
        backoff up to `params.max_worker_restarts`, and `health()` reports
        state/restarts/last error."""
        import queue as _q
        p = self.params
        self._stop.clear()
        self._draining.clear()
        self._retiring.clear()
        self._t_start = time.monotonic()
        try:
            # a prior drained shutdown closed admission; serving again means
            # taking traffic again
            self.queue.open_admission()
        except Exception:  # noqa: BLE001 — backend down: workers will report
            pass
        # bind the probe server FIRST: a port conflict must fail start()
        # before any worker thread begins consuming the queue
        if p.http_port is not None and self._http is None:
            from analytics_zoo_tpu.serving.http import HealthServer
            self._http = HealthServer(self, host=p.http_host,
                                      port=p.http_port).start()
        # zero cold start (PR 11): persistent compile cache + AOT warm-up.
        # The warm-up runs on its own thread so the pipeline serves (and
        # compiles lazily) meanwhile; /readyz reports `warming` with
        # per-program progress until the set is compiled, so the front
        # door routes around a still-cold replica instead of eating its
        # compile latency.
        if p.compile_cache_dir and p.compile_cache_dir != "off":
            from analytics_zoo_tpu.inference import aot
            aot.enable_persistent_cache(p.compile_cache_dir)
        if p.warmup and isinstance(self.model, InferenceModel):
            self._start_warmup()
        self._staged = _q.Queue(maxsize=p.pipeline_depth)
        # dispatch() takes no semaphore, so the engine is what bounds
        # device-resident batches: the handle queue holds `inflight`, plus
        # one mid-readback in the writer and one held by the predict worker
        # awaiting a slot — `inflight + 2` total.  Clamp the queue to the
        # model's supported_concurrent_num so that total never exceeds the
        # model's contract + 2 (the README sizing guidance)
        inflight = max(1, p.inflight_batches)
        model_cap = getattr(self.model, "concurrent_num", None)
        if model_cap is not None and inflight > model_cap:
            logger.warning(
                "serving: inflight_batches=%d exceeds the model's "
                "supported_concurrent_num=%d; clamping the handle queue "
                "(up to %d batches stay transiently resident)",
                inflight, model_cap, model_cap + 2)
            inflight = max(1, model_cap)
        self._writeq = _q.Queue(maxsize=inflight)
        self._last_trim = time.monotonic()
        self._pre_sup = SupervisedThread(
            self._pre_loop, name="serving-preprocess",
            max_restarts=p.max_worker_restarts,
            backoff_s=p.worker_backoff_s, stop_event=self._stop)
        if self._batcher is not None:
            # continuous batching (PR 12): ONE generate worker owns both
            # decode stepping and result writing — results must flush AT
            # step boundaries (a finished request unblocks its client
            # immediately), so splitting the stages would only add a
            # hand-off queue between two things that must stay in lockstep
            self._predict_sup = SupervisedThread(
                self._generate_loop, name="serving-generate",
                max_restarts=p.max_worker_restarts,
                backoff_s=p.worker_backoff_s, stop_event=self._stop)
            self._write_sup = None
        else:
            self._predict_sup = SupervisedThread(
                self._predict_loop, name="serving-predict",
                max_restarts=p.max_worker_restarts,
                backoff_s=p.worker_backoff_s, stop_event=self._stop)
            self._write_sup = SupervisedThread(
                self._write_loop, name="serving-write",
                max_restarts=p.max_worker_restarts,
                backoff_s=p.worker_backoff_s, stop_event=self._stop)
        self._pre_sup.start()
        self._predict_sup.start()
        if self._write_sup is not None:
            self._write_sup.start()
        self._event("start", mode=("generation" if self._batcher is not None
                                   else "predict"),
                    max_batch=p.max_batch or p.batch_size,
                    quantized_bits=self._quantized_bits() or None)
        # compat aliases: the raw threads, for callers that poked at them
        self._pre_thread = self._pre_sup._thread
        self._thread = self._predict_sup._thread
        return self

    # -- AOT warm-up (PR 11 zero cold start) ---------------------------------
    def _start_warmup(self) -> None:
        """Derive the warm-up manifest and compile it on a daemon thread.
        An underivable manifest (no declared input shape and no spec) is a
        warning, not a failed start — the deployment just stays on the
        lazy-compile path it had before PR 11."""
        from analytics_zoo_tpu.inference import aot
        p = self.params
        try:
            if self._batcher is not None:
                # continuous batching (PR 12): the warm-up set is the
                # scheduler's (prefill-bucket x decode-step) program set,
                # so a warm replica serves its first TOKEN with zero
                # compiles
                manifest = self._batcher.warmup_manifest()
            else:
                manifest = aot.resolve_manifest(self.model, p.warmup)
        except Exception as e:  # noqa: BLE001 — stay on the lazy path
            logger.warning(
                "serving: warm-up disabled — manifest underivable (%s: "
                "%s); pass warmup={'shape': [...]} in params",
                type(e).__name__, e)
            self._warm_state.update(state="off", error=str(e))
            return
        # `pending` BEFORE the thread runs: a /readyz scraped between
        # start() and the first compile must already say warming
        self._warm_state.update(state="pending", total=len(manifest),
                                compiled=0, failed=0, seconds=None)
        self._event("warmup", state="pending", total=len(manifest))
        self._warm_thread = threading.Thread(
            target=self._warmup_loop, args=(manifest,),
            name="serving-warmup", daemon=True)
        self._warm_thread.start()

    def _warmup_loop(self, manifest) -> None:
        from analytics_zoo_tpu.inference import aot
        self._warm_state["state"] = "warming"
        self._event("warmup", state="warming",
                    total=self._warm_state.get("total"))
        # fault point (PR 16): an armed warmup_crash kills the PROCESS
        # here — a real crash mid-warm-up, exercising the supervisor's
        # respawn-at-assigned-version path, not the exception handler below
        self._faults.check_warmup()

        def progress(done, total, entry):
            self._warm_state["compiled"] = done

        try:
            if self._batcher is not None:
                stats = self._batcher.warm(manifest, progress=progress,
                                           stop=self._stop.is_set)
            else:
                stats = aot.warm_up(self.model, manifest, progress=progress,
                                    stop=self._stop.is_set)
        except Exception as e:  # noqa: BLE001 — a warm-up crash must not
            # block readiness forever; the lazy path still serves
            logger.exception("serving: warm-up pass failed")
            self._warm_state.update(state="failed", error=str(e))
            self._event("warmup", state="failed", error=str(e)[:200])
            return
        if stats.get("stopped"):
            self._warm_state.update(state="cancelled")
            return
        self._warm_state.update(
            state="ready" if not stats["failed"] else "degraded",
            failed=stats["failed"], seconds=stats["seconds"],
            compile_stats=stats["compile_stats"])
        self._event("warmup",
                    state="ready" if not stats["failed"] else "degraded",
                    programs=stats["programs"], failed=stats["failed"],
                    seconds=stats["seconds"])
        self._g_warm.labels(phase="compile").set(float(stats["seconds"]))
        if self._cold_start_s is None:
            # serving-capable without having seen traffic yet: the replica
            # is warm — the clock stops here, not at the first record
            self._cold_start_s = time.monotonic() - self._t_construct
            self._g_cold.set(self._cold_start_s)
        logger.info(
            "serving: replica %s warm — %d/%d program(s) in %.2fs (%s "
            "backend compile(s), %s persistent-cache hit(s))",
            self.replica_id, stats["programs"] - stats["failed"],
            stats["programs"], stats["seconds"],
            stats["compile_stats"]["cache_misses"],
            stats["compile_stats"]["cache_hits"])

    def warmup_state(self) -> Dict:
        """Warm-up progress document (health doc / readyz / manager
        status surface)."""
        return dict(self._warm_state)

    def _pre_loop(self):
        sup = self._pre_sup
        while not self._stop.is_set():
            if sup is not None:
                sup.heartbeat()
            staged = self._read_and_preprocess()
            if not staged:
                # None = stream empty; [] = batch read but fully shed/
                # quarantined — only the former may end a drain, and only
                # when the backend is actually reachable: an outage ALSO
                # reads as an empty batch, but its backlog is still out
                # there, so keep polling until it heals or the drain budget
                # hard-stops us
                if staged is None and self._draining.is_set():
                    try:
                        if self.queue.read_path_healthy():
                            return     # drain: stream empty, clean exit
                    except Exception:  # noqa: BLE001 — state unknown
                        pass
                time.sleep(0.005)
                continue
            for group in staged:
                while not self._stop.is_set():
                    try:
                        self._staged.put(group, timeout=0.1)
                        break
                    except _FULL:
                        # buffer full: backpressure.  Still alive — stamp
                        # the heartbeat so a saturated replica doesn't read
                        # as dead to the autoscaler's stale-replica check
                        self._hb_ts = time.monotonic()
                        continue

    def _predict_loop(self):
        import queue as _q
        sup = self._predict_sup
        while not self._stop.is_set():
            if sup is not None:
                sup.heartbeat()
            try:
                group = self._staged.get(timeout=0.1)
            except _q.Empty:
                # drain exit: ONLY once the pre worker is dead AND the buffer
                # is (still) empty — is_alive first, so a group staged just
                # before the pre worker exited is seen by the empty() check
                if self._draining.is_set() and self._pre_sup is not None \
                        and not self._pre_sup.is_alive() \
                        and self._staged.empty():
                    return             # drain: upstream done + buffer empty
                continue
            inflight = self._predict_stage(*group)
            if inflight is None:
                continue               # whole group shed at gate 2
            while not self._stop.is_set():
                try:
                    self._writeq.put(inflight, timeout=0.1)
                    break
                except _FULL:
                    continue           # device pipeline full: backpressure

    def _write_loop(self):
        import queue as _q
        sup = self._write_sup
        while not self._stop.is_set():
            if sup is not None:
                sup.heartbeat()
            try:
                inflight = self._writeq.get(timeout=0.1)
            except _q.Empty:
                # drain exit mirrors _predict_loop: predict worker dead AND
                # nothing left in flight
                if self._draining.is_set() and self._predict_sup is not None \
                        and not self._predict_sup.is_alive() \
                        and self._writeq.empty():
                    return             # drain: upstream done + buffer empty
                continue
            self._write_stage(inflight)

    # -- continuous batching (PR 12 tentpole) ---------------------------------
    def _submit_group(self, group: _Staged) -> None:
        """Unpack one staged micro-batch into per-record generation
        requests and feed them to the scheduler.  The waiting room is
        bounded: when full, the generate loop keeps stepping (finishing
        requests frees it) instead of dropping records."""
        from analytics_zoo_tpu.serving.generate import GenRequest
        tensors = group.tensors
        if group.scales is not None:
            # int8-wire prompts: dequantize on host — token ids survive
            # the round-trip exactly when the producer quantized ids
            tensors = tensors.astype(np.float32) \
                * np.asarray(group.scales)[:, None]
        metas = group.metas or [None] * len(group.ids)
        traces = group.traces or [None] * len(group.ids)
        deadlines = group.deadlines or [None] * len(group.ids)
        for i, rid in enumerate(group.ids):
            meta = metas[i] if isinstance(metas[i], dict) else {}
            mt = meta.get("max_tokens")
            try:
                mt = None if mt is None else int(mt)
            except (TypeError, ValueError):
                mt = None
            if self._brownout is not None:
                # brownout stage 2 (PR 17): clamp generation length for
                # non-interactive traffic — lower-only, never a raise
                clamp = self._brownout.clamp_max_tokens(
                    meta.get("_priority", "batch"))
                if clamp is not None:
                    mt = clamp if mt is None else min(mt, clamp)
            resume = meta.get("_resume")
            rtoks, epoch = None, 0
            if isinstance(resume, dict):
                rtoks = resume.get("tokens") or None
                try:
                    epoch = int(resume.get("epoch") or 0)
                except (TypeError, ValueError):
                    epoch = 0
            req = GenRequest(rid, np.asarray(tensors[i]),
                             deadline_ns=deadlines[i],
                             trace_id=traces[i], t_read=group.t_read,
                             max_tokens=mt, tenant=meta.get("_tenant"),
                             resume_tokens=rtoks, epoch=epoch)
            if self.snapshot_path is not None \
                    and (self._gen_params.checkpoint_interval or 0) > 0:
                # ownership + resume state travel together (PR 20): the
                # lease annotation points the NEXT owner at this
                # replica's snapshot spool under this epoch
                try:
                    self.queue.annotate(rid, {
                        "spool": self.snapshot_path,
                        "epoch": epoch,
                        "replica": self.replica_id})
                except Exception:  # noqa: BLE001 — best-effort: a lost
                    pass           # annotation degrades to restart-from-0
            while not self._batcher.submit(req):
                if self._stop.is_set():
                    return
                # full boundary bookkeeping (not a bare step): tokens
                # emitted while the waiting room blocks are still charged
                # to their tenants at the step boundary
                self._gen_tick()

    def _gen_tick(self) -> None:
        """One decode-step boundary + its bookkeeping (stage timer,
        decode-step counter, tokens/sec window, per-boundary decode
        spans)."""
        b = self._batcher
        t0 = time.monotonic()
        events = b.step()
        now = time.monotonic()
        if b.active or events:
            self._stages["predict"].record(now - t0)
        # per-boundary decode spans (PR 13): one span per request per
        # boundary, carrying tokens-emitted — the spans TTFT decomposes
        # into (prefill -> first boundary -> ...).  This is the per-token
        # span volume trace_sample exists to govern; the span wrapper
        # applies the same head-sampling verdict fleet-wide.
        rows_by_tenant: Dict[Optional[str], int] = {}
        for rid, tid, emitted, ten in b.last_boundary:
            attrs = {"tokens": emitted}
            if ten is not None:
                attrs["tenant"] = ten
            self._span("decode", t0, now, trace_id=tid, uri=rid,
                       attrs=attrs)
            # generation tokens are charged per tenant at each step
            # boundary (PR 19) — not at finish, so a long generation
            # bills as it burns and a mid-flight shed stays charged
            self.meter.tokens(ten, emitted)
            rows_by_tenant[ten] = rows_by_tenant.get(ten, 0) + 1
        # step wall time apportioned by slot occupancy at this boundary —
        # the generation plane's device-seconds attribution
        self.meter.device_seconds(rows_by_tenant, now - t0)
        steps = b.decode_steps
        if steps > self._last_steps:
            self._m_decode_steps.inc(steps - self._last_steps)
            self._last_steps = steps
        self._update_tps(now)
        # generation continuity (PR 20): spool this boundary's
        # checkpoints BEFORE the crash fault below, so an injected
        # mid-decode kill dies with its resume state already durable —
        # the same ordering a real preemption depends on
        self._maybe_checkpoint()
        if b.resumed > self._last_resumed:
            self._m_resumed.inc(b.resumed - self._last_resumed)
            self._last_resumed = b.resumed
        if self._faults.decode_crash_active \
                and self._faults.take_decode_crash(b.generated_tokens):
            logger.error(
                "faults: injected decode_crash_after_n_tokens (%d "
                "generated) — exiting", b.generated_tokens)
            os._exit(3)
        kinds = [ev.kind for ev in events]
        if any(k in ("finish", "shed", "quarantine") for k in kinds) or \
                b.last_admitted:
            # scheduler-boundary event (PR 15): recorded only when the
            # slot population changed — per-quantum decode churn would
            # otherwise dominate the ring without adding forensic signal
            self._event("gen_boundary", active=b.active,
                        waiting=b.waiting,
                        admitted=b.last_admitted,
                        finished=kinds.count("finish"),
                        shed=kinds.count("shed"),
                        quarantined=kinds.count("quarantine"))
        self._handle_gen_events(events)

    def _maybe_checkpoint(self) -> None:
        """Drain the scheduler's queued resume snapshots into the
        per-replica gensnap spool (the tracecollect rotation/clock
        contract), stamping each with its integrity checksum — which the
        armed ``snapshot_corrupt`` fault deliberately breaks, so the
        resume path's verification is provable.  Engines without a wired
        ``snapshot_path`` (the manager sets it next to the pidfile)
        discard the drained batch: checkpointing is durable-or-off,
        never silently buffered."""
        b = self._batcher
        if not b.pending_checkpoints:
            return
        snaps = b.drain_checkpoints()
        if self.snapshot_path is None:
            return
        from analytics_zoo_tpu.serving import tracecollect
        corrupt = self._faults.snapshot_corrupt_active
        for rec in snaps:
            crc = tracecollect.snapshot_checksum(rec)
            if corrupt:
                crc ^= 0x5A5A5A5A
            rec["crc"] = crc
        try:
            tracecollect.append_snapshots(self.snapshot_path, snaps,
                                          source=self.replica_id)
            size = 0
            for path in (self.snapshot_path, self.snapshot_path + ".1"):
                try:
                    size += os.path.getsize(path)
                except OSError:
                    pass
            b.snapshot_bytes = size
            self._event("gen_checkpoint", count=len(snaps),
                        tokens=sum(int(r.get("n") or 0) for r in snaps),
                        spool_bytes=size)
        except Exception as e:  # noqa: BLE001 — a full/readonly disk
            # must not take decode down; resume degrades to older
            # snapshots (or restart-from-0), both loud on the other side
            logger.warning("serving: checkpoint spool write failed "
                           "(%s: %s)", type(e).__name__, e)

    def _update_tps(self, now: float) -> None:
        """Roll the tokens/sec rate window.  Called from every generate
        loop iteration — including idle ones, so the gauge decays to 0
        when traffic stops instead of freezing at the last burst's
        rate."""
        wt0, wtok = self._tps_window
        if now - wt0 >= 1.0:
            self._g_tps.set((self._batcher.generated_tokens - wtok)
                            / max(now - wt0, 1e-9))
            self._tps_window = (now, self._batcher.generated_tokens)

    def _handle_gen_events(self, events) -> None:
        """Turn scheduler events into the existing record contracts:
        finish -> batched result write + ack (+ e2e/cold-start stamps),
        partial -> best-effort streaming overwrite, shed -> terminal
        deadline marker, quarantine -> dead-letter, first_token -> TTFT."""
        pairs: List[Tuple[str, Dict]] = []
        finals = []
        for ev in events:
            if ev.kind == "first_token":
                if ev.ttft_s is not None:
                    self._m_ttft.record(ev.ttft_s)
                    # prefill span (PR 13): scheduler admission wait +
                    # prefill program, ending at the first token — the
                    # hop between queue_wait and the first decode
                    # boundary in the TTFT decomposition
                    now0 = time.monotonic()
                    self._span("prefill", now0 - ev.ttft_s, now0,
                               trace_id=ev.trace_id, uri=ev.rid)
            elif ev.kind == "partial":
                if self._brownout is not None \
                        and self._brownout.suppress_partials:
                    # brownout stage 1 (PR 17): partials are progress
                    # cosmetics — under SLO burn the write bandwidth
                    # goes to finals; the terminal result still flows
                    continue
                value = {"partial": True, "tokens": ev.tokens,
                         "n": len(ev.tokens)}
                if ev.trace_id is not None:
                    value["trace_id"] = ev.trace_id
                try:
                    # streaming is best-effort: a failed partial write
                    # must not retry-storm or quarantine a LIVE request —
                    # the next interval (or the terminal write)
                    # overwrites.  put_partial (PR 20) refuses to shadow
                    # a terminal: after a resume, the DEAD owner's last
                    # partial may still be in flight from its dying
                    # process, and one lineage must converge on the
                    # resumed terminal.
                    self.queue.put_partial(ev.rid, value)
                except Exception:  # noqa: BLE001
                    pass
            elif ev.kind == "finish":
                value = {"value": {"tokens": ev.tokens,
                                   "length": len(ev.tokens),
                                   "finish_reason": ev.finish_reason}}
                if ev.trace_id is not None:
                    value["trace_id"] = ev.trace_id
                if ev.tenant is not None:
                    value["tenant"] = ev.tenant
                deliveries = self._redelivered.pop(ev.rid, None)
                if deliveries:
                    value["deliveries"] = deliveries
                pairs.append((ev.rid, value))
                finals.append(ev)
                # tokens were already charged per tenant at each step
                # boundary (_gen_tick); nothing to double-count here
            elif ev.kind == "shed":
                # an ACTIVE request's shed event carries its progress:
                # say so ("before predict" would point triage at queueing
                # when the cost was decode time) and keep the tokens ON
                # the marker — the marker overwrites any streamed
                # partial, and default clients never return partials
                if ev.tokens is not None:
                    err = ("deadline-exceeded: budget elapsed "
                           f"mid-generation after {len(ev.tokens)} "
                           "token(s)")
                    extra = {"tokens": ev.tokens, "n": len(ev.tokens)}
                else:
                    err = "deadline-exceeded: budget elapsed before decode"
                    extra = None
                self._shed_terminal(ev.rid, stage="generate",
                                    trace_id=ev.trace_id, error=err,
                                    extra=extra, tenant=ev.tenant)
            elif ev.kind == "quarantine":
                self._quarantine(ev.rid, "generate",
                                 RuntimeError(ev.error or "generation "
                                                          "failed"),
                                 trace_id=ev.trace_id, tenant=ev.tenant)
            elif ev.kind == "resume_failed":
                # scheduler-level downgrade (PR 20): the resume prefix
                # could not be replayed (bare-state model, malformed
                # prefix, capacity) — the request restarts from 0; its
                # prefix is recomputed work, metered as wasted
                wasted = len(ev.tokens or ())
                if wasted:
                    self._m_resume_wasted.inc(wasted)
                self._event("gen_resume_failed", rid=ev.rid,
                            trace_id=ev.trace_id, reason=ev.error,
                            wasted=wasted)
        if not pairs:
            return
        tmap = {ev.rid: ev.trace_id for ev in finals}
        tenmap = {ev.rid: ev.tenant for ev in finals}
        n = self._flush_results(pairs, tmap=tmap, tenmap=tenmap)
        now = time.monotonic()
        for ev in finals:
            self._span("write", now, now, trace_id=ev.trace_id, uri=ev.rid)
            if ev.t_read is not None:
                self._e2e.record(now - ev.t_read)
                # SLO attribution: decode wall vs everything else; the
                # queue-wait measured at claim folds in via _slo_observe
                stages = {}
                if ev.wall_s is not None:
                    stages["decode"] = max(float(ev.wall_s), 0.0)
                e2e = self._slo_observe(ev.rid, now - ev.t_read, stages,
                                        tenant=ev.tenant)
                self.meter.request_seconds(ev.tenant, e2e)
        if n and self._cold_start_s is None:
            self._cold_start_s = now - self._t_construct
            self._g_cold.set(self._cold_start_s)
        self.total_records += n
        self._maybe_trim()

    def _generate_loop(self):
        """The serving-generate worker: slot-map continuous batching
        between preprocess and the result store.  Staged micro-batches are
        unpacked into per-record requests; the scheduler admits them into
        free decode slots at step boundaries, finished requests flush
        immediately, and the loop never busy-spins an idle device (empty
        scheduler -> blocking read on the staged queue)."""
        import queue as _q
        sup = self._predict_sup
        b = self._batcher
        while not self._stop.is_set():
            if sup is not None:
                sup.heartbeat()
            # idle scheduler: block briefly for new work; busy: only sweep
            # what is already staged, then take the next decode step
            try:
                if b.idle:
                    group = self._staged.get(timeout=0.1)
                else:
                    group = self._staged.get_nowait()
            except _q.Empty:
                group = None
            if group is not None:
                self._submit_group(group)
                while True:
                    try:
                        self._submit_group(self._staged.get_nowait())
                    except _q.Empty:
                        break
            if b.idle:
                self._update_tps(time.monotonic())
                if self._draining.is_set() and self._pre_sup is not None \
                        and not self._pre_sup.is_alive() \
                        and self._staged.empty():
                    return             # drain: upstream done + slots empty
                continue
            self._gen_tick()

    def stage_metrics(self) -> Dict:
        """Per-stage timing document (PR 3): read / preprocess / stage_wait /
        predict (dispatch -> host readback done) / write counters with
        p50/p99 over recent samples, plus ``e2e`` — per-record latency from
        read_batch return to result written."""
        doc = {name: st.snapshot() for name, st in self._stages.items()}
        doc["e2e"] = self._e2e.snapshot()
        return doc

    def _quantized_bits(self) -> int:
        """0 float, 8 W8A8, 4 W4A16 — what the loaded model serves with.
        Fixed after construction, so computed once and cached: health()
        backs the /healthz poll loops and must not re-flatten a large
        params tree per scrape."""
        if self._qbits is None:
            try:
                from analytics_zoo_tpu.inference.quantize import (
                    quantized_bits)
                self._qbits = quantized_bits(
                    getattr(self.model, "_params", None) or {})
            except Exception:  # noqa: BLE001 — bridge models, exotic params
                self._qbits = 0
        return self._qbits

    def _resources_doc(self) -> Dict:
        """The health-doc ``resources`` block (never raises — a probe
        must answer even when a component read fails mid-reload)."""
        try:
            return self._ledger.doc()
        except Exception as e:  # noqa: BLE001
            return {"error": f"{type(e).__name__}: {e}"}

    @staticmethod
    def _process_doc() -> Dict:
        from analytics_zoo_tpu.common.observability import process_stats
        try:
            return process_stats()
        except Exception:  # noqa: BLE001
            return {}

    def drain_usage(self) -> List[Dict]:
        """Per-interval usage deltas since the last drain (PR 19) — the
        manager's 1 s loop appends them to the per-replica usage journal
        next to the span/event spools."""
        return self.meter.drain()

    def health(self) -> Dict:
        """Serving health surface (manager `status` / ops, `/healthz`):
        worker states, restart counts, breaker state, record/dead-letter/
        shed counters, per-stage timing, queue health, and the readiness
        verdict — the one document every surface (health.json snapshot,
        health CLI, HTTP probes) serves."""
        workers = {}
        for sup in (self._pre_sup, self._predict_sup, self._write_sup):
            if sup is not None:
                workers[sup.name] = sup.health()
        running = bool(workers) and all(
            w["state"] in (SupervisedThread.STARTING,
                           SupervisedThread.RUNNING,
                           SupervisedThread.RESTARTING)
            for w in workers.values())
        try:
            queue_health = self.queue.health()
        except Exception as e:  # noqa: BLE001 — backend down ≠ probe down
            queue_health = {"backend": type(self.queue).__name__,
                            "reachable": False,
                            "error": f"{type(e).__name__}: {e}"}
        h = {"running": running,
             "draining": self._draining.is_set(),
             # staleness/restart detection (PR 4): a monotonically
             # increasing sequence lets orchestrators spot a frozen
             # snapshot file; pid + uptime reset on a silent restart
             "uptime_s": round(time.monotonic() - self._t_start, 3),
             "pid": os.getpid(),
             "snapshot_seq": next(self._snapshot_seq),
             # wall/monotonic clock pair (PR 13): spans carry monotonic
             # timestamps; the fleet trace collector normalizes each
             # replica's spans onto the wall clock through this pair
             "clock": {"wall": time.time(), "monotonic": time.monotonic()},
             # replica identity + failover counters (PR 5)
             "replica_id": self.replica_id,
             # version identity (PR 16): the registry version this replica
             # serves — None when unversioned.  Fleet aggregation reports
             # the version MIX across replicas (normal mid-rollout); the
             # canary judge compares replicas by it.
             "model_version": self.model_version,
             "heartbeat_age_s": round(self._heartbeat_age(), 3),
             "reclaimed": self.reclaimed,
             "duplicates": self.duplicates,
             "total_records": self.total_records,
             "dead_lettered": self.dead_lettered,
             "shed": self.shed,
             # zero cold start (PR 11): warm-up progress + the replica's
             # measured spawn-to-first-result — these ride the health doc
             # into fleet aggregation and FleetSignals
             "warmup": self.warmup_state(),
             "cold_start_s": (None if self._cold_start_s is None
                              else round(self._cold_start_s, 3)),
             # fused-dequant quantized predict (PR 14): what the model
             # serves with — 0 float, 8 int8 (W8A8), 4 int4 (W4A16)
             "quantized_bits": self._quantized_bits(),
             # resource accounting (PR 15): HBM decomposition (weights /
             # kv_state / executables + per-program exec counts) and the
             # per-process resource read — fleet-aggregated by
             # serving/fleet.py, scrapeable as serving_hbm_bytes /
             # process_* gauges
             "resources": self._resources_doc(),
             "process": self._process_doc(),
             # flight-recorder ring pressure (PR 15): a dropped count
             # means the ring is too small for the drain period
             "recorder": self.recorder.stats(),
             "breaker": self._breaker.health(),
             "dead_letter_breaker": self._dead_breaker.health(),
             # live data-plane knob targets (PR 10): the autoscaler's
             # fleet aggregation reads the fast tier's position from here
             "knobs": self.knobs(),
             "workers": workers,
             "stages": self.stage_metrics(),
             "queue": queue_health}
        if self._batcher is not None:
            # continuous batching (PR 12): slot occupancy + token counters
            # ride the health doc into fleet aggregation
            h["generation"] = self._batcher.stats()
        if self._slo is not None:
            # SLO attribution (PR 13): objective + windowed burn rate ride
            # the health doc so fleet aggregation / FleetSignals can
            # consume them without a separate scrape
            h["slo"] = self._slo.snapshot()
        # usage attribution (PR 19): cumulative per-tenant totals — fleet
        # aggregation sums these across replicas for `manager metrics`
        h["usage"] = self.meter.snapshot()
        if self._admission is not None:
            # overload armor (PR 17): admitted/rejected tallies the fleet
            # aggregation sums, and the per-reason split for triage
            h["admission"] = self._admission.snapshot()
        if self._brownout is not None:
            # the ladder stage (fleet-merged as MAX) + transition history
            # — what incident bundles show for "when did we degrade"
            h["brownout"] = self._brownout.snapshot()
        if self._faults.any_active:
            # fault injection (PR 16): an armed replica must be visible
            # from the outside — never silently chaotic
            h["faults"] = self._faults.describe()
        h["ready"] = self._readiness(h)
        return h

    def _readiness(self, h: Dict) -> Dict:
        """/readyz verdict derived from an already-computed health doc."""
        reasons = []
        if h["draining"]:
            reasons.append("draining")
        if not h["running"]:
            reasons.append("workers-not-running")
        w = h.get("warmup") or {}
        if w.get("state") in ("pending", "warming"):
            # a cold replica must not take routed traffic: every record it
            # claims pays a compile the warm fleet members would not.
            # `failed`/`degraded` do NOT hold readiness — the lazy-compile
            # path still serves, just cold.
            reasons.append(
                f"warming ({w.get('compiled', 0)}/{w.get('total', 0)} "
                f"programs)")
        if h["breaker"]["state"] == CircuitBreaker.OPEN:
            reasons.append("result-write-breaker-open")
        q = h["queue"]
        if not q.get("reachable", True):
            reasons.append("backend-unreachable")
        rb = q.get("read_breaker")
        if rb is not None and rb["state"] == CircuitBreaker.OPEN:
            reasons.append("read-breaker-open")
        cap = self.params.ready_queue_depth
        if cap is None:
            cap = q.get("max_depth")
        depth = q.get("depth", -1)
        if cap is not None and depth >= 0 and depth >= cap:
            reasons.append(f"queue-depth {depth} >= {cap}")
        if self._faults.readyz_active:
            # fault point (PR 16): hold readiness for the configured
            # uptime — exercises the rollout's wait-for-ready timeout
            fr = self._faults.readyz_block_reason(h["uptime_s"])
            if fr:
                reasons.append(fr)
        return {"ready": not reasons, "reasons": reasons}

    def ready(self) -> Dict:
        """Readiness probe document (`/readyz`).  While the AOT warm-up
        set is compiling the verdict is not-ready with a
        ``warming (k/n programs)`` reason, and the progress block rides
        the body so operators see WHY a new replica is not taking traffic
        yet."""
        h = self.health()
        doc = dict(h["ready"])
        if self._warm_state.get("state") != "off":
            doc["warmup"] = {
                k: self._warm_state.get(k)
                for k in ("state", "compiled", "total", "seconds")}
        return doc

    @staticmethod
    def metrics_from_health(h: Dict) -> Dict:
        """The `/metrics` JSON document derived from a health() document —
        shared with `manager metrics`, which only has the snapshot file."""
        e2e = h["stages"]["e2e"]
        doc = {"served": h["total_records"],
               "quarantined": h["dead_lettered"],
               "shed": h["shed"],
               "restarts": sum(w["restart_count"]
                               for w in h["workers"].values()),
               "queue_depth": h["queue"].get("depth", -1),
               "dead_letters": h["queue"].get("dead_letters", -1),
               "breaker_trips": h["breaker"]["trip_count"],
               "stages": h["stages"],
               "latency_ms": {"p50": e2e["p50_ms"], "p99": e2e["p99_ms"]}}
        if isinstance(h.get("admission"), dict):
            doc["admitted"] = h["admission"].get("admitted", 0)
            doc["rejected"] = h["admission"].get("rejected", 0)
        if isinstance(h.get("brownout"), dict):
            doc["brownout_stage"] = h["brownout"].get("stage", 0)
        return doc

    def metrics(self) -> Dict:
        """Flat JSON counters + the per-stage timing breakdown (`/metrics`)
        — byte-compatible with the PR 2/3 document; the Prometheus rendering
        of the same registry lives on `prom_metrics()`."""
        return self.metrics_from_health(self.health())

    def prom_metrics(self) -> str:
        """Prometheus text exposition v0.0.4 of this engine's registry
        (`/metrics?format=prom`)."""
        return self.registry.to_prometheus()

    def export_trace(self, path: str) -> str:
        """Dump the tracer's span ring as Chrome trace-event JSON (open in
        Perfetto / chrome://tracing, or summarize with
        `tools/trace_view.py`)."""
        return self.tracer.export_chrome_trace(path)

    def shutdown(self, drain_s: Optional[float] = None,
                 close_admission: bool = True):
        """Stop serving.  With ``drain_s`` (graceful drain, PR 2): close
        admission on the queue, flip `/readyz` to ``draining`` so probes
        stop routing traffic, let the workers finish the stream backlog and
        flush every staged AND dispatched in-flight batch, then join —
        falling back to a hard stop when the budget runs out.  Without it:
        immediate stop (the PR 1 behaviour).

        ``close_admission=False`` (PR 10) is the SCALE-DOWN drain: this
        replica stops claiming new work and flushes what it holds, but the
        shared queue stays open — N-replica deployments must not have one
        retiring replica cut off ingest for the survivors (the autoscaler
        and ``manager scale N`` retire replicas this way)."""
        if drain_s is None:
            drain_s = 0.0
        self._event("shutdown", drain_s=drain_s,
                    retire=not close_admission)
        sups = (self._pre_sup, self._predict_sup, self._write_sup)
        started = any(s is not None for s in sups)
        if drain_s > 0 and started:
            self._draining.set()
            if close_admission:
                try:
                    self.queue.close_admission()
                except Exception:  # noqa: BLE001 — backend down: drain
                    pass           # anyway
            else:
                self._retiring.set()
            wait_until(lambda: not any(
                s is not None and s.is_alive() for s in sups), drain_s)
        # the compat aliases (_pre_thread/_thread) point at the SAME thread
        # objects the supervisors own — joining the supervisors covers them
        self._stop.set()
        for sup in sups:
            if sup is not None:
                sup.join(timeout=5)
        if self._pre_pool is not None:
            self._pre_pool.shutdown(wait=False)
            self._pre_pool = None
        if self._http is not None:
            self._http.stop()
            self._http = None
        # deregister this engine's callback gauges: a stopped engine must
        # not contribute stale samples to (or be kept alive by) a registry
        # it shares with live engines; idempotent across repeat shutdowns
        for gauge, fn in self._gauge_fns:
            gauge.remove_function(fn)
        self._gauge_fns = []
        # drop this replica's heartbeat series entirely (scale-down): a
        # stopped replica must not linger in the exposition as a frozen or
        # zero "age", which would read as perfectly fresh
        self._hb_gauge.remove(replica=self.replica_id)
        # release cached shm-ring attachments (PR 7): a long-lived engine
        # serving successive shm-lane producers must not hold their
        # (unlinked) segments mapped forever.  close() is view-safe — a
        # mapping with live exported buffers survives the attempt — and a
        # later shm record simply re-attaches by name.
        try:
            _wire.detach_all()
        except Exception:  # noqa: BLE001 — cleanup is best-effort
            pass
        if self._tb is not None:
            self._tb.flush()
