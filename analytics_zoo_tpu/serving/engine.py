"""Cluster Serving engine — queue → batcher → TPU predict → result store.

Reference parity: `ClusterServing.main` (serving/ClusterServing.scala:34-352): a
streaming micro-batch loop reading the Redis stream, batching to `batch_size`,
pre-processing base64 images, broadcast-model predict, top-N post-processing, writing
the result table with back-pressure, XTRIM memory guard, and throughput scalars
(`Serving Throughput`, `Total Records Number`) to TensorBoard.

TPU-native: the "broadcast model" is just the jitted predict function; batching pads to
power-of-two buckets (InferenceModel) so the compile cache stays tiny; the micro-batch
loop is a plain thread, not a Spark Structured Streaming job.
"""

from __future__ import annotations

import base64
import json
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional

import numpy as np

from analytics_zoo_tpu.inference.inference_model import InferenceModel
from analytics_zoo_tpu.serving.queues import BaseQueue


class QuantizedTensor(NamedTuple):
    """A tensor kept in its compact integer dtype until it is ON the
    accelerator (round 5): do_predict transfers the int8/uint8 bytes and
    dequantizes (x * scale) inside the jitted program — 4x less
    host->device traffic than f32, which is the binding constraint when the
    device link (e.g. this environment's axon relay) is the bottleneck."""

    data: np.ndarray      # int8 / uint8
    scale: float


def default_preprocess(record: Dict):
    """base64 bytes -> decoded image float (PreProcessing.scala:1-53), a
    QuantizedTensor for int8-wire / uint8-image records, or raw tensor
    passthrough for `data` records."""
    if "image" in record:
        import cv2
        buf = np.frombuffer(base64.b64decode(record["image"]), np.uint8)
        img = cv2.imdecode(buf, cv2.IMREAD_COLOR)
        if record.get("u8"):
            if "resize" in record:
                h, w = record["resize"]
                img = cv2.resize(img, (w, h))
            return QuantizedTensor(np.asarray(img, np.uint8), 1.0)
        # float path: convert BEFORE resizing (float interpolation), keeping
        # pre-round-5 numerics byte-identical
        img = img.astype(np.float32)
        if "resize" in record:
            h, w = record["resize"]
            img = cv2.resize(img, (w, h))
        return img
    if "b64" in record:
        # raw-bytes tensor (client.enqueue_tensor wire format); explicit
        # little-endian dtype tag so cross-endian pairs stay correct, and a
        # copy so downstream in-place normalization works (frombuffer views
        # are read-only)
        arr = np.frombuffer(base64.b64decode(record["b64"]),
                            np.dtype(record.get("dtype", "<f4")))
        if "shape" in record:
            arr = arr.reshape([int(s) for s in record["shape"]])
        if "scale" in record:       # int8 wire: stay int8 until on device
            return QuantizedTensor(arr.astype(np.int8),
                                   float(record["scale"]))
        return arr.astype(np.float32)
    if "data" in record:
        arr = np.asarray(record["data"], np.float32)
        if "shape" in record:
            arr = arr.reshape(record["shape"])
        return arr
    raise ValueError(f"record has neither image nor data: {list(record)}")


def default_postprocess(probs: np.ndarray, top_n: int = 5) -> List:
    """top-N (class, prob) pairs (PostProcessing.scala:1-117)."""
    idx = np.argsort(-probs)[:top_n]
    return [[int(i), float(probs[i])] for i in idx]


class ServingParams:
    """config.yaml surface (scripts/cluster-serving/config.yaml parity)."""

    def __init__(self, batch_size: int = 4, top_n: int = 5,
                 poll_timeout_s: float = 0.05, stream_max_len: int = 100000,
                 filter_threshold: Optional[float] = None,
                 write_retries: int = 5, write_backoff_s: float = 0.05,
                 pipeline_depth: int = 2):
        self.batch_size = batch_size
        self.top_n = top_n
        self.poll_timeout_s = poll_timeout_s
        self.stream_max_len = stream_max_len
        self.filter_threshold = filter_threshold
        # result-write backpressure (ClusterServing.scala:276-307 analog)
        self.write_retries = write_retries
        self.write_backoff_s = write_backoff_s
        # staged micro-batches between the host preprocess thread and the
        # device predict thread; bounds memory AND provides backpressure
        self.pipeline_depth = pipeline_depth

    @staticmethod
    def from_yaml(path: str) -> "ServingParams":
        import yaml
        with open(path) as f:
            cfg = yaml.safe_load(f) or {}
        params = cfg.get("params", {})
        return ServingParams(
            batch_size=int(params.get("batch_size", 4)),
            top_n=int(params.get("top_n", 5)))


class ClusterServing:
    def __init__(self, model: InferenceModel, queue: BaseQueue,
                 params: Optional[ServingParams] = None,
                 preprocess: Callable = default_preprocess,
                 postprocess: Optional[Callable] = None,
                 tensorboard_dir: Optional[str] = None):
        self.model = model
        self.queue = queue
        self.params = params or ServingParams()
        self.preprocess = preprocess
        self.postprocess = postprocess or (
            lambda p: default_postprocess(p, self.params.top_n))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.total_records = 0
        self._tb = None
        if tensorboard_dir:
            from analytics_zoo_tpu.utils.tbwriter import FileWriter
            self._tb = FileWriter(tensorboard_dir)

    # -- result write with backpressure (ClusterServing.scala:276-307) -------
    def _put_result(self, rid, value):
        backoff = self.params.write_backoff_s
        for attempt in range(self.params.write_retries + 1):
            try:
                self.queue.put_result(rid, value)
                return
            except Exception:
                if attempt == self.params.write_retries:
                    raise
                time.sleep(backoff)
                backoff *= 2           # blocking retry: upstream reads stall

    def _read_and_preprocess(self):
        batch = self.queue.read_batch(self.params.batch_size,
                                      self.params.poll_timeout_s)
        if not batch:
            return None
        ids = [rid for rid, _ in batch]
        items = [self.preprocess(rec) for _, rec in batch]
        if all(isinstance(it, QuantizedTensor) for it in items):
            # compact-dtype batch: ship the int8/uint8 bytes to the device,
            # dequantize there (per-row scales)
            tensors = np.stack([it.data for it in items])
            scales = np.asarray([it.scale for it in items], np.float32)
            return ids, tensors, scales
        # mixed float/quantized batches dequantize the stragglers on host
        tensors = np.stack([
            it.data.astype(np.float32) * it.scale
            if isinstance(it, QuantizedTensor) else it for it in items])
        return ids, tensors, None

    def _predict_and_write(self, ids, tensors, scales=None) -> int:
        t0 = time.time()
        probs = self.model.do_predict(tensors, scales=scales)
        for rid, row in zip(ids, probs):
            self._put_result(rid,
                             {"value": self.postprocess(np.asarray(row))})
        n = len(ids)
        self.total_records += n
        dt = max(time.time() - t0, 1e-9)
        if self._tb is not None:
            self._tb.add_scalar("Serving Throughput", n / dt,
                                self.total_records)
            self._tb.add_scalar("Total Records Number", self.total_records,
                                self.total_records)
        self.queue.trim(self.params.stream_max_len)
        return n

    # -- one micro-batch (synchronous path, used by tests/clients) -----------
    def serve_once(self) -> int:
        staged = self._read_and_preprocess()
        if staged is None:
            return 0
        return self._predict_and_write(*staged)

    # -- lifecycle (cluster-serving-start/stop scripts parity) ----------------
    def start(self):
        """Pipelined loop: a host thread reads+preprocesses micro-batches into
        a bounded buffer while the predict thread runs the device — host
        preprocessing overlaps device compute (round-2 weak #5); the bounded
        buffer gives natural backpressure when predict falls behind."""
        import queue as _q
        self._stop.clear()
        self._staged = _q.Queue(maxsize=self.params.pipeline_depth)
        self._pre_thread = threading.Thread(target=self._pre_loop, daemon=True)
        self._thread = threading.Thread(target=self._predict_loop, daemon=True)
        self._pre_thread.start()
        self._thread.start()
        return self

    def _pre_loop(self):
        while not self._stop.is_set():
            staged = self._read_and_preprocess()
            if staged is None:
                time.sleep(0.005)
                continue
            while not self._stop.is_set():
                try:
                    self._staged.put(staged, timeout=0.1)
                    break
                except Exception:
                    continue           # buffer full: backpressure

    def _predict_loop(self):
        import queue as _q
        while not self._stop.is_set():
            try:
                ids, tensors, scales = self._staged.get(timeout=0.1)
            except _q.Empty:
                continue
            self._predict_and_write(ids, tensors, scales)

    def shutdown(self):
        self._stop.set()
        for t in (getattr(self, "_pre_thread", None), self._thread):
            if t is not None:
                t.join(timeout=5)
        if self._tb is not None:
            self._tb.flush()
