"""Versioned model registry (PR 16) — the publish/resolve half of the
zero-drop rollout subsystem.

Layered on the PR 11 weight store: a ``publish`` snapshots one immutable
version directory

    <registry>/<model>/<version>/
        weights/           # weight store (leaf-*.npy + manifest.json)
        version.json       # fingerprint, quantize spec, warm-up manifest,
                           # publish metadata

plus an atomically-updated ``<registry>/<model>/latest`` pointer file.
Versions are IMMUTABLE once published: republishing the same version with
identical content is an idempotent no-op, republishing it with different
content is an error (a version name must mean one set of bytes, or canary
judging and rollback are meaningless).

Integrity is checked at resolution time (:func:`verify`): the version's
``version.json`` fingerprint must match the weight store's own manifest
fingerprint and every leaf file must exist with its manifest byte size.  A
truncated or corrupted version is rejected LOUDLY before any replica is
retired onto it — the previous version keeps serving.

Everything here is stdlib-only (no jax/numpy import) so the supervisor and
the ``manager publish/versions/rollout`` CLI can use it without touching
the accelerator runtime.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
import time
from typing import Dict, List, Optional

VERSION_META = "version.json"
STORE_SUBDIR = "weights"
LATEST = "latest"
DEFAULT_MODEL = "default"

_VERSION_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")


class RegistryError(RuntimeError):
    """Publish/resolve/verify failure — always carries a human-readable
    reason naming the registry path and version involved."""


def _check_name(kind: str, name: str) -> str:
    if not _VERSION_RE.match(name or ""):
        raise RegistryError(
            f"invalid {kind} name {name!r}: must match "
            f"[A-Za-z0-9][A-Za-z0-9._-]*")
    return name


def model_dir(registry: str, model: str = DEFAULT_MODEL) -> str:
    return os.path.join(registry, _check_name("model", model))


def version_dir(registry: str, version: str,
                model: str = DEFAULT_MODEL) -> str:
    return os.path.join(model_dir(registry, model),
                        _check_name("version", version))


def store_path(registry: str, version: str,
               model: str = DEFAULT_MODEL) -> str:
    """The version's weight-store directory (feed to ``load_store``)."""
    return os.path.join(version_dir(registry, version, model), STORE_SUBDIR)


def read_meta(registry: str, version: str,
              model: str = DEFAULT_MODEL) -> Optional[dict]:
    path = os.path.join(version_dir(registry, version, model), VERSION_META)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _store_fingerprint(store_dir: str) -> Optional[str]:
    try:
        with open(os.path.join(store_dir, "manifest.json")) as f:
            return json.load(f).get("fingerprint")
    except (OSError, ValueError):
        return None


def publish(registry: str, version: str, store_dir: str,
            model: str = DEFAULT_MODEL,
            quantize=None, warmup=None, meta: Optional[dict] = None,
            set_latest_pointer: bool = True) -> dict:
    """Snapshot ``store_dir`` (a PR 11 weight store) into an immutable
    ``<registry>/<model>/<version>/`` and bump the ``latest`` pointer.

    The snapshot is built in a temp dir and ``os.replace``d into place, so
    a reader never sees a half-copied version.  Returns the version.json
    document.
    """
    _check_name("version", version)
    fp = _store_fingerprint(store_dir)
    if fp is None:
        raise RegistryError(
            f"cannot publish {version!r}: {store_dir!r} is not a weight "
            f"store (no readable manifest.json)")
    vdir = version_dir(registry, version, model)
    existing = read_meta(registry, version, model)
    if existing is not None:
        if existing.get("fingerprint") == fp:
            # idempotent republish of identical bytes
            if set_latest_pointer:
                set_latest(registry, version, model)
            return existing
        raise RegistryError(
            f"version {version!r} already published with fingerprint "
            f"{existing.get('fingerprint')!r}; refusing to overwrite with "
            f"{fp!r} — versions are immutable, pick a new name")
    if os.path.isdir(vdir):
        # half-published leftover (no readable version.json): clear it
        shutil.rmtree(vdir, ignore_errors=True)
    mdir = model_dir(registry, model)
    os.makedirs(mdir, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=f".publish-{version}-", dir=mdir)
    try:
        shutil.copytree(store_dir, os.path.join(tmp, STORE_SUBDIR))
        doc = {
            "version": version,
            "model": model,
            "fingerprint": fp,
            "created": time.time(),
            "quantize": quantize,
            "warmup": warmup,
            "meta": meta or {},
        }
        with open(os.path.join(tmp, VERSION_META), "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, vdir)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if set_latest_pointer:
        set_latest(registry, version, model)
    return doc


def set_latest(registry: str, version: str,
               model: str = DEFAULT_MODEL) -> None:
    """Atomically point ``<registry>/<model>/latest`` at ``version``."""
    mdir = model_dir(registry, model)
    os.makedirs(mdir, exist_ok=True)
    path = os.path.join(mdir, LATEST)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(version + "\n")
    os.replace(tmp, path)


def latest(registry: str, model: str = DEFAULT_MODEL) -> Optional[str]:
    try:
        with open(os.path.join(model_dir(registry, model), LATEST)) as f:
            name = f.read().strip()
        return name or None
    except OSError:
        return None


def resolve(registry: str, version: Optional[str] = None,
            model: str = DEFAULT_MODEL) -> str:
    """Pin resolution: an explicit version name wins; ``None``/"latest"
    follow the pointer.  Raises :class:`RegistryError` when the registry
    has nothing to offer."""
    if version in (None, "", LATEST):
        name = latest(registry, model)
        if name is None:
            raise RegistryError(
                f"registry {registry!r} has no published version for "
                f"model {model!r}")
        return name
    if read_meta(registry, version, model) is None:
        raise RegistryError(
            f"version {version!r} not found in registry {registry!r} "
            f"(model {model!r})")
    return version


def versions(registry: str, model: str = DEFAULT_MODEL) -> List[dict]:
    """Every published version's metadata, oldest first, each stamped
    with ``latest: true/false``."""
    mdir = model_dir(registry, model)
    out: List[dict] = []
    try:
        names = sorted(os.listdir(mdir))
    except OSError:
        return out
    cur = latest(registry, model)
    for name in names:
        if name.startswith(".") or name == LATEST:
            continue
        doc = read_meta(registry, name, model)
        if doc is None:
            continue
        doc = dict(doc)
        doc["latest"] = (name == cur)
        out.append(doc)
    out.sort(key=lambda d: d.get("created", 0.0))
    return out


def verify(registry: str, version: str,
           model: str = DEFAULT_MODEL) -> List[str]:
    """Integrity check for one published version; returns a list of
    human-readable problems (empty == healthy).  Checks, in order: the
    version.json is readable, the weight store's own manifest is readable,
    the two fingerprints agree, and every leaf file exists with the exact
    byte size ``np.save`` wrote (header + data) — a truncated leaf is the
    classic partial-copy corruption and must be caught BEFORE a replica is
    retired onto this version."""
    problems: List[str] = []
    doc = read_meta(registry, version, model)
    if doc is None:
        return [f"version {version!r}: no readable {VERSION_META}"]
    sdir = store_path(registry, version, model)
    try:
        with open(os.path.join(sdir, "manifest.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return [f"version {version!r}: weight store manifest unreadable "
                f"({e})"]
    if manifest.get("fingerprint") != doc.get("fingerprint"):
        problems.append(
            f"version {version!r}: store fingerprint "
            f"{manifest.get('fingerprint')!r} != published "
            f"{doc.get('fingerprint')!r}")
    sizes: Dict[str, int] = {}
    for key, meta in (manifest.get("leaves") or {}).items():
        fname = meta.get("file")
        if not fname:
            problems.append(f"version {version!r}: leaf {key!r} has no "
                            f"file entry in the manifest")
            continue
        path = os.path.join(sdir, fname)
        try:
            sizes[fname] = os.path.getsize(path)
        except OSError:
            problems.append(
                f"version {version!r}: leaf file {fname} missing")
            continue
        if sizes[fname] == 0:
            problems.append(
                f"version {version!r}: leaf file {fname} is empty "
                f"(truncated copy?)")
    total = manifest.get("total_bytes")
    if total is not None and sizes and sum(sizes.values()) < int(total):
        problems.append(
            f"version {version!r}: leaf files hold "
            f"{sum(sizes.values())} bytes < manifest total_bytes {total} "
            f"(truncated copy?)")
    return problems
