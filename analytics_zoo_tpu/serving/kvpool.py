"""Host-side KV block-pool allocator + prefix-sharing index (PR 18).

The device side of paged KV lives in ``ops/paged_attention.py`` (pool
buffers + block-table reads) and ``serving/generate.py`` (the compiled
prefill/commit/decode programs).  This module is the HOST side the
scheduler thread drives at admission/free boundaries — plain python, no
device traffic:

- ``BlockPool`` — fixed set of ``block_len``-token block ids with a free
  list and per-block REFCOUNTS.  Block id 0 is the reserved TRASH block:
  table padding and inactive decode rows point at it, so their in-program
  writes land somewhere harmless instead of corrupting live state (the
  device arrays are allocated with ``n_blocks + 1`` rows).  A block frees
  when its last holder (slot or prefix-cache entry) releases it —
  copy-on-write degenerates to pure sharing because SHARED blocks are
  always full prompt-prefix blocks, which are immutable by construction
  (the decode cursor starts past them and never moves backwards).
- ``PrefixIndex`` — full-block prompt prefixes, keyed by their exact
  token bytes (no hash collisions at serving prompt lengths), LRU
  ordered.  ``lookup`` returns the LONGEST registered prefix of a new
  prompt and takes a reference on its blocks for the admitting slot;
  ``register`` parks a freshly-prefetched prompt's full blocks with a
  CACHE hold of their own, so the pages outlive the request that paid
  their prefill.  ``evict`` drops LRU entries (their cache hold) when the
  allocator runs dry — pages still referenced by live slots stay
  resident until those slots free.

Thread contract: scheduler-thread-only, like the rest of the batcher's
host state.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

import numpy as np

TRASH_BLOCK = 0


class BlockPool:
    """``n_blocks`` usable blocks of ``block_len`` tokens (ids 1 ..
    n_blocks; id 0 is the trash block and is never handed out)."""

    def __init__(self, n_blocks: int, block_len: int):
        if n_blocks < 1 or block_len < 1:
            raise ValueError(
                f"need n_blocks >= 1 and block_len >= 1, got "
                f"{n_blocks}/{block_len}")
        self.n_blocks = int(n_blocks)
        self.block_len = int(block_len)
        self._free: deque = deque(range(1, self.n_blocks + 1))
        self._refs: Dict[int, int] = {}

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.n_blocks - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Claim ``n`` blocks at refcount 1, or None if the pool can't
        cover them (nothing is claimed on failure)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        ids = [self._free.popleft() for _ in range(n)]
        for b in ids:
            self._refs[b] = 1
        return ids

    def addref(self, ids) -> None:
        for b in ids:
            if b not in self._refs:
                raise ValueError(f"addref on unallocated block {b}")
            self._refs[b] += 1

    def release(self, ids) -> int:
        """Drop one reference per id; blocks hitting zero return to the
        free list.  Returns how many blocks actually freed."""
        freed = 0
        for b in ids:
            n = self._refs.get(b)
            if n is None:
                raise ValueError(f"release on unallocated block {b}")
            if n > 1:
                self._refs[b] = n - 1
            else:
                del self._refs[b]
                self._free.append(b)
                freed += 1
        return freed

    def refcount(self, block_id: int) -> int:
        return self._refs.get(block_id, 0)


class PrefixIndex:
    """LRU index of full-block prompt prefixes -> resident pool blocks."""

    def __init__(self, pool: BlockPool, max_entries: int = 256):
        self.pool = pool
        self.max_entries = max(1, int(max_entries))
        # key (prefix token bytes) -> tuple of block ids; LRU order
        self._entries: "OrderedDict[bytes, Tuple[int, ...]]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    @staticmethod
    def _key(tokens: np.ndarray) -> bytes:
        return np.ascontiguousarray(tokens, np.int32).tobytes()

    def lookup(self, tokens: np.ndarray,
               max_blocks: Optional[int] = None) -> Tuple[int, List[int]]:
        """Longest registered full-block prefix of ``tokens`` -> (number
        of shared blocks, their pool ids), with one reference taken per
        block FOR THE CALLER (the admitting slot releases them with the
        rest of its table).  ``max_blocks`` caps the share (admission
        leaves at least one suffix token to prefill, so the request still
        produces first-token logits).  (0, []) on miss."""
        bl = self.pool.block_len
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        k_max = tokens.size // bl
        if max_blocks is not None:
            k_max = min(k_max, int(max_blocks))
        for k in range(k_max, 0, -1):
            ids = self._entries.get(self._key(tokens[:k * bl]))
            if ids is None:
                continue
            self._entries.move_to_end(self._key(tokens[:k * bl]))
            self.pool.addref(ids)
            self.hits += 1
            return k, list(ids)
        self.misses += 1
        return 0, []

    def register(self, tokens: np.ndarray, block_ids) -> bool:
        """Park ``tokens`` (exactly len(block_ids) * block_len of them) ->
        ``block_ids`` with a cache hold on each block.  No-op (False) when
        the prefix is already resident — the duplicate's blocks simply
        stay private to their slot.  Registering past ``max_entries``
        evicts the LRU entry first."""
        bl = self.pool.block_len
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size != len(block_ids) * bl:
            raise ValueError(
                f"register: {tokens.size} tokens != {len(block_ids)} "
                f"blocks * block_len {bl}")
        if not block_ids:
            return False
        key = self._key(tokens)
        if key in self._entries:
            self._entries.move_to_end(key)
            return False
        while len(self._entries) >= self.max_entries:
            self._evict_one()
        self.pool.addref(block_ids)
        self._entries[key] = tuple(block_ids)
        return True

    def _evict_one(self) -> int:
        key, ids = self._entries.popitem(last=False)
        self.evictions += 1
        return self.pool.release(ids)

    def evict_for(self, need_blocks: int) -> int:
        """Drop LRU entries until ``need_blocks`` are free in the pool or
        the index is empty.  Returns blocks actually freed (entries whose
        blocks are still held by live slots free nothing NOW — their
        cache hold is dropped, so they free when the slots do)."""
        freed = 0
        while self.pool.free_blocks < need_blocks and self._entries:
            freed += self._evict_one()
        return freed

    def stats(self) -> Dict:
        return {"entries": len(self._entries), "hits": self.hits,
                "misses": self.misses, "evictions": self.evictions}
