"""Brownout degradation ladder (PR 17 tentpole).

Between "healthy" and "shed everything" a serving fleet has a third
mode the reference platform never had: **degrade gracefully**.  This
module is a hysteresis ladder driven by the PR 13 windowed SLO burn
rate (``SloTracker`` — fraction of the error budget being consumed):

- **stage 1** — suppress streaming partials (PR 12 long-poll progress
  updates): pure overhead when the fleet is burning budget; finals
  still flow.
- **stage 2** — clamp ``gen.max_tokens`` for batch / best-effort
  generation traffic: long decodes are the biggest per-request cost
  the engine can shrink without dropping anyone.
- **stage 3** — shed best-effort at admission (serving/admission.py
  consults ``stage`` before the bucket): the last rung before hard
  overload behavior.

Hysteresis is what makes the ladder safe to automate: a stage is
entered only after burn exceeds its threshold for ``dwell_s``
(transient spikes don't flap the fleet into degradation), and exited
only after burn falls below ``exit_ratio`` x the entry threshold AND
the stage has been held ``hold_s`` (recovered capacity doesn't bounce
straight back into overload).  Every transition is recorded as a
flight-recorder ``brownout`` event and kept in a bounded in-memory
history that ``snapshot()`` exposes — so ``health()["brownout"]``, the
fleet aggregation, and incident bundles all show WHEN the fleet
degraded and why.

Pure stdlib, fake-clock injectable, no engine import.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional

# burn-rate entry thresholds per stage (stage i entered above enter[i-1]);
# burn 1.0 = consuming the error budget exactly as fast as allowed
DEFAULT_ENTER = (1.0, 2.0, 4.0)
DEFAULT_EXIT_RATIO = 0.5
DEFAULT_DWELL_S = 2.0     # burn must exceed the threshold this long to climb
DEFAULT_HOLD_S = 10.0     # minimum residence in a stage before descending
DEFAULT_BATCH_MAX_TOKENS = 32
HISTORY = 32


class BrownoutLadder:
    """One per replica, owned by the engine; ``observe()`` is called
    from the read loop with the current SLO burn rate."""

    def __init__(self, config: Optional[Dict] = None,
                 clock=time.monotonic,
                 recorder=None,
                 registry=None,
                 replica_id: Optional[str] = None):
        cfg = config if isinstance(config, dict) else {}
        self.enabled = bool(cfg.get("enabled", True))
        self._clock = clock
        self._recorder = recorder
        self._replica = replica_id
        enter = cfg.get("enter")
        if isinstance(enter, (list, tuple)) and len(enter) == 3:
            try:
                self.enter = tuple(sorted(float(x) for x in enter))
            except (TypeError, ValueError):
                self.enter = DEFAULT_ENTER
        else:
            self.enter = DEFAULT_ENTER
        self.exit_ratio = self._clamped(cfg.get("exit_ratio"),
                                        DEFAULT_EXIT_RATIO, 0.0, 1.0)
        self.dwell_s = self._clamped(cfg.get("dwell_s"),
                                     DEFAULT_DWELL_S, 0.0, 3600.0)
        self.hold_s = self._clamped(cfg.get("hold_s"),
                                    DEFAULT_HOLD_S, 0.0, 3600.0)
        self.batch_max_tokens = max(1, int(
            cfg.get("batch_max_tokens", DEFAULT_BATCH_MAX_TOKENS)))
        self.stage = 0
        self._entered_at = self._clock()
        self._above_since: Optional[float] = None  # burn > next threshold
        self._last_burn = 0.0
        self._transitions: deque = deque(maxlen=HISTORY)
        self._g_stage = None
        if registry is not None:
            self._g_stage = registry.gauge(
                "serving_brownout_stage",
                "Brownout degradation ladder stage (0 = healthy)")
            self._g_stage.set(0)

    @staticmethod
    def _clamped(v, default: float, lo: float, hi: float) -> float:
        try:
            return min(hi, max(lo, float(v)))
        except (TypeError, ValueError):
            return default

    # -- policy helpers the engine consults per record --------------------
    @property
    def suppress_partials(self) -> bool:
        return self.stage >= 1

    def clamp_max_tokens(self, priority: str) -> Optional[int]:
        """Stage >= 2 clamps generation length for non-interactive
        traffic; interactive keeps its requested budget."""
        if self.stage >= 2 and priority in ("batch", "best_effort"):
            return self.batch_max_tokens
        return None

    @property
    def shed_best_effort(self) -> bool:
        return self.stage >= 3

    # -- the ladder --------------------------------------------------------
    def observe(self, burn_rate, now: Optional[float] = None) -> int:
        """Feed one burn-rate sample; returns the (possibly new) stage.
        Climbs ONE rung per dwell window and descends one rung per hold
        window — degradation and recovery are both gradual."""
        if not self.enabled:
            return self.stage
        if now is None:
            now = self._clock()
        try:
            burn = max(0.0, float(burn_rate))
        except (TypeError, ValueError):
            burn = 0.0
        self._last_burn = burn
        # climb: burn above the NEXT stage's entry threshold for dwell_s
        if self.stage < len(self.enter) and burn >= self.enter[self.stage]:
            if self._above_since is None:
                self._above_since = now
            if now - self._above_since >= self.dwell_s:
                self._transition(self.stage + 1, burn, now)
                self._above_since = now if (
                    self.stage < len(self.enter)
                    and burn >= self.enter[self.stage]) else None
        else:
            self._above_since = None
        # descend: burn below exit threshold AND the stage was held
        if self.stage > 0 \
                and burn <= self.exit_ratio * self.enter[self.stage - 1] \
                and now - self._entered_at >= self.hold_s:
            self._transition(self.stage - 1, burn, now)
        return self.stage

    def _transition(self, to: int, burn: float, now: float) -> None:
        frm, self.stage = self.stage, to
        self._entered_at = now
        entry = {"from": frm, "to": to, "burn": round(burn, 4),
                 "t": now}
        self._transitions.append(entry)
        if self._g_stage is not None:
            self._g_stage.set(to)
        if self._recorder is not None:
            self._recorder.record(
                "brownout", stage=to,
                action=("enter" if to > frm else "exit"),
                reason=f"burn={burn:.2f}", count=frm,
                replica=self._replica)

    def snapshot(self) -> Dict:
        """The ``health()["brownout"]`` block; the transition history is
        what incident bundles and the fleet view render."""
        now = self._clock()
        history: List[Dict] = [
            {"from": t["from"], "to": t["to"], "burn": t["burn"],
             "age_s": round(now - t["t"], 3)}
            for t in self._transitions]
        return {
            "enabled": self.enabled,
            "stage": self.stage,
            "burn": round(self._last_burn, 4),
            "in_stage_s": round(now - self._entered_at, 3),
            "enter": list(self.enter),
            "exit_ratio": self.exit_ratio,
            "dwell_s": self.dwell_s,
            "hold_s": self.hold_s,
            "transitions": history,
        }
