"""Binary wire format + zero-copy shared-memory lane (PR 7 tentpole).

PR 3's stage timers identified record decode as the preprocess-side bound:
every tensor crossed the queue as base64-wrapped JSON — a ~33% byte
inflation plus a full decode copy on BOTH the enqueue and the consume side.
This module replaces that wire with a versioned **binary frame**:

    offset 0   magic   b"AZ"                (2 bytes)
    offset 2   version u8        (currently 1)
    offset 3   flags   u8        (bit 0: payload lives in a shm slot)
    offset 4   hlen    u32 LE    (header length in bytes)
    offset 8   plen    u32 LE    (inline payload length; 0 for shm frames)
    offset 12  header  JSON      (utf-8, compact separators, sorted keys)
    offset 12+hlen     payload   (raw little-endian tensor bytes)

No base64, no payload-in-JSON: the header is a small JSON document (so the
metadata surface stays schema-free and parseable from any language) and the
tensor bytes follow it verbatim.  The prefix's ``plen`` double-books the
payload length so a truncated or padded frame is detected as malformed
instead of decoded into garbage.  Header keys are SHORT on the wire and
expanded at decode — ``u``=uri ``t``=trace_id ``d``=deadline_ns
``dt``=dtype ``s``=shape ``sc``=scale ``sm``=shm ``m``=meta — and the
defaults are elided (``dt`` when ``<f4``, ``s`` when 1-D): a tensor
record's overhead is the prefix plus ~40 header bytes, which is what keeps
the wire-byte cut vs the base64-JSON record >= 25% instead of asymptoting
just under it.  Sorted-key compact JSON makes encoding DETERMINISTIC — the
golden-frame test pins the exact bytes, so an accidental layout change
cannot ship silently.

Zero-copy shared-memory lane (same-host producers): ``ShmRing`` is a ring
of fixed-size slots in one ``multiprocessing.shared_memory`` segment.  The
frame header travels through the queue as usual, but the payload is a slot
REFERENCE (``{"name", "slot", "gen", "len"}``); the consumer materializes
it with ``np.frombuffer`` over the mapped segment — one copy total (the
float32 normalization) instead of three.  Each slot carries a generation
counter written before and after the payload: a producer lapping a slow
consumer is DETECTED (generation mismatch -> ``FrameError`` -> per-record
quarantine), never silently served as torn bytes.  Size the ring at least
as deep as the queue's admission cap (``slots >= max_depth``) so a full
queue cannot lap the ring.

Copy accounting: the whole point of this wire is fewer payload-sized buffer
materializations, so the module counts them (``COPY_STATS``) at each
physical copy site — b64 encode/decode, frame build, spool write/read, shm
slot write, float32 normalization.  The structural win (shm < bin < json
copies per record) is asserted by test, not inferred from wall clock.

Pure stdlib + numpy: safe to import from the client, the queues, and the
HTTP gateway without dragging in jax.
"""

from __future__ import annotations

import json
import logging
import struct
import threading
from typing import Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

MAGIC = b"AZ"
VERSION = 1
FLAG_SHM = 0x01

_PREFIX = struct.Struct("<2sBBII")         # magic, version, flags, hlen,
PREFIX_LEN = _PREFIX.size                  # plen — 12 bytes

# header keys are SHORT on the wire, expanded at decode: every byte of
# per-record overhead eats into the 33% base64 inflation this wire removes.
# "tc" (trace_ctx, PR 13) carries the propagated span context — the
# gateway's traceparent + ingest timestamp ({"tp": str, "ts": ns}) — and is
# VERSION-COMPATIBLE both ways: old frames simply lack the key, and an old
# decoder passes the unexpanded "tc" through untouched (the engine only
# acts on "trace_ctx").  "tn"/"pr" (tenant/priority, PR 19) are trust-edge
# fields the gateway overwrites on every frame, with the same
# compatibility contract: old frames lack them (the engine attributes to
# tenant="unknown"), old decoders pass them through unexpanded.
_SHORT = {"uri": "u", "trace_id": "t", "deadline_ns": "d", "dtype": "dt",
          "shape": "s", "scale": "sc", "shm": "sm", "meta": "m",
          "trace_ctx": "tc", "tenant": "tn", "priority": "pr"}
_LONG = {v: k for k, v in _SHORT.items()}

# wire-format tags used for metrics labels and bench A/Bs
FMT_JSON = "json"                          # legacy base64-JSON record
FMT_BIN = "bin"                            # binary frame, payload inline
FMT_SHM = "shm"                            # binary frame, payload in shm


class FrameError(ValueError):
    """Malformed binary frame (bad magic, truncated header, payload length
    mismatch, stale shm slot).  Producers see it at encode/enqueue; the
    engine quarantines the offending record and keeps serving."""


# -- copy accounting -----------------------------------------------------------

class _CopyStats:
    """Counts payload-sized buffer materializations per wire path so the
    copy-count reduction is a TESTABLE structural claim.  Sites:

    - ``b64_encode`` / ``b64_decode`` — legacy JSON wire
    - ``frame_build``                 — payload memcpy into a binary frame
    - ``spool_write`` / ``spool_read``— FileQueue payload traversal
    - ``shm_write``                   — payload memcpy into a ring slot
    - ``normalize``                   — the float32 materialization copy
                                        every path pays exactly once
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._bytes: Dict[str, int] = {}

    def record(self, site: str, nbytes: int = 0) -> None:
        with self._lock:
            self._counts[site] = self._counts.get(site, 0) + 1
            self._bytes[site] = self._bytes.get(site, 0) + int(nbytes)

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {site: {"count": self._counts[site],
                           "bytes": self._bytes.get(site, 0)}
                    for site in self._counts}

    def total_copies(self) -> int:
        with self._lock:
            return sum(self._counts.values())

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._bytes.clear()


COPY_STATS = _CopyStats()


# -- frame codec ---------------------------------------------------------------

def _header_bytes(header: Dict) -> bytes:
    # sorted keys + compact separators: deterministic bytes for the golden
    # fixture, and byte-for-byte stable across Python versions
    return json.dumps(header, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def encode_frame(header: Dict, payload=b"", flags: int = 0) -> bytes:
    """Assemble one frame.  ``header`` uses the LONG key names (uri,
    trace_id, ...) — they are shortened on the wire and re-expanded at
    decode.  ``payload`` is any buffer (bytes, memoryview, contiguous
    ndarray); it is copied exactly once, into the frame."""
    payload = memoryview(payload).cast("B") \
        if not isinstance(payload, (bytes, bytearray)) else payload
    plen = len(payload) if not isinstance(payload, memoryview) \
        else payload.nbytes
    hbytes = _header_bytes({_SHORT.get(k, k): v for k, v in header.items()})
    frame = bytearray(PREFIX_LEN + len(hbytes) + plen)
    _PREFIX.pack_into(frame, 0, MAGIC, VERSION, flags, len(hbytes), plen)
    frame[PREFIX_LEN:PREFIX_LEN + len(hbytes)] = hbytes
    if plen:
        frame[PREFIX_LEN + len(hbytes):] = payload      # the ONE copy
        COPY_STATS.record("frame_build", plen)
    return bytes(frame)


def encode_tensor_frame(uri: str, arr: np.ndarray,
                        scale: Optional[float] = None,
                        deadline_ns: Optional[int] = None,
                        trace_id: Optional[str] = None,
                        shm_ref: Optional[Dict] = None,
                        meta: Optional[Dict] = None,
                        trace_ctx: Optional[Dict] = None) -> bytes:
    """One tensor record as a binary frame.  ``arr`` must already be
    contiguous little-endian (the client normalizes before calling); with
    ``shm_ref`` the payload stays in its shm slot and the frame carries only
    the reference."""
    header: Dict = {"uri": str(uri)}
    # single-byte dtypes stringify as "|i1": normalize to the "<"-prefixed
    # tags the legacy wire (and the engine's int8 gate) already speak
    dtype_str = arr.dtype.str
    if dtype_str.startswith("|"):
        dtype_str = "<" + dtype_str[1:]
    if dtype_str != "<f4":                 # "<f4" is the decode default
        header["dtype"] = dtype_str
    if arr.ndim != 1:                      # a flat payload needs no reshape
        header["shape"] = list(arr.shape)
    if scale is not None:
        header["scale"] = float(scale)
    if deadline_ns is not None:
        header["deadline_ns"] = int(deadline_ns)
    if trace_id is not None:
        header["trace_id"] = str(trace_id)
    if trace_ctx:
        header["trace_ctx"] = dict(trace_ctx)
    if meta:
        header["meta"] = meta
    if shm_ref is not None:
        header["shm"] = dict(shm_ref)
        return encode_frame(header, flags=FLAG_SHM)
    return encode_frame(header, payload=arr)


def is_frame(buf) -> bool:
    """Cheap sniff: does this buffer start like a binary frame?"""
    try:
        return len(buf) >= PREFIX_LEN and bytes(buf[:2]) == MAGIC
    except (TypeError, ValueError):
        return False


def decode_frame(buf) -> Tuple[int, Dict, memoryview]:
    """Parse one frame into ``(flags, header, payload_view)``.  The payload
    is a zero-copy memoryview over ``buf``; for shm frames it is empty and
    the header's ``shm`` reference locates the real bytes.  Raises
    ``FrameError`` on anything malformed — bad magic, unknown version,
    truncated header, or a payload whose length disagrees with the header's
    ``plen``."""
    view = memoryview(buf).cast("B") if not isinstance(buf, memoryview) \
        else buf.cast("B")
    flags, hlen, plen, header = _parse_prefix_and_header(view)
    payload = view[PREFIX_LEN + hlen:]
    if flags & FLAG_SHM:
        if plen or payload.nbytes:
            raise FrameError("shm frame must carry no inline payload "
                             f"(prefix plen {plen}, {payload.nbytes} "
                             "trailing bytes)")
        if not isinstance(header.get("shm"), dict):
            raise FrameError("shm frame header lacks the 'shm' reference")
    elif plen != payload.nbytes:
        raise FrameError(f"payload length mismatch: prefix says {plen}, "
                         f"frame carries {payload.nbytes}")
    return flags, header, payload


def _parse_prefix_and_header(view: memoryview):
    """Shared prefix+header parse: ``(flags, hlen, plen, header)`` with the
    short keys expanded.  Payload validation is the caller's business."""
    if view.nbytes < PREFIX_LEN:
        raise FrameError(f"frame truncated: {view.nbytes} bytes < "
                         f"{PREFIX_LEN}-byte prefix")
    magic, version, flags, hlen, plen = _PREFIX.unpack_from(view, 0)
    if magic != MAGIC:
        raise FrameError(f"bad magic {magic!r} (expected {MAGIC!r})")
    if version != VERSION:
        raise FrameError(f"unsupported frame version {version} "
                         f"(this decoder speaks {VERSION})")
    if view.nbytes < PREFIX_LEN + hlen:
        raise FrameError(f"frame truncated: header says {hlen} bytes, "
                         f"only {view.nbytes - PREFIX_LEN} present")
    try:
        raw = json.loads(bytes(view[PREFIX_LEN:PREFIX_LEN + hlen]))
    except ValueError as e:
        raise FrameError(f"frame header is not valid JSON: {e}") from e
    if not isinstance(raw, dict):
        raise FrameError("frame header must be a JSON object")
    header = {_LONG.get(k, k): v for k, v in raw.items()}
    if "uri" not in header:
        raise FrameError("frame header must carry a 'uri'")
    return flags, hlen, plen, header


def decode_header(buf) -> Dict:
    """Header-only parse: prefix + header JSON, WITHOUT the payload-length
    validation (enqueue-side, the queue only needs the uri for the record
    id — full frame validation happens once, at the consume boundary)."""
    view = memoryview(buf).cast("B") if not isinstance(buf, memoryview) \
        else buf.cast("B")
    return _parse_prefix_and_header(view)[3]


def frame_to_record(buf) -> Dict:
    """One decoded frame as the engine-facing record dict: header fields
    hoisted to the top level (``uri``/``trace_id``/``deadline_ns`` keep the
    exact keys the deadline gates and tracer already read), the payload as
    a zero-copy ``memoryview`` under ``"payload"`` (or the shm reference
    under ``"shm"``), plus ``wire_fmt``/``wire_bytes`` for the byte
    accounting metrics."""
    flags, header, payload = decode_frame(buf)
    rec: Dict = dict(header)
    if flags & FLAG_SHM:
        rec["wire_fmt"] = FMT_SHM
    else:
        rec["payload"] = payload
        rec["wire_fmt"] = FMT_BIN
    rec["wire_bytes"] = memoryview(buf).nbytes \
        if not isinstance(buf, (bytes, bytearray)) else len(buf)
    return rec


def restamp_frame(buf, trace_id: Optional[str] = None,
                  deadline_ns: Optional[int] = None) -> bytes:
    """Rewrite a frame's header (gateway ingest: issue a trace_id, stamp an
    edge deadline) without touching fields already present.  Returns the
    original buffer unchanged when there is nothing to add; otherwise the
    payload is spliced behind the new header (one copy — the gateway
    already owns the request body, so this is the only copy it pays)."""
    return restamp_frame_with_header(buf, trace_id=trace_id,
                                     deadline_ns=deadline_ns)[0]


def restamp_frame_with_header(
        buf, trace_id: Optional[str] = None,
        deadline_ns: Optional[int] = None,
        trace_ctx_fn=None,
        overwrite_trace_ctx: bool = False,
        set_fields: Optional[Dict] = None) -> Tuple[bytes, Dict]:
    """``restamp_frame`` plus the (post-stamp) decoded header, so a caller
    that needs both — the gateway reads back uri/trace_id/deadline for its
    reply — pays ONE header parse instead of re-decoding the result.

    ``trace_ctx_fn`` (PR 13): called with the post-stamp header to produce
    the propagated span context to stamp.  A callable (not a value)
    because the context must name the frame's FINAL trace_id — which may
    be the client's own, only known after the stamp.  By default a
    context already present is kept (native producers re-framing);
    ``overwrite_trace_ctx=True`` REPLACES it — the gateway is the trust
    edge for remote frames, where a client-supplied context would forge
    the queue-wait ingest timestamp (and through it the SLO attribution)
    and mis-parent every engine span."""
    flags, header, payload = decode_frame(buf)
    changed = False
    # trust-edge stamps (PR 17): fields the gateway OWNS — tenant
    # identity and priority class — overwrite whatever the remote frame
    # carried (a client-supplied tenant would bill someone else's bucket)
    for k, v in (set_fields or {}).items():
        if header.get(k) != v:
            header[k] = v
            changed = True
    if trace_id is not None and "trace_id" not in header:
        header["trace_id"] = trace_id
        changed = True
    if deadline_ns is not None and "deadline_ns" not in header:
        header["deadline_ns"] = int(deadline_ns)
        changed = True
    if trace_ctx_fn is not None and (overwrite_trace_ctx
                                     or "trace_ctx" not in header):
        ctx = trace_ctx_fn(header)
        if isinstance(ctx, dict) and ctx \
                and ctx != header.get("trace_ctx"):
            header["trace_ctx"] = ctx
            changed = True
    if not changed:
        return (bytes(buf) if not isinstance(buf, bytes) else buf), header
    return encode_frame(header, payload=payload, flags=flags), header


def sanitize_record(record: Optional[Dict]) -> Optional[Dict]:
    """JSON-safe copy of a record for dead-letter entries: a binary
    payload (memoryview / bytes) is re-encoded as base64 under ``"b64"``
    so the entry serializes AND ``replay_dead_letters`` can re-enqueue it
    through the legacy decode path; a shm reference is dropped (the slot
    may be reused long before any replay) with a note."""
    if record is None or not isinstance(record, dict):
        return record
    if "payload" not in record and "shm" not in record:
        return record
    import base64
    out = {k: v for k, v in record.items()
           if k not in ("payload", "shm", "wire_fmt", "wire_bytes")}
    payload = record.get("payload")
    if payload is not None:
        try:
            out["b64"] = base64.b64encode(payload).decode("ascii")
        except (TypeError, ValueError):
            out["payload_repr"] = repr(payload)[:128]
    elif "shm" in record:
        out["shm_dropped"] = "payload lived in a shm slot (not retained)"
    return out


# -- zero-copy shared-memory lane ---------------------------------------------

class ShmRing:
    """Ring of fixed-size payload slots in one shared-memory segment.

    Layout: ``slots`` control records (``gen`` u64 + ``len`` u64 + ``crc``
    u32 of the payload), then ``slots`` payload regions of ``slot_bytes``
    each.  The producer writes round-robin; every write invalidates the
    slot (gen=0), copies the payload, then publishes generation + crc — a
    consumer checks the generation before reading and, after
    materializing, verifies BOTH the generation and the crc32 of the slot
    bytes against the reference.  The generation catches slot reuse; the
    crc makes torn-read detection architecture-independent (a plain
    seqlock's store ordering is only guaranteed on TSO hardware like x86 —
    on weaker memory models the payload stores could become visible before
    the invalidation, and the checksum is what still catches the mix).
    Either way: a lapped or mid-write slot raises ``FrameError`` ->
    per-record quarantine, never torn bytes served as data.

    The ring does not track consumption: a producer that laps a slot whose
    record is still queued invalidates that record (detected at decode ->
    quarantine).  Size ``slots`` at least as deep as the queue's admission
    cap to make lapping impossible."""

    CTRL = struct.Struct("<QQI")           # gen, len, crc32(payload)

    def __init__(self, name: Optional[str] = None, slots: int = 64,
                 slot_bytes: int = 1 << 16, create: bool = True):
        from multiprocessing import shared_memory
        self.slots = int(slots)
        self.slot_bytes = int(slot_bytes)
        size = self.slots * (self.CTRL.size + self.slot_bytes)
        if create:
            self._shm = shared_memory.SharedMemory(create=True, size=size)
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            # consumer-side attach: on Python <= 3.12 SharedMemory
            # registers EVERY mapping with the resource tracker, which
            # unlinks at process exit — a restarting consumer would
            # destroy a segment its producer still owns.  Cleanup belongs
            # to the creating process alone; unregister the attachment.
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(self._shm._name,
                                            "shared_memory")
            except Exception:  # noqa: BLE001 — tracker internals differ
                pass           # across versions; worst case is a warning
            if self._shm.size < size:
                # a reference whose geometry exceeds the real segment
                # would compute control/payload offsets past the mapping
                # — and, cached, poison every later decode for this
                # segment name: refuse the attach instead
                actual = self._shm.size
                self.close()
                raise FrameError(
                    f"shm ref geometry ({self.slots} slots x "
                    f"{self.slot_bytes} bytes -> {size} bytes) exceeds "
                    f"segment {name!r} ({actual} bytes)")
        self.name = self._shm.name
        self._next = 0
        self._lock = threading.Lock()
        self._owner = create

    def _ctrl_off(self, slot: int) -> int:
        return slot * self.CTRL.size

    def _payload_off(self, slot: int) -> int:
        return self.slots * self.CTRL.size + slot * self.slot_bytes

    def write(self, data) -> Dict:
        """Copy one payload into the next slot; returns the reference dict
        that travels in the frame header.  Raises ``ValueError`` when the
        payload exceeds ``slot_bytes`` (the caller falls back to an inline
        frame)."""
        import zlib
        view = memoryview(data).cast("B")
        n = view.nbytes
        if n > self.slot_bytes:
            raise ValueError(f"payload {n} bytes exceeds shm slot size "
                             f"{self.slot_bytes}")
        crc = zlib.crc32(view)
        with self._lock:
            slot = self._next % self.slots
            self._next += 1
            gen = self._next                # monotonic, never 0
            buf = self._shm.buf
            # invalidate -> copy -> publish: a concurrent reader can never
            # match `gen` against half-written bytes (and the crc catches
            # the mix even where stores reorder)
            self.CTRL.pack_into(buf, self._ctrl_off(slot), 0, 0, 0)
            off = self._payload_off(slot)
            buf[off:off + n] = view
            COPY_STATS.record("shm_write", n)
            self.CTRL.pack_into(buf, self._ctrl_off(slot), gen, n, crc)
        # geometry rides in the reference so the consumer can map the
        # segment without out-of-band coordination
        return {"name": self.name, "slot": slot, "gen": gen, "len": n,
                "crc": crc,
                "slots": self.slots, "slot_bytes": self.slot_bytes}

    def slot_view(self, ref: Dict) -> memoryview:
        """Zero-copy view over a referenced slot, validated against the
        reference's generation.  Call ``verify(ref)`` again AFTER
        materializing the bytes — the window between view and copy is where
        a lapping producer could overwrite."""
        self.verify(ref, check_crc=False)   # cheap pre-check; the full
        off = self._payload_off(int(ref["slot"]))   # crc runs post-copy
        return self._shm.buf[off:off + int(ref["len"])]

    def verify(self, ref: Dict, check_crc: bool = True) -> None:
        gen, ln, crc = self.CTRL.unpack_from(
            self._shm.buf, self._ctrl_off(int(ref["slot"])))
        if gen != int(ref["gen"]) or ln != int(ref["len"]):
            raise FrameError(
                f"shm slot {ref['slot']} overwritten (gen {gen} != "
                f"{ref['gen']}): producer lapped the ring — size slots >= "
                "the queue's max_depth")
        if check_crc and "crc" not in ref:
            # the crc is MANDATORY for the full check: every write()
            # stamps one, so a ref without it is hand-built — and gen/len
            # alone can collide under a mismatched geometry (a spoofed
            # layout reading the honest ring's slot-0 control record),
            # which would serve arbitrary in-segment bytes as tensor data
            raise FrameError(
                f"shm ref for slot {ref['slot']} lacks the payload crc")
        if check_crc:
            # checksum the CURRENT slot bytes against the reference: on
            # weakly-ordered hardware a lapping writer's payload stores can
            # land before its invalidation store, which the generation
            # alone cannot see — the crc still catches the mixed bytes
            import zlib
            off = self._payload_off(int(ref["slot"]))
            if zlib.crc32(self._shm.buf[off:off + ln]) != int(ref["crc"]):
                raise FrameError(
                    f"shm slot {ref['slot']} overwritten mid-read "
                    "(payload checksum mismatch): producer lapped the "
                    "ring — size slots >= the queue's max_depth")

    def close(self) -> None:
        try:
            self._shm.close()
        except (OSError, BufferError):
            pass

    def unlink(self) -> None:
        if self._owner:
            try:
                self._shm.unlink()
            except (OSError, FileNotFoundError):
                pass


# consumer-side attachment cache: one mapping per (segment name, geometry)
# per process — keyed on geometry so a ref with a bogus layout attaches its
# OWN (self-quarantining) mapping and can never poison the mapping a
# legitimate producer's records decode through
_ATTACHED: Dict[Tuple[str, int, int], ShmRing] = {}
_ATTACH_LOCK = threading.Lock()
# honest producers use one geometry per segment; a flood of DISTINCT
# spoofed geometries must not accumulate live mappings on a long-lived
# engine (eviction is unsafe — another thread may hold a slot view into
# an evicted mapping — so past the cap new attachments quarantine instead)
_MAX_ATTACHED = 32


def attach_ring(ref: Dict) -> ShmRing:
    """Attach (once per process per geometry) to the segment a slot
    reference names.  The control layout is self-describing only through
    the producer's geometry, which rides in the reference — so the attach
    validates that geometry against the real segment size (``FrameError``
    on a ref that overstates it, nothing cached) and caches per
    (name, slots, slot_bytes): a ref that UNDERSTATES the geometry maps a
    layout whose gen/crc checks fail only for its own records, while the
    honest producer's refs keep decoding through their own mapping."""
    name = str(ref["name"])
    slots = int(ref.get("slots", 64))
    slot_bytes = int(ref.get("slot_bytes", 1 << 16))
    key = (name, slots, slot_bytes)
    with _ATTACH_LOCK:
        ring = _ATTACHED.get(key)
        if ring is None:
            if len(_ATTACHED) >= _MAX_ATTACHED:
                _evict_dead_attachments()
            if len(_ATTACHED) >= _MAX_ATTACHED:
                raise FrameError(
                    f"shm attachment cache full ({_MAX_ATTACHED} live "
                    "mappings): refusing a new (name, geometry) "
                    "attachment — distinct-geometry ref flood, or "
                    "detach_all() overdue")
            ring = ShmRing(name=name, slots=slots, slot_bytes=slot_bytes,
                           create=False)
            _ATTACHED[key] = ring
        return ring


def _evict_dead_attachments() -> None:
    """Called with ``_ATTACH_LOCK`` held when the cache is at cap: drop
    mappings whose segment has been UNLINKED.  Every producer restart
    creates a fresh segment name (`InputQueue` -> new ``ShmRing``), so on
    a long-lived engine dead mappings would otherwise fill the cap and
    permanently quarantine the 33rd producer's traffic.  An unlinked
    segment's in-flight records are already doomed to quarantine (the
    producer must outlive consumption — README caveat), so evicting its
    mapping under pressure costs nothing that was not already lost."""
    from multiprocessing import shared_memory
    for key in list(_ATTACHED):
        try:
            probe = shared_memory.SharedMemory(name=key[0])
        except FileNotFoundError:
            _ATTACHED.pop(key).close()
            continue
        except OSError:
            continue                   # transient: keep the mapping
        # still live: release the probe (and keep it off the resource
        # tracker's exit-time unlink list, same as the ShmRing attach)
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(probe._name, "shared_memory")
        except Exception:  # noqa: BLE001 — tracker internals differ
            pass
        probe.close()


def detach_all() -> None:
    """Drop every cached attachment (tests / engine shutdown)."""
    with _ATTACH_LOCK:
        for ring in _ATTACHED.values():
            ring.close()
        _ATTACHED.clear()


def resolve_payload(record: Dict) -> Tuple[memoryview, Optional[Dict]]:
    """The decode seam used by the engine: returns ``(payload_view,
    shm_ref)`` for a binary record.  For inline frames the view aliases the
    frame bytes; for shm frames it aliases the mapped slot and the caller
    MUST re-``verify`` the reference (via ``attach_ring(ref).verify(ref)``)
    after materializing, to detect a producer lapping mid-copy."""
    if "payload" in record:
        return memoryview(record["payload"]).cast("B"), None
    ref = record.get("shm")
    if not isinstance(ref, dict):
        raise FrameError("binary record has neither payload nor shm ref")
    ring = attach_ring(ref)
    return ring.slot_view(ref), ref
