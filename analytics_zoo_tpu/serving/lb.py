"""Multi-replica load-balancing front door (PR 10 tentpole).

PR 7/9 gave every replica its own HTTP ingestion gateway on
``http_port + i`` — but clients had to pick a replica port by hand, and a
scale event (autoscaler or ``manager scale N``) changed the port set under
them.  ``LoadBalancer`` is the missing single-port front: it proxies

- ``POST /v1/enqueue``      — least-inflight pick over the READY replica
  gateways; a member that fails at the transport level (connection refused
  / reset / timeout — the SIGKILLed-replica shape) is marked out and the
  request retries on the next member, so a mid-stream replica death is
  **never** a client-visible failure.  503 from a member (draining) also
  re-routes; semantic statuses (200, 400, 411, 413, 429) pass through
  untouched — a full queue is full on every member alike.
- ``GET /v1/result/<uri>``  — results live in the SHARED queue backend, so
  any replica can answer; transport failures and gateway-side 5xx re-route
  with the remaining long-poll budget, 404 ("not ready") passes through.

plus its own ``/healthz`` / ``/readyz`` (ready = at least one ready
member) and ``/metrics`` (JSON or ``?format=prom``).

Membership is DYNAMIC: a ``member_source()`` callable returns the current
replica gateway URLs and is re-polled every probe tick, so the autoscaler
resizing the fleet (or an operator's ``manager scale N``) needs no client
reconfig — new replicas join the rotation as soon as their ``/readyz``
goes green, drained ones leave it.  ``manager_members(pidfile, ...)``
derives the URL set from the supervisor's scale file + per-replica
pidfiles; ``static_members([...])`` pins a fixed set.

Zero dependencies (stdlib ``ThreadingHTTPServer`` + ``urllib``), same as
the per-replica gateway it fronts.

CLI::

    python -m analytics_zoo_tpu.serving.lb --port 8000 -c config.yaml \\
        [--pidfile cluster-serving.pid]      # members from the supervisor
    python -m analytics_zoo_tpu.serving.lb --port 8000 \\
        --members http://127.0.0.1:8081,http://127.0.0.1:8082

(The manager runs one in-process with ``manager start --replicas N
--lb-port P``.)
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

from analytics_zoo_tpu.common.observability import (MetricsRegistry,
                                                    SpanContext, Tracer,
                                                    new_trace_id,
                                                    trace_sampled)
from analytics_zoo_tpu.serving.http import LONGPOLL_CAP_S, MAX_BODY_BYTES

logger = logging.getLogger(__name__)

# per-attempt transport timeout for enqueue proxying; result proxying uses
# the remaining long-poll budget + a small margin instead
ENQUEUE_TIMEOUT_S = 30.0
RESULT_MARGIN_S = 5.0


class _Transport(RuntimeError):
    """A member failed below HTTP (refused / reset / timeout): retry-able
    on another member, and grounds to mark the member unhealthy."""


class _Member:
    __slots__ = ("url", "inflight", "healthy", "fails", "lock")

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.inflight = 0
        self.healthy = False        # joins rotation on its first green probe
        self.fails = 0
        self.lock = threading.Lock()

    def mark(self, healthy: bool) -> None:
        with self.lock:
            flipped = healthy != self.healthy
            self.healthy = healthy
            self.fails = 0 if healthy else self.fails + 1
        if flipped:
            # incident flight recorder (PR 15): member rotation flips are
            # exactly the "what was the front door seeing" evidence an
            # incident bundle needs (the supervisor drains this ring)
            try:
                from analytics_zoo_tpu.common.observability import (
                    get_recorder)
                get_recorder().record(
                    "lb_member_up" if healthy else "lb_member_down",
                    url=self.url)
            except Exception:  # noqa: BLE001 — diagnostics only
                pass


def static_members(urls: List[str]) -> Callable[[], List[str]]:
    urls = [u.rstrip("/") for u in urls]
    return lambda: list(urls)


def manager_members(pidfile: str, http_host: str = "127.0.0.1",
                    http_port: Optional[int] = None,
                    count: Optional[int] = None) -> Callable[[], List[str]]:
    """Member URLs from a ``manager start --replicas`` deployment: the
    supervisor's ``<pidfile>.replicas`` target names the slots, replica
    ``i`` serves its gateway on ``http_port + i``.  Slots whose replica
    pidfile is missing are still listed (the replica may be mid-spawn) —
    the readiness probe keeps them out of rotation until green."""

    def source() -> List[str]:
        if not http_port:
            return []
        n = count
        if n is None:
            from analytics_zoo_tpu.serving.fleet import read_scale
            n = read_scale(pidfile)
        return [f"http://{http_host}:{http_port + i}" for i in range(n)]

    return source


class LoadBalancer:
    """One port in front of N replica gateways (see module docstring)."""

    def __init__(self, member_source: Callable[[], List[str]],
                 host: str = "127.0.0.1", port: int = 0,
                 registry: Optional[MetricsRegistry] = None,
                 probe_interval_s: float = 0.5,
                 probe_timeout_s: float = 1.0,
                 tracer: Optional[Tracer] = None,
                 trace_sample: float = 1.0,
                 span_spool: Optional[str] = None,
                 retry_budget: Optional[Dict] = None):
        self.member_source = member_source
        self.host = host
        self.port = port                    # actual port after start()
        self.registry = registry or MetricsRegistry()
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        # fleet tracing (PR 13): the front door opens the ROOT span of
        # every proxied request and forwards the context as a W3C
        # `traceparent` header — the gateway continues it, the engine
        # parents its stage spans under it.  Head sampling uses the same
        # pure-function verdict as every other process; `span_spool`
        # names the jsonl file `drain_spans_to_spool()` appends to (the
        # manager supervisor / standalone CLI call it periodically).
        self.tracer = tracer or Tracer(replica_id="lb")
        try:
            self.trace_sample = min(max(float(trace_sample), 0.0), 1.0)
        except (TypeError, ValueError):
            self.trace_sample = 1.0
        self.span_spool = span_spool
        self._members: Dict[str, _Member] = {}
        self._members_lock = threading.Lock()
        self._rr = 0                        # least-inflight tie-breaker
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        reg = self.registry
        self._m_requests = reg.counter(
            "lb_requests_total", "Front-door requests, by endpoint and "
            "status code", labels=("endpoint", "code"))
        self._m_retries = reg.counter(
            "lb_retries_total", "Requests re-routed to another member "
            "after a transport failure or 5xx", labels=("endpoint",))
        for ep in ("enqueue", "result"):
            self._m_retries.labels(endpoint=ep).inc(0)
        # retry budget (PR 17): re-routes are amplification — under a
        # fleet-wide brownout every member answers 5xx and N members x M
        # clients of blind retries would triple the offered load exactly
        # when capacity is scarcest.  The budget caps the retry fraction
        # per window; a denied retry returns the member's LAST answer
        # (the truth: everyone is overloaded) instead of hammering on.
        self._retry_budget = None
        if retry_budget is not None and (
                retry_budget.get("enabled", True) if
                isinstance(retry_budget, dict) else bool(retry_budget)):
            from analytics_zoo_tpu.common.resilience import RetryBudget
            cfg = retry_budget if isinstance(retry_budget, dict) else {}
            self._retry_budget = RetryBudget(
                ratio=float(cfg.get("ratio", 0.2)),
                min_retries=int(cfg.get("min_retries", 3)),
                window_s=float(cfg.get("window_s", 10.0)))
        self._m_budget_exhausted = reg.counter(
            "lb_retry_budget_exhausted_total", "Re-routes denied because "
            "the retry budget was spent")
        self._m_budget_exhausted.inc(0)
        self._m_latency = reg.histogram(
            "lb_request_seconds", "Front-door request latency, by endpoint",
            labels=("endpoint",))
        reg.gauge("lb_members_total", "Known replica gateways",
                  fn=lambda: float(len(self._snapshot_members())))
        reg.gauge("lb_members_ready", "Replica gateways in rotation",
                  fn=lambda: float(sum(
                      1 for m in self._snapshot_members() if m.healthy)))

    # -- membership -----------------------------------------------------------
    def _snapshot_members(self) -> List[_Member]:
        with self._members_lock:
            return list(self._members.values())

    def refresh_members(self) -> None:
        """Reconcile the member table with the source: new URLs join
        (out of rotation until probed green), vanished URLs leave."""
        try:
            urls = {u.rstrip("/") for u in (self.member_source() or [])}
        except Exception as e:  # noqa: BLE001 — a broken source must not
            logger.warning("lb: member source failed (%s: %s)",  # kill probes
                           type(e).__name__, e)
            return
        with self._members_lock:
            for url in urls - set(self._members):
                self._members[url] = _Member(url)
            for url in set(self._members) - urls:
                self._members.pop(url)

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            self.probe_once()
            self._stop.wait(self.probe_interval_s)

    def probe_once(self) -> None:
        """One membership refresh + readiness sweep (exposed for tests and
        for callers that want an immediate converge after a scale event)."""
        self.refresh_members()
        for member in self._snapshot_members():
            try:
                req = urllib.request.Request(member.url + "/readyz")
                with urllib.request.urlopen(
                        req, timeout=self.probe_timeout_s) as resp:
                    member.mark(resp.status == 200)
            except Exception:  # noqa: BLE001 — not ready / not reachable
                member.mark(False)

    def _pick(self, exclude) -> Optional[_Member]:
        """Least-inflight over ready members (round-robin tie-break).  When
        NO member is ready — probe data may be stale right after a mass
        restart — fall back to any un-excluded member so the request gets
        one real attempt instead of a blind 503."""
        members = [m for m in self._snapshot_members()
                   if m.url not in exclude]
        ready = [m for m in members if m.healthy]
        pool = ready or members
        if not pool:
            return None
        self._rr += 1
        return min(pool, key=lambda m: (m.inflight,
                                        hash((m.url, self._rr)) & 0xffff))

    # -- proxying -------------------------------------------------------------
    def _retry_allowed(self, endpoint: str) -> bool:
        """One re-route, if the retry budget (PR 17) has room.  Counts the
        retry when taken, the exhaustion when denied — a denied re-route
        surfaces the member's last answer instead of amplifying load."""
        if self._retry_budget is not None \
                and not self._retry_budget.allow_retry():
            self._m_budget_exhausted.inc()
            return False
        self._m_retries.labels(endpoint=endpoint).inc()
        return True

    @staticmethod
    def _forward(member: _Member, method: str, path_qs: str,
                 body: Optional[bytes], ctype: Optional[str],
                 timeout: float, headers=()):
        req = urllib.request.Request(member.url + path_qs, data=body,
                                     method=method)
        if ctype:
            req.add_header("Content-Type", ctype)
        for k, v in headers or ():
            req.add_header(k, v)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, resp.read(), resp.headers
        except urllib.error.HTTPError as e:
            # semantic HTTP answer (4xx/5xx with a body): NOT a transport
            # failure — the caller decides pass-through vs re-route
            try:
                payload = e.read()
            except OSError:
                payload = b""
            return e.code, payload, e.headers
        except Exception as e:  # noqa: BLE001 — refused/reset/timeout/DNS
            raise _Transport(f"{type(e).__name__}: {e}") from e

    def _proxy(self, endpoint: str, method: str, path: str, query: str,
               body: Optional[bytes], ctype: Optional[str],
               deadline: float, retry_503: bool, headers=()):
        """Try members until one answers: transport failures and (when
        ``retry_503``) 503s mark the member out and re-route; anything else
        passes through.  A result long-poll's ``timeout_s`` is REWRITTEN to
        the remaining budget on every attempt, so a re-route after a
        replica death long-polls the survivor for what is left — not the
        original budget past our own transport timeout.  Returns
        (status, body, headers, attempts)."""
        from urllib.parse import parse_qs, urlencode
        tried: set = set()
        last = None
        attempts = 0
        if self._retry_budget is not None:
            self._retry_budget.note_request()
        while True:
            member = self._pick(tried)
            if member is None:
                break
            tried.add(member.url)
            attempts += 1
            budget = deadline - time.monotonic()
            if budget <= 0:
                break                      # total budget spent re-routing
            qs = query
            if endpoint == "result":
                remaining = max(0.0, budget - RESULT_MARGIN_S)
                q = {k: v[-1] for k, v in parse_qs(query).items()}
                q["timeout_s"] = f"{remaining:.3f}"
                qs = urlencode(q)
                timeout = remaining + RESULT_MARGIN_S
            else:
                # the deadline bounds the WHOLE request across re-routes:
                # N wedged-but-listening members must cost at most one
                # enqueue budget total, not one each
                timeout = min(ENQUEUE_TIMEOUT_S, budget)
            path_qs = path + (f"?{qs}" if qs else "")
            with member.lock:
                member.inflight += 1
            try:
                status, payload, resp_headers = self._forward(
                    member, method, path_qs, body, ctype, timeout,
                    headers=headers)
            except _Transport as e:
                member.mark(False)
                logger.info("lb: member %s failed (%s); re-routing",
                            member.url, e)
                if not self._retry_allowed(endpoint):
                    break
                continue
            finally:
                with member.lock:
                    member.inflight -= 1
            if status >= 500 or (status == 503 and retry_503):
                # a 5xx (or a draining member's 503) may succeed elsewhere;
                # keep the answer in case every member says the same
                last = (status, payload, resp_headers, attempts)
                if status == 503:
                    member.mark(False)
                if not self._retry_allowed(endpoint):
                    break
                continue
            return status, payload, resp_headers, attempts
        if last is not None:
            return last
        return (503,
                json.dumps({"error": "no replica gateway available"})
                .encode(),
                {"Retry-After": "1"}, attempts)

    # -- distributed tracing (PR 13) ------------------------------------------
    _SNIFF_CAP = 262144                    # biggest reply body worth parsing

    @staticmethod
    def _parse_reply(payload: bytes) -> Optional[Dict]:
        """Gateway JSON reply body (enqueue ack / result) — how the front
        door joins its spans to a trace whose id may have been decided
        downstream (client-stamped frames win over the LB's root id), and
        how it tells terminal results from streaming partials.
        Best-effort: non-JSON / oversized bodies just yield None."""
        if not payload or len(payload) > LoadBalancer._SNIFF_CAP:
            return None
        try:
            doc = json.loads(payload)
        except ValueError:
            return None
        return doc if isinstance(doc, dict) else None

    @staticmethod
    def _sniff_trace_id(payload: bytes) -> Optional[str]:
        doc = LoadBalancer._parse_reply(payload)
        tid = doc.get("trace_id") if doc else None
        return tid if isinstance(tid, str) and tid else None

    def _record_root_span(self, stage: str, t0: float, ctx: SpanContext,
                          result, uri=None, inbound: bool = False,
                          parent_id=None, tenant=None,
                          priority=None) -> None:
        """The front door's span, IF this trace is sampled.  The verdict:
        an inbound traceparent's flag is authoritative (the upstream
        already decided — recording an explicitly-unsampled trace would
        leave orphan LB-only spans the rest of the fleet dropped);
        otherwise the fleet-pure hash of the trace's REAL id — sniffed
        from the reply when it differs from ours (client-stamped frame
        ids win downstream).  A reply with no sniffable id on a context
        we minted unsampled (header-less result polls) records nothing:
        a random id would mint a one-span orphan trace per poll."""
        if inbound and not ctx.sampled:
            return
        if not inbound and self.trace_sample <= 0.0 and not ctx.sampled:
            return                         # spans fully off: skip the parse
        status, payload, _, attempts = result
        doc = self._parse_reply(payload)
        if stage == "lb_result" and (doc is None or doc.get("partial")):
            # only a PARSED, terminal result records the lb_result leg: a
            # streaming partial at the long-poll deadline (PR 12) is not
            # terminal — a 20-poll token stream must not deposit one
            # bogus span per poll — and an unparseable/oversized body
            # cannot be told apart from one, so it records nothing rather
            # than flood (the gateway-side result_poll span still covers
            # the terminal fetch)
            return
        tid = doc.get("trace_id") if doc else None
        trace_id = tid if isinstance(tid, str) and tid else ctx.trace_id
        if not inbound:
            if trace_id == ctx.trace_id:
                if not ctx.sampled:
                    return
            elif not trace_sampled(trace_id, self.trace_sample):
                return
        attrs = {"code": int(status), "attempts": int(attempts)}
        # tenant attribution (PR 19): the client-declared identity rides
        # the LB root span as-declared (the gateway's admission normalizes
        # it downstream — the LB is outside the trust edge)
        if isinstance(tenant, str) and tenant:
            attrs["tenant"] = tenant
        if isinstance(priority, str) and priority:
            attrs["priority"] = priority
        if attempts > 1:
            # the retry made visible: a re-routed request's root span says
            # so, next to the reclaim span the serving replica records
            attrs["rerouted"] = True
        # parent: the CALLER's span when it sent a traceparent — the
        # chain must not break at the fleet edge for clients carrying
        # their own tracing
        self.tracer.span(stage, t0, time.monotonic(), trace_id=trace_id,
                         uri=uri, span_id=ctx.span_id,
                         parent_id=parent_id, attrs=attrs)

    def drain_spans_to_spool(self) -> int:
        """Append every buffered span to ``span_spool`` (no-op without
        one).  Called by the manager supervisor loop and the standalone
        CLI — the LB's half of the fleet trace collection."""
        if not self.span_spool:
            return 0
        spans = self.tracer.drain_spans()
        if spans:
            from analytics_zoo_tpu.serving import tracecollect
            tracecollect.append_spans(self.span_spool, spans, source="lb")
        return len(spans)

    # -- HTTP surface ---------------------------------------------------------
    def start(self) -> "LoadBalancer":
        lb = self

        class _Handler(BaseHTTPRequestHandler):
            timeout = 30

            def log_message(self, fmt, *args):  # noqa: A003
                logger.debug("lb: " + fmt, *args)

            def _reply(self, status: int, body: bytes,
                       ctype: str = "application/json",
                       extra=()) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, status: int, doc, extra=()) -> None:
                self._reply(status, json.dumps(doc).encode(), extra=extra)

            def _observe(self, endpoint: str, code: int,
                         t0: float) -> None:
                lb._m_requests.labels(endpoint=endpoint,
                                      code=str(code)).inc()
                lb._m_latency.labels(endpoint=endpoint).record(
                    time.monotonic() - t0)

            def _passthrough(self, result, endpoint: str,
                             t0: float) -> None:
                # headers is an http.client message or the plain dict from
                # _proxy's no-member fallback — both support .get
                status, payload, headers, attempts = result
                extra = []
                replica = headers.get("X-Replica-Id")
                if replica:
                    extra.append(("X-Replica-Id", str(replica)))
                retry_after = headers.get("Retry-After")
                if retry_after:
                    extra.append(("Retry-After", str(retry_after)))
                extra.append(("X-LB-Attempts", str(attempts)))
                ctype = headers.get("Content-Type") or "application/json"
                self._reply(status, payload, ctype=ctype, extra=extra)
                self._observe(endpoint, status, t0)

            def do_GET(self):  # noqa: N802
                from urllib.parse import parse_qs, urlsplit
                parts = urlsplit(self.path)
                try:
                    if parts.path == "/healthz":
                        members = lb._snapshot_members()
                        doc = {
                            "running": True,
                            "members": {m.url: {"ready": m.healthy,
                                                "inflight": m.inflight,
                                                "fails": m.fails}
                                        for m in members}}
                        if lb._retry_budget is not None:
                            doc["retry_budget"] = \
                                lb._retry_budget.snapshot()
                        self._reply_json(200, doc)
                    elif parts.path == "/readyz":
                        ready = [m.url for m in lb._snapshot_members()
                                 if m.healthy]
                        self._reply_json(
                            200 if ready else 503,
                            {"ready": bool(ready), "members": ready},
                            extra=(() if ready
                                   else (("Retry-After", "1"),)))
                    elif parts.path == "/metrics":
                        fmt = (parse_qs(parts.query).get("format")
                               or [None])[0]
                        if fmt == "prom" or (
                                fmt is None and "text/plain" in
                                (self.headers.get("Accept") or "")):
                            self._reply(
                                200,
                                lb.registry.to_prometheus().encode(),
                                ctype=MetricsRegistry.CONTENT_TYPE)
                        else:
                            self._reply_json(200, lb.registry.snapshot())
                    elif parts.path.startswith("/v1/result/"):
                        t0 = time.monotonic()
                        raw = (parse_qs(parts.query).get("timeout_s")
                               or ["0"])[0]
                        try:
                            budget = min(max(float(raw), 0.0),
                                         LONGPOLL_CAP_S)
                        except ValueError:
                            budget = 0.0
                        # result polls JOIN an existing trace (sniffed
                        # from the terminal reply) — continue an inbound
                        # context when one came in, otherwise let the
                        # sniffed trace_id's own sampling verdict decide
                        inbound = SpanContext.from_traceparent(
                            self.headers.get("traceparent"))
                        ctx = inbound.child() if inbound is not None \
                            else SpanContext(sampled=False)
                        result = lb._proxy(
                            "result", "GET", parts.path, parts.query,
                            None, None,
                            deadline=t0 + budget + RESULT_MARGIN_S,
                            retry_503=True,
                            headers=[("traceparent",
                                      ctx.to_traceparent())])
                        self._passthrough(result, "result", t0)
                        if result[0] == 200:
                            from urllib.parse import unquote
                            uri = unquote(
                                parts.path[len("/v1/result/"):])
                            lb._record_root_span(
                                "lb_result", t0, ctx, result, uri=uri,
                                inbound=inbound is not None,
                                parent_id=(inbound.span_id
                                           if inbound is not None
                                           else None),
                                tenant=self.headers.get("X-Tenant"),
                                priority=self.headers.get("X-Priority"))
                    else:
                        self._reply_json(
                            404, {"error": f"no route {parts.path}"})
                except Exception as e:  # noqa: BLE001 — front door answers
                    self._reply_json(500,
                                     {"error": f"{type(e).__name__}: {e}"})

            def do_POST(self):  # noqa: N802
                from urllib.parse import urlsplit
                parts = urlsplit(self.path)
                if parts.path != "/v1/enqueue":
                    self._reply_json(404,
                                     {"error": f"no route {parts.path}"})
                    return
                t0 = time.monotonic()
                try:
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                    except ValueError:
                        length = 0
                    if length <= 0:
                        self._reply_json(
                            411, {"error": "Content-Length required"})
                        self._observe("enqueue", 411, t0)
                        return
                    if length > MAX_BODY_BYTES:
                        self._reply_json(
                            413, {"error": f"body {length} bytes > cap "
                                           f"{MAX_BODY_BYTES}"})
                        self._observe("enqueue", 413, t0)
                        return
                    body = self.rfile.read(length)
                    # the ROOT span of the request's trace (PR 13): mint
                    # trace + span id, decide sampling once (pure function
                    # of the id — the whole fleet agrees), forward the
                    # context so the gateway and engine parent under it
                    inbound = SpanContext.from_traceparent(
                        self.headers.get("traceparent"))
                    if inbound is not None:
                        ctx = inbound.child()
                    else:
                        tid = new_trace_id()
                        ctx = SpanContext(
                            tid, sampled=trace_sampled(
                                tid, lb.trace_sample))
                    fwd = [("traceparent", ctx.to_traceparent())]
                    # tenant identity + priority class (PR 17) ride to the
                    # gateway trust edge, where admission normalizes and
                    # stamps them — dropping them here would collapse every
                    # client into the anonymous default/batch lane
                    for h in ("X-Api-Key", "X-Tenant", "X-Priority"):
                        v = self.headers.get(h)
                        if v:
                            fwd.append((h, v))
                    result = lb._proxy(
                        "enqueue", "POST", parts.path, parts.query,
                        body, self.headers.get("Content-Type"),
                        deadline=t0 + ENQUEUE_TIMEOUT_S, retry_503=True,
                        headers=fwd)
                    self._passthrough(result, "enqueue", t0)
                    if result[0] == 200:
                        lb._record_root_span(
                            "lb_enqueue", t0, ctx, result,
                            inbound=inbound is not None,
                            parent_id=(inbound.span_id
                                       if inbound is not None
                                       else None),
                            tenant=self.headers.get("X-Tenant"),
                            priority=self.headers.get("X-Priority"))
                except Exception as e:  # noqa: BLE001
                    self._reply_json(500,
                                     {"error": f"{type(e).__name__}: {e}"})

        self._stop.clear()
        self._server = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="serving-lb", daemon=True)
        self._thread.start()
        self.probe_once()                  # converge before first request
        self._probe_thread = threading.Thread(target=self._probe_loop,
                                              name="serving-lb-probe",
                                              daemon=True)
        self._probe_thread.start()
        logger.info("serving lb on http://%s:%d -> %d member(s)",
                    self.host, self.port, len(self._snapshot_members()))
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        for t in (self._thread, self._probe_thread):
            if t is not None:
                t.join(timeout)
        self._thread = self._probe_thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="serving-lb")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--members", default=None,
                    help="comma-separated replica gateway URLs (fixed set)")
    ap.add_argument("--pidfile", default="cluster-serving.pid",
                    help="manager deployment: derive members from the "
                         "supervisor's scale file + config http_port")
    ap.add_argument("-c", "--config", default="config.yaml")
    ap.add_argument("--probe-interval", type=float, default=0.5)
    ap.add_argument("--span-spool", default=None, metavar="PATH",
                    help="append the front door's trace spans to this "
                         "jsonl spool (fleet trace collection; the "
                         "manager-run LB spools to <pidfile>.lb.spans."
                         "jsonl automatically)")
    ap.add_argument("--trace-sample", type=float, default=1.0,
                    help="head-sampling rate for the root spans")
    args = ap.parse_args(argv)
    if args.members:
        source = static_members(
            [u for u in args.members.split(",") if u.strip()])
    else:
        from analytics_zoo_tpu.serving.engine import ServingParams
        from analytics_zoo_tpu.serving.manager import load_config
        try:
            params = ServingParams.from_dict(
                load_config(args.config).get("params", {}))
        except OSError:
            params = ServingParams()
        if not params.http_port:
            ap.error("config has no params.http_port; pass --members "
                     "explicitly")
        source = manager_members(args.pidfile, http_host=params.http_host,
                                 http_port=params.http_port)
    retry_budget = None
    try:
        from analytics_zoo_tpu.serving.manager import load_config
        retry_budget = load_config(args.config).get("retry_budget")
    except OSError:
        pass
    lb = LoadBalancer(source, host=args.host, port=args.port,
                      probe_interval_s=args.probe_interval,
                      trace_sample=args.trace_sample,
                      span_spool=args.span_spool,
                      retry_budget=retry_budget).start()
    print(json.dumps({"lb": lb.url}), flush=True)
    try:
        while True:
            time.sleep(1.0)
            lb.drain_spans_to_spool()
    except KeyboardInterrupt:
        lb.drain_spans_to_spool()
        lb.stop()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
