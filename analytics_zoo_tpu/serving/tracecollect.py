"""Fleet-wide trace collection (PR 13 tentpole).

Each process in the serving fleet — the LB front door, every replica
(whose gateway shares its process), the generation scheduler — records
spans into its own in-process ``Tracer`` ring on the MONOTONIC clock.
This module is the collection half of the Dapper shape:

- **spools** — every replica's manager loop periodically calls
  ``Tracer.drain_spans()`` and appends the result here
  (``append_spans``), one jsonl file per process next to its health
  snapshot: ``<pidfile>.rN.spans.jsonl`` per replica,
  ``<pidfile>.lb.spans.jsonl`` for the front door.  Each drain batch is
  preceded by a CLOCK record (``{"kind": "clock", "wall": ..., "mono":
  ...}``) captured at the drain, so the spool is self-describing.
- **merge** — ``merge_spools`` loads every spool, normalizes each span's
  monotonic ``ts`` onto the wall clock through the nearest preceding
  clock record (falling back to a health-doc ``clock`` pair when a
  legacy spool carries none), and returns one flat span list with
  ``ts_wall`` (epoch seconds) per span.  Same-host processes share the
  wall clock, so after normalization spans from different processes
  order correctly on one timeline.
- **reconstruction** — ``reconstruct(spans, trace_id)`` is the `manager
  trace <id>` document: the request's spans across every process, time-
  offset from the trace start, with parent links, per-process
  attribution, the e2e wall span, and the untracked gaps (queue
  residency, cross-process handoffs).  ``slowest(spans, n)`` ranks
  traces by e2e.  ``chrome_trace(spans)`` renders the fleet timeline
  with ONE pid/track per process for Perfetto.

Pure stdlib: importable from the manager CLI and ``tools/trace_view.py``
without dragging in jax or numpy.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

# one-generation rotation cap per spool file: a week-long deployment must
# not grow an unbounded span log next to its pidfile
SPOOL_MAX_BYTES = 8 * 1024 * 1024


def spool_path(pidfile: str) -> str:
    """The span spool owned by the process whose pidfile this is (replica
    pidfiles are ``<base>.rN``, so the per-replica spool lands at
    ``<base>.rN.spans.jsonl`` — globbable from the base)."""
    return pidfile + ".spans.jsonl"


def events_path(pidfile: str) -> str:
    """The flight-recorder event spool (PR 15) next to the span spool:
    same per-process ownership, same rotation/clock contract."""
    return pidfile + ".events.jsonl"


def find_spools(pidfile: str) -> List[str]:
    """Every span spool of a deployment: the daemon's own, each
    replica's, and the LB's — anything matching ``<pidfile>*`` with the
    spool suffix (rotated ``.1`` generations included)."""
    out = sorted(set(glob.glob(pidfile + "*.spans.jsonl")
                     + glob.glob(pidfile + "*.spans.jsonl.1")))
    return out


def find_event_spools(pidfile: str) -> List[str]:
    """Every flight-recorder event spool of a deployment (PR 15) —
    replicas, the supervisor's own (autoscaler/LB/incident events), and
    rotated generations."""
    out = sorted(set(glob.glob(pidfile + "*.events.jsonl")
                     + glob.glob(pidfile + "*.events.jsonl.1")))
    return out


def usage_path(pidfile: str) -> str:
    """The usage journal (PR 19) next to the span/event spools: one jsonl
    file of per-interval per-(tenant, model) usage deltas per replica."""
    return pidfile + ".usage.jsonl"


def find_usage_spools(pidfile: str) -> List[str]:
    """Every usage journal of a deployment (rotated generations
    included)."""
    out = sorted(set(glob.glob(pidfile + "*.usage.jsonl")
                     + glob.glob(pidfile + "*.usage.jsonl.1")))
    return out


def gensnap_path(pidfile: str) -> str:
    """The generation-snapshot spool (PR 20) next to the span/event/usage
    spools: one jsonl file of checkpointed decode state per replica, so a
    surviving replica can resume a dead owner's in-flight generations."""
    return pidfile + ".gensnap.jsonl"


def find_snapshot_spools(pidfile: str) -> List[str]:
    """Every generation-snapshot spool of a deployment (rotated
    generations included)."""
    out = sorted(set(glob.glob(pidfile + "*.gensnap.jsonl")
                     + glob.glob(pidfile + "*.gensnap.jsonl.1")))
    return out


def _append_records(path: str, records: List[Dict], kind: str,
                    source: Optional[str], max_bytes: int) -> int:
    """The one spool writer (spans AND events): a clock record
    (wall/monotonic pair captured NOW, i.e. at the drain — the offset the
    merge uses for every record in the batch) followed by the batch.  The
    file rotates once to ``.1`` past ``max_bytes`` so a long-lived
    replica cannot fill the disk."""
    if not records:
        return 0
    try:
        if max_bytes and os.path.exists(path) \
                and os.path.getsize(path) > max_bytes:
            os.replace(path, path + ".1")
    except OSError:
        pass
    clock = {"kind": "clock", "wall": time.time(),
             "mono": time.monotonic()}
    if source is not None:
        clock["source"] = source
    lines = [json.dumps(clock)]
    for s in records:
        rec = {"kind": kind}
        rec.update(s)
        if source is not None:
            rec.setdefault("replica_id", source)
        try:
            lines.append(json.dumps(rec))
        except (TypeError, ValueError):
            # a record smuggling a non-JSON attr must not kill the batch
            lines.append(json.dumps(
                {k: v for k, v in rec.items()
                 if isinstance(v, (str, int, float, bool, type(None)))}))
    with open(path, "a") as f:
        f.write("\n".join(lines) + "\n")
    return len(records)


def append_spans(path: str, spans: Iterable[Dict],
                 source: Optional[str] = None,
                 max_bytes: int = SPOOL_MAX_BYTES) -> int:
    """Append one ``Tracer.drain_spans()`` batch.  Returns the number of
    spans written."""
    return _append_records(path, list(spans), "span", source, max_bytes)


def append_events(path: str, events: Iterable[Dict],
                  source: Optional[str] = None,
                  max_bytes: int = SPOOL_MAX_BYTES) -> int:
    """Append one ``FlightRecorder.drain_events()`` batch (PR 15) — the
    SAME rotation + drain-time clock contract as span spools, so
    ``merge_spools`` normalizes both onto one wall timeline and `manager
    trace` / `incident_view` agree about when everything happened."""
    return _append_records(path, list(events), "event", source, max_bytes)


def append_snapshots(path: str, records: Iterable[Dict],
                     source: Optional[str] = None,
                     max_bytes: int = SPOOL_MAX_BYTES) -> int:
    """Append one batch of generation checkpoints (PR 20) — the SAME
    rotation + drain-time clock contract as the other spools.  The
    ``gensnap`` kind is unknown to ``merge_spools``, so snapshots never
    pollute trace timelines; they are read back only by
    ``load_snapshots`` on the resume path."""
    return _append_records(path, list(records), "gensnap", source,
                           max_bytes)


def snapshot_checksum(rec: Dict) -> int:
    """Integrity stamp over the fields a resume actually replays: the
    identity, epoch, prompt and generated tokens.  Stored in the record
    at checkpoint time and re-derived at resume time — a truncated or
    corrupted snapshot fails loudly instead of resuming garbage."""
    import zlib
    body = json.dumps([str(rec.get("rid")), int(rec.get("epoch") or 0),
                       [int(t) for t in rec.get("prompt") or []],
                       [int(t) for t in rec.get("tokens") or []]])
    return zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF


def load_snapshots(paths: Iterable[str]) -> List[Dict]:
    """Every generation checkpoint of the given spools, each stamped
    with ``ts_wall`` via the nearest preceding clock record of its file
    (the ``load_usage`` contract; a record with no clock keeps its raw
    ``ts`` and gains ``clock_skewed: true``)."""
    out: List[Dict] = []
    for path in paths:
        offset: Optional[float] = None
        for rec in load_spool(path):
            kind = rec.get("kind")
            if kind == "clock":
                try:
                    offset = float(rec["wall"]) - float(rec["mono"])
                except (KeyError, TypeError, ValueError):
                    pass
                continue
            if kind != "gensnap":
                continue
            rec = {k: v for k, v in rec.items() if k != "kind"}
            try:
                ts = float(rec.get("ts", 0.0))
            except (TypeError, ValueError):
                continue
            if offset is not None:
                rec["ts_wall"] = ts + offset
            else:
                rec["ts_wall"] = ts
                rec["clock_skewed"] = True
            out.append(rec)
    out.sort(key=lambda r: r.get("ts_wall", 0.0))
    return out


def append_usage(path: str, records: Iterable[Dict],
                 source: Optional[str] = None,
                 max_bytes: int = SPOOL_MAX_BYTES) -> int:
    """Append one ``UsageMeter.drain()`` batch (PR 19) — the SAME
    rotation + drain-time clock contract as span/event spools, so the
    journal's monotonic ``ts`` stamps normalize onto the wall clock the
    same way spans do."""
    return _append_records(path, list(records), "usage", source, max_bytes)


def load_usage(paths: Iterable[str]) -> List[Dict]:
    """Every usage delta of the given journals, each stamped with
    ``ts_wall`` via the nearest preceding clock record of its file
    (mirroring ``merge_spools``; a record with no clock keeps its raw
    ``ts`` and gains ``clock_skewed: true``)."""
    out: List[Dict] = []
    for path in paths:
        offset: Optional[float] = None
        for rec in load_spool(path):
            kind = rec.get("kind")
            if kind == "clock":
                try:
                    offset = float(rec["wall"]) - float(rec["mono"])
                except (KeyError, TypeError, ValueError):
                    pass
                continue
            if kind != "usage":
                continue
            rec = {k: v for k, v in rec.items() if k != "kind"}
            try:
                ts = float(rec.get("ts", 0.0))
            except (TypeError, ValueError):
                continue
            if offset is not None:
                rec["ts_wall"] = ts + offset
            else:
                rec["ts_wall"] = ts
                rec["clock_skewed"] = True
            out.append(rec)
    out.sort(key=lambda r: r.get("ts_wall", 0.0))
    return out


_USAGE_SUM_FIELDS = ("records", "tokens", "device_s", "bytes", "sheds")


def aggregate_usage(records: Iterable[Dict], by: str = "tenant",
                    since: Optional[float] = None) -> Dict:
    """The ``manager usage`` rollup: sum the journal's per-interval
    deltas grouped ``by`` tenant (default) or model, optionally limited
    to deltas drained after wall time ``since`` (epoch seconds).
    Replaying the journal reproduces the counters, so the rollup is the
    billing-grade view of the same numbers the labelled series carry."""
    if by not in ("tenant", "model"):
        raise ValueError(f"usage rollup: by must be tenant|model, "
                         f"got {by!r}")
    groups: Dict[str, Dict[str, float]] = {}
    n_intervals = 0
    for rec in records:
        if since is not None and rec.get("ts_wall", 0.0) < since:
            continue
        key = str(rec.get(by) or "unknown")
        g = groups.setdefault(key, dict.fromkeys(_USAGE_SUM_FIELDS, 0.0))
        for f in _USAGE_SUM_FIELDS:
            try:
                g[f] += float(rec.get(f, 0) or 0)
            except (TypeError, ValueError):
                pass
        n_intervals += 1
    for g in groups.values():
        for f in _USAGE_SUM_FIELDS:
            g[f] = round(g[f], 6) if g[f] != int(g[f]) else int(g[f])
    return {"by": by, "since": since, "intervals": n_intervals,
            "usage": {k: groups[k] for k in sorted(groups)}}


def load_spool(path: str) -> List[Dict]:
    """Every record (clock + span) of one spool, malformed lines
    skipped."""
    out: List[Dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def _doc_clock(doc: Optional[Dict]) -> Optional[Tuple[float, float]]:
    """(wall, mono) out of a health document's ``clock`` block."""
    if not isinstance(doc, dict):
        return None
    c = doc.get("clock")
    try:
        return float(c["wall"]), float(c["monotonic"])
    except (TypeError, KeyError, ValueError):
        return None


def merge_spools(paths: Iterable[str],
                 health_docs: Optional[Dict[str, Dict]] = None
                 ) -> List[Dict]:
    """One flat fleet span list, every span stamped with ``ts_wall``
    (epoch seconds) via the nearest PRECEDING clock record of its spool —
    the drain writes the pair at the same instant as the batch, so the
    offset is exact for that batch even across replica restarts (each
    boot's monotonic epoch differs, which is exactly why a single static
    offset would be wrong).

    ``health_docs`` maps replica_id -> health document; a legacy spool
    with no clock records falls back to its replica's health-doc
    wall/monotonic pair, and a span with no clock at all keeps its raw
    ``ts`` with ``clock_skewed: true`` so downstream consumers can warn
    instead of silently mis-ordering it.

    Flight-recorder EVENT spools (PR 15) merge through the same path:
    an event record keeps ``kind: "event"`` and gets its ``event`` name
    mirrored into ``stage`` so every downstream consumer (reconstruct,
    chrome_trace, incident_view) lays events and spans out on the one
    timeline as zero-duration marks."""
    by_replica_clock: Dict[str, Tuple[float, float]] = {}
    for rid, doc in (health_docs or {}).items():
        pair = _doc_clock(doc)
        if pair is not None:
            by_replica_clock[str(rid)] = pair
    merged: List[Dict] = []
    for path in paths:
        offset: Optional[float] = None
        for rec in load_spool(path):
            if rec.get("kind") == "clock":
                try:
                    offset = float(rec["wall"]) - float(rec["mono"])
                except (KeyError, TypeError, ValueError):
                    pass
                continue
            if rec.get("kind") not in (None, "span", "event"):
                continue
            if rec.get("kind") == "event":
                span = {k: v for k, v in rec.items()}
                span.setdefault("stage", str(span.get("event")))
                span.setdefault("dur_s", 0.0)
            else:
                span = {k: v for k, v in rec.items() if k != "kind"}
            off = offset
            if off is None:
                pair = by_replica_clock.get(
                    str(span.get("replica_id") or ""))
                if pair is not None:
                    off = pair[0] - pair[1]
            try:
                ts = float(span.get("ts", 0.0))
            except (TypeError, ValueError):
                continue
            if off is not None:
                span["ts_wall"] = ts + off
            else:
                span["ts_wall"] = ts
                span["clock_skewed"] = True
            merged.append(span)
    merged.sort(key=lambda s: s.get("ts_wall", 0.0))
    return merged


# -- reconstruction -------------------------------------------------------------

def _span_source(span: Dict) -> str:
    return str(span.get("replica_id") or "unknown")


def traces_in(spans: Iterable[Dict]) -> Dict[str, List[Dict]]:
    out: Dict[str, List[Dict]] = {}
    for s in spans:
        tid = s.get("trace_id")
        if tid:
            out.setdefault(str(tid), []).append(s)
    return out


def reconstruct(spans: Iterable[Dict], trace_id: str) -> Dict:
    """The ``manager trace <id>`` document: one request's timeline across
    every process.  Spans are offset from the trace start (``t_ms``),
    ordered, parent-linked, and the gaps between consecutive spans are
    listed with a ``cross_process`` flag — the handoff costs (queue
    residency, LB->gateway hop) that no single process's ring can see."""
    mine = [s for s in spans if str(s.get("trace_id")) == str(trace_id)]
    if not mine:
        return {"trace_id": trace_id, "spans": 0, "found": False}
    mine.sort(key=lambda s: s.get("ts_wall", 0.0))
    t0 = min(s["ts_wall"] for s in mine)
    t1 = max(s["ts_wall"] + float(s.get("dur_s", 0.0)) for s in mine)
    timeline = []
    for s in mine:
        entry = {"t_ms": round((s["ts_wall"] - t0) * 1e3, 3),
                 "dur_ms": round(float(s.get("dur_s", 0.0)) * 1e3, 3),
                 "stage": s.get("stage"),
                 "process": _span_source(s),
                 "uri": s.get("uri")}
        for key in ("span_id", "parent_id", "error", "tokens",
                    "attempts", "rerouted", "code", "clock_skewed",
                    "tenant", "priority"):
            if s.get(key) is not None:
                entry[key] = s[key]
        timeline.append(entry)
    gaps = []
    for prev, nxt in zip(mine, mine[1:]):
        gap = nxt["ts_wall"] - (prev["ts_wall"]
                                + float(prev.get("dur_s", 0.0)))
        if gap > 0:
            gaps.append({
                "after": prev.get("stage"),
                "before": nxt.get("stage"),
                "gap_ms": round(gap * 1e3, 3),
                "cross_process":
                    _span_source(prev) != _span_source(nxt)})
    stages: Dict[str, float] = {}
    for s in mine:
        st = str(s.get("stage"))
        stages[st] = stages.get(st, 0.0) + float(s.get("dur_s", 0.0)) * 1e3
    return {"trace_id": trace_id,
            "found": True,
            "spans": len(mine),
            "processes": sorted({_span_source(s) for s in mine}),
            "e2e_ms": round((t1 - t0) * 1e3, 3),
            "stages_ms": {k: round(v, 3) for k, v in stages.items()},
            "untracked_ms": round(sum(g["gap_ms"] for g in gaps), 3),
            "errors": [s["error"] for s in mine if s.get("error")],
            "timeline": timeline,
            "gaps": gaps}


def slowest(spans: Iterable[Dict], n: int = 5) -> List[Dict]:
    """Top-N traces by fleet-wide e2e (first span start to last span
    end) — each entry a summary; feed the trace_id back to
    ``reconstruct`` for the full timeline."""
    out = []
    for tid, mine in traces_in(spans).items():
        t0 = min(s.get("ts_wall", 0.0) for s in mine)
        t1 = max(s.get("ts_wall", 0.0) + float(s.get("dur_s", 0.0))
                 for s in mine)
        out.append({
            "trace_id": tid,
            "e2e_ms": round((t1 - t0) * 1e3, 3),
            "spans": len(mine),
            "processes": sorted({_span_source(s) for s in mine}),
            "uri": next((s.get("uri") for s in mine
                         if s.get("uri") is not None), None),
            "error": next((s.get("error") for s in mine
                           if s.get("error")), None)})
    out.sort(key=lambda t: -t["e2e_ms"])
    return out[: max(0, int(n))]


def chrome_trace(spans: Iterable[Dict]) -> Dict:
    """Fleet Chrome trace-event JSON: one pid (track group) per PROCESS —
    lb / replica-0 / replica-1 ... — one tid per stage inside it, so
    Perfetto lays the request out as the cross-process waterfall it is."""
    pids: Dict[str, int] = {}
    tids: Dict[Tuple[str, str], int] = {}
    events: List[Dict] = []
    meta: List[Dict] = []
    for s in spans:
        src = _span_source(s)
        pid = pids.get(src)
        if pid is None:
            pid = pids[src] = len(pids) + 1
            meta.append({"name": "process_name", "ph": "M", "pid": pid,
                         "tid": 0, "args": {"name": src}})
        key = (src, str(s.get("stage")))
        tid = tids.get(key)
        if tid is None:
            tid = tids[key] = sum(1 for k in tids if k[0] == src) + 1
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": key[1]}})
        ev = {"name": str(s.get("stage")), "cat": "serving", "ph": "X",
              "ts": round(float(s.get("ts_wall", s.get("ts", 0.0)))
                          * 1e6, 3),
              "dur": round(float(s.get("dur_s", 0.0)) * 1e6, 3),
              "pid": pid, "tid": tid,
              "args": {k: v for k, v in s.items()
                       if k not in ("stage", "ts", "ts_wall", "dur_s")}}
        events.append(ev)
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def export_chrome_trace(spans: Iterable[Dict], path: str) -> str:
    doc = chrome_trace(spans)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def collect(pidfile: str,
            health_docs: Optional[Dict[str, Dict]] = None,
            events: bool = False) -> List[Dict]:
    """The one-call fleet merge the CLI uses: find every spool of the
    deployment, merge, normalize.  ``events=True`` (PR 15) folds the
    flight-recorder event spools into the same timeline — the `manager
    incident --show` / `tools/incident_view.py` view."""
    paths = find_spools(pidfile)
    if events:
        paths = sorted(set(paths) | set(find_event_spools(pidfile)))
    return merge_spools(paths, health_docs=health_docs)
