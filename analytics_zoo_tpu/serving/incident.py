"""Incident capture — self-contained forensic bundles (PR 15 tentpole).

When something goes wrong — SLO burn crosses the configured threshold, a
replica crashes and the supervisor respawns it, or an operator wants a
snapshot — the question is "what was every process DOING around that
moment", and by the time someone logs in the evidence has rotated away.
``capture()`` snapshots the deployment's entire observable state into
``<pidfile>.incidents/<ts>/``:

- every span spool (PR 13 traces) and flight-recorder event spool (the
  last-N typed events of every process — ring-bounded, so "last N" is
  what the spool holds),
- every per-replica health snapshot, the autoscaler decision log, the LB
  telemetry snapshot, and the knobs/scale files,
- an ``incident.json`` manifest naming the trigger, the capture wall
  time, and what was captured.

Capture is MANAGER-side file copying of already-drained spools: the
serving hot path is never blocked, paused, or even aware.  Bundles are
bounded (``max_bundles``, oldest evicted) so a flapping trigger cannot
fill the disk.

``load_timeline()`` merges a bundle's spools through the PR 13
clock-normalization contract (``tracecollect.merge_spools`` accepts
event spools), so `manager incident --show` and ``tools/incident_view.py``
render recorder events and trace spans on ONE timeline.

Pure stdlib: importable from the manager CLI and the supervisor without
dragging in jax.
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import time
from typing import Dict, List, Optional

from analytics_zoo_tpu.serving import tracecollect

# file patterns bundled from the deployment dir, relative to the pidfile
_CAPTURE_GLOBS = (
    "*.spans.jsonl", "*.spans.jsonl.1",
    "*.events.jsonl", "*.events.jsonl.1",
    # usage metering (PR 19): the per-tenant usage journal — an incident
    # bundle shows WHO was being served when things went wrong
    "*.usage.jsonl", "*.usage.jsonl.1",
    "*.health.json",
    ".autoscaler.json", ".lb.json", ".knobs.json", ".replicas",
    # rollout (PR 16): the phase / target / per-replica version
    # assignments at capture time — a rollback bundle must show WHERE the
    # fleet was mid-roll
    ".rollout.state.json",
)

DEFAULT_MAX_BUNDLES = 20


def incidents_dir(pidfile: str) -> str:
    return pidfile + ".incidents"


def capture(pidfile: str, reason: str, meta: Optional[Dict] = None,
            max_bundles: int = DEFAULT_MAX_BUNDLES) -> Optional[str]:
    """Snapshot one incident bundle.  Returns the bundle directory, or
    None when nothing was capturable (no spools/snapshots exist yet).
    Never raises: incident capture must not take the supervisor down."""
    try:
        files: List[str] = []
        for pattern in _CAPTURE_GLOBS:
            files.extend(glob.glob(pidfile + pattern))
        files = sorted(set(f for f in files if os.path.isfile(f)))
        if not files:
            return None
        base = incidents_dir(pidfile)
        # names must be UNIQUE AND MONOTONE even across evictions: a
        # plain per-second name freed by eviction would be reused by the
        # next same-second capture, sort oldest, and get evicted as its
        # own predecessor.  Second AND fraction derive from ONE clock
        # read — two reads could straddle a second boundary and produce
        # "S+1.000..." sorting before "S.999...", the same inversion
        now_ns = time.time_ns()
        ts = time.strftime("%Y%m%d-%H%M%S",
                           time.localtime(now_ns // 1_000_000_000))
        frac = now_ns % 1_000_000_000
        bundle = os.path.join(base, f"{ts}.{frac:09d}")
        n = 1
        while os.path.exists(bundle):       # same-nanosecond paranoia
            bundle = os.path.join(base, f"{ts}.{frac:09d}.{n}")
            n += 1
        os.makedirs(bundle, exist_ok=True)
        prefix = os.path.basename(pidfile)
        captured = []
        for src in files:
            # keep names deployment-relative: <pidfile base name> +
            # suffix, so a bundle is self-describing when copied around
            name = prefix + src[len(pidfile):]
            try:
                shutil.copy2(src, os.path.join(bundle, name))
                captured.append(name)
            except OSError:
                continue
        manifest = {
            "reason": str(reason),
            "wall": time.time(),
            "iso": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "pidfile": os.path.abspath(pidfile),
            "files": captured,
        }
        if meta:
            manifest["meta"] = {
                k: v for k, v in meta.items()
                if isinstance(v, (str, int, float, bool, type(None)))}
        with open(os.path.join(bundle, "incident.json"), "w") as f:
            json.dump(manifest, f, indent=1)
        _evict_old(base, max_bundles)
        return bundle
    except Exception:  # noqa: BLE001 — forensics must not crash the
        return None    # supervisor


def _evict_old(base: str, max_bundles: int) -> None:
    try:
        bundles = sorted(
            d for d in glob.glob(os.path.join(base, "*"))
            if os.path.isdir(d))
        for d in bundles[: max(0, len(bundles) - max(1, int(max_bundles)))]:
            shutil.rmtree(d, ignore_errors=True)
    except OSError:
        pass


def list_incidents(pidfile: str) -> List[Dict]:
    """Bundle summaries, oldest first — the `manager incident --list`
    document."""
    out: List[Dict] = []
    for d in sorted(glob.glob(os.path.join(incidents_dir(pidfile), "*"))):
        if not os.path.isdir(d):
            continue
        entry = {"bundle": os.path.basename(d), "path": d}
        try:
            with open(os.path.join(d, "incident.json")) as f:
                man = json.load(f)
            entry.update({k: man.get(k) for k in ("reason", "iso", "wall")})
            entry["files"] = len(man.get("files") or ())
            if man.get("meta"):
                entry["meta"] = man["meta"]
        except (OSError, ValueError):
            entry["reason"] = "unknown (manifest unreadable)"
        out.append(entry)
    return out


def resolve_bundle(pidfile: str, which: Optional[str] = None
                   ) -> Optional[str]:
    """Bundle dir for `--show [which]`: a bundle name, an absolute path,
    or None/"latest" for the newest."""
    if which and os.path.isdir(which):
        return which
    bundles = list_incidents(pidfile)
    if not bundles:
        return None
    if which in (None, "", "latest"):
        return bundles[-1]["path"]
    for b in bundles:
        if b["bundle"] == which:
            return b["path"]
    return None


def load_timeline(bundle: str) -> List[Dict]:
    """Every span + flight-recorder event of a bundle, merged onto one
    wall timeline (``ts_wall``) via the PR 13 clock contract.  Health
    snapshots in the bundle provide the legacy clock fallback."""
    spools = sorted(
        glob.glob(os.path.join(bundle, "*.spans.jsonl"))
        + glob.glob(os.path.join(bundle, "*.spans.jsonl.1"))
        + glob.glob(os.path.join(bundle, "*.events.jsonl"))
        + glob.glob(os.path.join(bundle, "*.events.jsonl.1")))
    health_docs: Dict[str, Dict] = {}
    for path in glob.glob(os.path.join(bundle, "*.health.json")):
        try:
            with open(path) as f:
                doc = json.load(f)
            rid = str(doc.get("replica_id") or "")
            if rid:
                health_docs[rid] = doc
        except (OSError, ValueError):
            continue
    return tracecollect.merge_spools(spools, health_docs=health_docs)


def render(bundle: str, last: int = 200) -> Dict:
    """The `manager incident --show` document: manifest + the merged
    cross-process timeline (recorder events AND trace spans), trimmed to
    the last ``last`` entries, with per-process and per-kind counts so
    the shape of the incident reads before the detail."""
    manifest: Dict = {}
    try:
        with open(os.path.join(bundle, "incident.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        pass
    merged = load_timeline(bundle)
    t0 = merged[0].get("ts_wall", 0.0) if merged else 0.0
    timeline = []
    for s in merged[-max(1, int(last)):]:
        entry = {
            "t_ms": round((s.get("ts_wall", 0.0) - t0) * 1e3, 3),
            "kind": "event" if s.get("kind") == "event" else "span",
            "what": (s.get("event") if s.get("kind") == "event"
                     else s.get("stage")),
            "process": str(s.get("replica_id") or "unknown"),
        }
        for key in ("uri", "trace_id", "error", "rid", "state",
                    "count", "action", "reason", "replica", "index",
                    "clock_skewed", "stage", "tenant", "priority"):
            if s.get(key) is not None:
                entry[key] = s[key]
        if s.get("dur_s"):                 # zero-width marks stay terse
            entry["dur_s"] = s["dur_s"]
        timeline.append(entry)
    counts: Dict[str, int] = {}
    for s in merged:
        what = str(s.get("event") or s.get("stage"))
        counts[what] = counts.get(what, 0) + 1
    return {
        "bundle": bundle,
        "reason": manifest.get("reason"),
        "captured": manifest.get("iso"),
        "meta": manifest.get("meta"),
        "processes": sorted({str(s.get("replica_id") or "unknown")
                             for s in merged}),
        "entries_total": len(merged),
        "entries_shown": len(timeline),
        "events_by_kind": dict(sorted(counts.items(),
                                      key=lambda kv: -kv[1])),
        "errors": [s.get("error") for s in merged if s.get("error")][-20:],
        "timeline": timeline,
    }
