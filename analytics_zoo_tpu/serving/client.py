"""Serving client — InputQueue / OutputQueue / Client.

Reference parity: pyzoo/zoo/serving/client.py:62-160 — `InputQueue.enqueue_image`
(base64 → stream XADD) and `OutputQueue.query/dequeue` (result table reads), over any
queue backend (in-proc, file spool, or Redis).

Availability layer (PR 2): `timeout_s` at enqueue stamps ``deadline_ns`` on
the record — the engine sheds it with a ``deadline-exceeded`` error result
once the budget elapses (never wasting a predict slot on a dead request),
and `Client.query` polls against the SAME budget, so an enqueue+query pair
shares one end-to-end deadline.  ``deadline_ns`` is WALL-CLOCK epoch ns
(`time.time_ns`): with producer and engine on different hosts the deadline
is only as accurate as their clock sync (NTP drift stretches or shrinks
budgets by the skew) — keep budgets comfortably above the expected skew, or
run producer and engine on the same host for exact semantics.  `xadd` may raise `QueueFull`/`QueueClosed`
(admission control / graceful drain) — a typed rejection at enqueue time
instead of unbounded queue growth.

Horizontal replicas (PR 5): delivery is AT-LEAST-ONCE server-side — a
record claimed by a replica that crashes is reclaimed and re-served by a
survivor — but the result table stays exactly-one-result per uri (writes
are idempotent per key and redeliveries that already have a result are
suppressed), so nothing changes in how a client polls.  A result recovered
through failover carries ``"deliveries": n >= 2``
(`OutputQueue.deliveries`), and because results are keyed by uri, a
producer that re-enqueues the SAME uri after its own crash is idempotent
end to end.
"""

from __future__ import annotations

import base64
import json
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.common.observability import new_trace_id
from analytics_zoo_tpu.common.resilience import Deadline, RetryPolicy
from analytics_zoo_tpu.serving import wire as _wire
from analytics_zoo_tpu.serving.queues import (BaseQueue, QueueClosed,
                                              QueueFull)

logger = logging.getLogger(__name__)


def _stamp_deadline(record: Dict, timeout_s: Optional[float]) -> Dict:
    """Wire metadata stamped at enqueue: ``deadline_ns`` (when a budget was
    given) and — PR 4 — a ``trace_id`` riding next to it, so the engine's
    per-stage spans, quarantine errors, and the client's own deadline
    warnings all correlate on one id.  PR 13 adds the ingest timestamp
    (``trace_ctx.ts``, wall-clock ns): the engine computes the QUEUE-WAIT
    span (enqueue -> claim) from it, so native producers get the same
    latency attribution the HTTP gateway stamps for remote ones."""
    if timeout_s is not None:
        record["deadline_ns"] = time.time_ns() + int(timeout_s * 1e9)
    record.setdefault("trace_id", new_trace_id())
    record.setdefault("trace_ctx", {"ts": time.time_ns()})
    return record


class InputQueue:
    def __init__(self, queue: BaseQueue, shm_slots: int = 64,
                 shm_slot_bytes: Optional[int] = None):
        self.queue = queue
        # trace of the last enqueue, PER THREAD: two threads sharing one
        # client must not cross-wire each other's trace ids between the
        # enqueue and the caller reading this back
        self._tl = threading.local()
        # wire accounting (PR 7): cumulative bytes-on-the-wire + record
        # count, so the bench can report wire_bytes_per_record per format
        self.wire_bytes_enqueued = 0
        self.records_enqueued = 0
        # zero-copy shm lane (PR 7): ring created lazily on the first
        # wire="shm" enqueue, sized to the first payload unless pinned
        self._shm_slots = int(shm_slots)
        self._shm_slot_bytes = shm_slot_bytes
        self._shm_ring: Optional[_wire.ShmRing] = None
        self._shm_warned = False
        # briefly-full-queue retry (PR 17): a queue pinned at max_depth is
        # usually one engine batch-drain away from having room, so the
        # producer retries with capped jittered backoff instead of
        # surfacing a typed failure for a transient.  Tests swap the
        # policy for one with an injected sleep.
        self._full_retry = RetryPolicy(max_retries=4, base_delay_s=0.02,
                                       max_delay_s=0.5, jitter=0.5)

    def close(self) -> None:
        """Release the shm ring (producer side owns the segment).  Safe to
        call on a queue that never used the shm lane."""
        if self._shm_ring is not None:
            self._shm_ring.close()
            self._shm_ring.unlink()
            self._shm_ring = None

    @property
    def last_trace_id(self) -> Optional[str]:
        return getattr(self._tl, "trace_id", None)

    def enqueue_image(self, uri: str, image, resize=None, fmt: str = ".png",
                      quality: int = 95, device_uint8: bool = False,
                      timeout_s: Optional[float] = None) -> str:
        """image: path, encoded bytes, or HWC ndarray (encoded to `fmt`).

        fmt=".jpg" (round 5) ships compressed JPEG — the reference's actual
        wire format (ClusterServing PreProcessing consumed base64 JPEG) and
        ~10-20x smaller than raw floats on network queues.  device_uint8
        keeps the DECODED image uint8 all the way onto the accelerator
        (engine QuantizedTensor path, 4x less host->device transfer than
        f32); the model must then accept raw 0..255 inputs."""
        if isinstance(image, str):
            with open(image, "rb") as f:
                data = f.read()
        elif isinstance(image, (bytes, bytearray)):
            data = bytes(image)
        else:
            import cv2
            opts = ([int(cv2.IMWRITE_JPEG_QUALITY), int(quality)]
                    if fmt.lower() in (".jpg", ".jpeg") else [])
            ok, buf = cv2.imencode(fmt, np.asarray(image), opts)
            if not ok:
                raise ValueError(f"failed to encode image as {fmt}")
            data = buf.tobytes()
        record = {"uri": uri, "image": base64.b64encode(data).decode()}
        if resize is not None:
            record["resize"] = list(resize)
        if device_uint8:
            record["u8"] = 1
        return self._xadd(record, timeout_s)

    def _xadd_admitted(self, payload):
        """``queue.xadd`` with a bounded retry on ``QueueFull``.
        ``QueueClosed`` (draining) subclasses QueueFull but is TERMINAL —
        re-raised untouched, retrying a shutdown is pointless — and a
        server-stamped ``retry_after_s`` riding on the exception stretches
        the backoff (the admission 429 contract), capped by the policy's
        ``max_delay_s`` so a hostile hint cannot park the producer.  The
        final QueueFull re-raises as ITSELF, keeping the typed rejection
        callers already handle."""
        attempt = 0
        while True:
            try:
                return self.queue.xadd(payload)
            except QueueClosed:
                raise
            except QueueFull as e:
                if attempt >= self._full_retry.max_retries:
                    raise
                self._full_retry._sleep(
                    self._full_retry.delay_for(attempt, e))
                attempt += 1

    def _xadd(self, record: Dict, timeout_s: Optional[float]) -> str:
        record = _stamp_deadline(record, timeout_s)
        self._tl.trace_id = record["trace_id"]
        rid = self._xadd_admitted(record)
        # wire accounting: the b64 string dominates a legacy record's bytes;
        # the rest of the header is serialized here only because it is tiny
        b64 = record.get("b64") or record.get("image") or ""
        small = {k: v for k, v in record.items()
                 if k not in ("b64", "image")}
        self.wire_bytes_enqueued += len(b64) + len(json.dumps(small)) + 10
        self.records_enqueued += 1
        return rid

    def _xadd_frame(self, frame: bytes, trace_id: str) -> str:
        self._tl.trace_id = trace_id
        rid = self._xadd_admitted(frame)
        self.wire_bytes_enqueued += len(frame)
        self.records_enqueued += 1
        return rid

    def _shm_write(self, arr: np.ndarray):
        """Payload into the next ring slot (lazily creating the ring sized
        to the first tensor); returns the slot reference, or None when the
        payload outgrows the slots — the caller falls back to an inline
        frame rather than failing the enqueue."""
        if self._shm_ring is None:
            slot_bytes = self._shm_slot_bytes or max(arr.nbytes, 1 << 12)
            self._shm_ring = _wire.ShmRing(slots=self._shm_slots,
                                           slot_bytes=slot_bytes)
        try:
            return self._shm_ring.write(arr)
        except ValueError:
            if not self._shm_warned:
                self._shm_warned = True
                logger.warning(
                    "serving client: payload (%d bytes) exceeds the shm "
                    "slot size (%d); falling back to inline binary frames "
                    "— recreate the InputQueue with shm_slot_bytes >= the "
                    "largest tensor to stay zero-copy",
                    arr.nbytes, self._shm_ring.slot_bytes)
            return None

    def enqueue_tensor(self, uri: str, tensor: np.ndarray,
                       wire: str = "f32",
                       timeout_s: Optional[float] = None) -> str:
        """Enqueue one tensor record.  Wire formats:

        - ``"f32"`` / ``"int8"`` — the legacy base64-JSON record (int8 is
          symmetric per-tensor quantization, scale = absmax/127, kept int8
          until ON the accelerator).  PR 7 fixed the double copy here: the
          contiguous array feeds ``b64encode`` directly through the buffer
          protocol instead of materializing an intermediate ``tobytes()``.
        - ``"bin"`` (PR 7) — versioned binary frame: length-prefixed header
          JSON + raw little-endian payload.  No base64 (~25% fewer wire
          bytes), single producer-side copy (the payload memcpy into the
          frame), and the engine decodes with ``np.frombuffer`` instead of
          a base64 pass.
        - ``"shm"`` (PR 7) — zero-copy same-host lane: the payload goes
          into a shared-memory ring slot and only the frame HEADER crosses
          the queue; the engine materializes straight from the mapped
          segment.  Requires producer and engine on one host; see the
          README shm-lane caveats (ring sizing vs queue depth)."""
        if wire == "int8":
            a = np.asarray(tensor, np.float32)
            scale = float(np.max(np.abs(a)) / 127.0) or 1.0
            q = np.ascontiguousarray(
                np.clip(np.round(a / scale), -127, 127).astype(np.int8))
            # b64encode reads the array through the buffer protocol: one
            # output buffer, no tobytes() intermediate (PR 7 satellite)
            b64 = base64.b64encode(q).decode("ascii")
            _wire.COPY_STATS.record("b64_encode", q.nbytes)
            return self._xadd({
                "uri": uri,
                "b64": b64,
                "dtype": "<i1",
                "scale": scale,
                "shape": list(q.shape)}, timeout_s)
        if wire in ("bin", "shm"):
            arr = np.ascontiguousarray(np.asarray(tensor, "<f4"))
            record = _stamp_deadline({"uri": uri}, timeout_s)
            shm_ref = None
            if wire == "shm":
                # admission BEFORE the slot write: a rejected enqueue must
                # not burn a ring generation — the slot write is
                # irreversible and may lap a payload a still-queued record
                # references.  Best-effort under concurrent producers, the
                # same semantics as the queues' own cross-process cap.
                # xadd re-checks, so the shm lane pays the depth probe
                # twice per record — a deliberate trade: the lane's win is
                # the payload bytes, and slot integrity beats one probe.
                self.queue._check_admission()
                shm_ref = self._shm_write(arr)
            frame = _wire.encode_tensor_frame(
                uri, arr,
                deadline_ns=record.get("deadline_ns"),
                trace_id=record["trace_id"],
                shm_ref=shm_ref,
                trace_ctx=record.get("trace_ctx"))
            return self._xadd_frame(frame, record["trace_id"])
        if wire != "f32":
            raise ValueError(f"unknown wire format {wire!r} "
                             "(expected 'f32', 'int8', 'bin' or 'shm')")
        arr = np.ascontiguousarray(np.asarray(tensor, "<f4"))
        b64 = base64.b64encode(arr).decode("ascii")
        _wire.COPY_STATS.record("b64_encode", arr.nbytes)
        return self._xadd({
            "uri": uri,
            "b64": b64,
            "dtype": "<f4",
            "shape": list(arr.shape)}, timeout_s)


class OutputQueue:
    def __init__(self, queue: BaseQueue):
        self.queue = queue

    def query(self, uri: str, timeout_s: Optional[float] = 0.0,
              poll_s: float = 0.01,
              poll_max_s: float = 0.1,
              partials: bool = False) -> Optional[Dict]:
        """Poll for the record's result until `timeout_s` (None = until a
        result arrives).  A quarantined
        record resolves to an ``{"error": ...}`` dict (engine dead-letter
        path) — callers should check `is_error` rather than blocking on a
        value that will never arrive.

        Generation deployments (PR 12) stream ``{"partial": true,
        "tokens": [...]}`` results while a request decodes.  By default
        those are NOT returned — the poll keeps waiting for the terminal
        result (falling back to the freshest partial at the deadline so
        progress is never discarded); ``partials=True`` returns the first
        result of either kind, for callers consuming tokens-so-far.

        The poll interval backs off 1.5x per empty read up to
        ``poll_max_s`` (PR 3): a long wait costs O(log) round-trips against
        the backend instead of one per ``poll_s``."""
        deadline = Deadline(timeout_s)
        poll = poll_s
        partial = None
        while True:
            res = self.queue.get_result(uri)
            if res is not None:
                if partials or not self.is_partial(res):
                    return res
                partial = res
            if deadline.expired():
                return res if res is not None else partial
            time.sleep(min(poll, max(deadline.remaining(), 0.001)))
            poll = min(poll * 1.5, poll_max_s)

    def query_many(self, uris, timeout_s: Optional[float] = 0.0,
                   poll_s: float = 0.01,
                   poll_max_s: float = 0.25,
                   partials: bool = False) -> Dict[str, Optional[Dict]]:
        """Poll for MANY records with one batched ``get_results`` per sweep
        (PR 3): a 1k-record query costs one backend round-trip per poll
        instead of 1k, and the poll interval backs off while results are
        pending.  Returns ``{uri: result-or-None}``; unresolved uris map to
        None once ``timeout_s`` elapses (None = wait for all).  Streaming
        partials (PR 12) do not resolve a uri unless ``partials=True`` —
        at the deadline an unresolved uri falls back to its freshest
        partial rather than None."""
        uris = list(uris)              # may be a generator: iterated twice
        deadline = Deadline(timeout_s)
        got: Dict[str, Dict] = {}
        latest_partial: Dict[str, Dict] = {}
        pending = list(uris)
        poll = poll_s
        while pending:
            res = self.queue.get_results(pending)
            for u, r in res.items():
                if r is None:
                    continue
                if partials or not self.is_partial(r):
                    got[u] = r
                else:
                    latest_partial[u] = r
            before = len(pending)
            pending = [u for u in pending if u not in got]
            if not pending or deadline.expired():
                break
            if len(pending) < before:
                poll = poll_s          # stream is draining: stay responsive
            time.sleep(min(poll, max(deadline.remaining(), 0.001)))
            poll = min(poll * 1.5, poll_max_s)
        return {u: got.get(u, latest_partial.get(u)) for u in uris}

    def dequeue(self, uris) -> Dict[str, Dict]:
        """One batched read for all uris (no polling)."""
        return dict(self.queue.get_results(uris))

    @staticmethod
    def is_error(result: Optional[Dict]) -> bool:
        """True when a result is a dead-letter error marker."""
        return isinstance(result, dict) and "error" in result

    @staticmethod
    def is_partial(result: Optional[Dict]) -> bool:
        """True when a result is a streaming tokens-so-far partial (PR 12
        generation) — NOT a terminal state; keep polling for the final."""
        return isinstance(result, dict) and bool(result.get("partial"))

    @staticmethod
    def is_deadline_exceeded(result: Optional[Dict]) -> bool:
        """True when a result is a deadline-shed marker (engine- or
        client-side)."""
        return (OutputQueue.is_error(result)
                and str(result["error"]).startswith("deadline-exceeded"))

    @staticmethod
    def deliveries(result: Optional[Dict]) -> int:
        """How many times the record was delivered to a replica before this
        result was produced (PR 5 at-least-once).  1 = normal first
        delivery; >= 2 = the original replica died mid-flight and a
        survivor reclaimed and re-served it; 0 = no result yet."""
        if not isinstance(result, dict):
            return 0
        return int(result.get("deliveries", 1))

    def dead_letters(self) -> List[Dict]:
        """Quarantined records (uri + error + offending record when small)."""
        return self.queue.dead_letters()


class Client:
    """Enqueue + query with ONE end-to-end budget (PR 2 availability).

    ``enqueue_tensor(uri, x, timeout_s=2.0)`` stamps ``deadline_ns`` on the
    record; ``query(uri)`` then polls against the REMAINING budget of that
    same deadline — and resolves to a local ``deadline-exceeded`` error when
    it elapses, so a caller never hangs past its budget even if the engine
    died before shedding the record."""

    def __init__(self, queue: BaseQueue,
                 default_timeout_s: Optional[float] = None):
        self.input = InputQueue(queue)
        self.output = OutputQueue(queue)
        self.default_timeout_s = default_timeout_s
        self._deadline_ns: Dict[str, int] = {}
        # uri -> (trace_id, budget_s): kept in lockstep with _deadline_ns so
        # the deadline-expiry warning can name the trace and the budget
        self._trace_meta: Dict[str, Tuple[Optional[str], float]] = {}

    _MAX_TRACKED = 1024

    def _remember(self, uri: str, timeout_s: Optional[float]) -> None:
        now = time.time_ns()
        if len(self._deadline_ns) >= self._MAX_TRACKED:
            # fire-and-forget producers never query(): prune expired budgets
            # so the map stays bounded over a long-lived client
            self._deadline_ns = {u: d for u, d in self._deadline_ns.items()
                                 if d > now}
            if len(self._deadline_ns) >= self._MAX_TRACKED:
                # all still live (high rate x long budgets): evict the
                # soonest-expiring half so the map — and the per-enqueue
                # prune cost — stays hard-bounded; an evicted uri's query()
                # degrades to a plain poll instead of a synthesized
                # deadline-exceeded marker
                keep = sorted(self._deadline_ns.items(),
                              key=lambda kv: kv[1])[self._MAX_TRACKED // 2:]
                self._deadline_ns = dict(keep)
            self._trace_meta = {u: m for u, m in self._trace_meta.items()
                                if u in self._deadline_ns}
        if timeout_s is not None:
            self._deadline_ns[uri] = now + int(timeout_s * 1e9)
            self._trace_meta[uri] = (self.input.last_trace_id,
                                     float(timeout_s))

    def enqueue_tensor(self, uri: str, tensor, wire: str = "f32",
                       timeout_s: Optional[float] = None) -> str:
        timeout_s = timeout_s if timeout_s is not None \
            else self.default_timeout_s
        rid = self.input.enqueue_tensor(uri, tensor, wire=wire,
                                        timeout_s=timeout_s)
        self._remember(rid, timeout_s)
        return rid

    def enqueue_image(self, uri: str, image, timeout_s: Optional[float] = None,
                      **kwargs) -> str:
        timeout_s = timeout_s if timeout_s is not None \
            else self.default_timeout_s
        rid = self.input.enqueue_image(uri, image, timeout_s=timeout_s,
                                       **kwargs)
        self._remember(rid, timeout_s)
        return rid

    def query(self, uri: str, timeout_s: Optional[float] = None,
              poll_s: float = 0.01) -> Optional[Dict]:
        """Poll for `uri`'s result within the budget stamped at enqueue (or
        an explicit `timeout_s` override; with neither, wait until a result
        arrives).  Resolves to a `deadline-exceeded` error only once the
        STAMPED budget has truly elapsed — a short explicit poll that comes
        back empty mid-budget returns None, not a terminal error."""
        stamped = self._deadline_ns.get(uri)
        if timeout_s is None and stamped is not None:
            timeout_s = max((stamped - time.time_ns()) / 1e9, 0.0)
        elif timeout_s is None:
            # uri not tracked (never stamped, or evicted from the bounded
            # map): fall back to the client default rather than an
            # unbounded wait
            timeout_s = self.default_timeout_s
        res = self.output.query(uri, timeout_s=timeout_s, poll_s=poll_s)
        if res is not None:
            self._deadline_ns.pop(uri, None)
            self._trace_meta.pop(uri, None)
            return res
        if stamped is not None and time.time_ns() >= stamped:
            self._deadline_ns.pop(uri, None)
            trace_id, budget_s = self._trace_meta.pop(uri, (None, None))
            # structured expiry warning (PR 4): the old behaviour — a bare
            # None quietly turning into "not ready" — hid dropped requests;
            # the trace_id links this client-side timeout to whatever the
            # engine's spans say happened (or never happened) server-side
            logger.warning(
                "serving client: deadline expired uri=%s trace_id=%s "
                "budget_s=%s", uri, trace_id,
                "?" if budget_s is None else f"{budget_s:.3f}")
            err = {"error": "deadline-exceeded: client budget elapsed "
                            "before a result arrived"}
            if trace_id is not None:
                err["trace_id"] = trace_id
            return err
        return None

    def predict(self, uri: str, tensor, wire: str = "f32",
                timeout_s: Optional[float] = None) -> Optional[Dict]:
        """One-shot enqueue+wait sharing a single end-to-end deadline
        (no budget anywhere -> waits until the result arrives)."""
        self.enqueue_tensor(uri, tensor, wire=wire, timeout_s=timeout_s)
        return self.query(uri)
