"""Serving client — InputQueue / OutputQueue.

Reference parity: pyzoo/zoo/serving/client.py:62-160 — `InputQueue.enqueue_image`
(base64 → stream XADD) and `OutputQueue.query/dequeue` (result table reads), over any
queue backend (in-proc, file spool, or Redis).
"""

from __future__ import annotations

import base64
import time
from typing import Dict, Optional

import numpy as np

from analytics_zoo_tpu.serving.queues import BaseQueue


class InputQueue:
    def __init__(self, queue: BaseQueue):
        self.queue = queue

    def enqueue_image(self, uri: str, image, resize=None) -> str:
        """image: path, encoded bytes, or HWC ndarray (encoded to png)."""
        if isinstance(image, str):
            with open(image, "rb") as f:
                data = f.read()
        elif isinstance(image, (bytes, bytearray)):
            data = bytes(image)
        else:
            import cv2
            ok, buf = cv2.imencode(".png", np.asarray(image))
            if not ok:
                raise ValueError("failed to encode image")
            data = buf.tobytes()
        record = {"uri": uri, "image": base64.b64encode(data).decode()}
        if resize is not None:
            record["resize"] = list(resize)
        return self.queue.xadd(record)

    def enqueue_tensor(self, uri: str, tensor: np.ndarray) -> str:
        """Raw little-endian bytes, base64-wrapped (the reference's
        b64-encoded tensor wire format, serving/http style) — a Python-list
        round trip here cost ~5 ms/record to encode and ~10x that to decode,
        capping serving throughput at ~16 rec/s regardless of the model."""
        arr = np.ascontiguousarray(np.asarray(tensor, "<f4"))
        return self.queue.xadd({
            "uri": uri,
            "b64": base64.b64encode(arr.tobytes()).decode("ascii"),
            "dtype": "<f4",
            "shape": list(arr.shape)})


class OutputQueue:
    def __init__(self, queue: BaseQueue):
        self.queue = queue

    def query(self, uri: str, timeout_s: float = 0.0) -> Optional[Dict]:
        deadline = time.time() + timeout_s
        while True:
            res = self.queue.get_result(uri)
            if res is not None or time.time() >= deadline:
                return res
            time.sleep(0.01)

    def dequeue(self, uris) -> Dict[str, Dict]:
        return {u: self.queue.get_result(u) for u in uris}
