"""Serving client — InputQueue / OutputQueue.

Reference parity: pyzoo/zoo/serving/client.py:62-160 — `InputQueue.enqueue_image`
(base64 → stream XADD) and `OutputQueue.query/dequeue` (result table reads), over any
queue backend (in-proc, file spool, or Redis).
"""

from __future__ import annotations

import base64
import time
from typing import Dict, List, Optional

import numpy as np

from analytics_zoo_tpu.common.resilience import Deadline
from analytics_zoo_tpu.serving.queues import BaseQueue


class InputQueue:
    def __init__(self, queue: BaseQueue):
        self.queue = queue

    def enqueue_image(self, uri: str, image, resize=None, fmt: str = ".png",
                      quality: int = 95, device_uint8: bool = False) -> str:
        """image: path, encoded bytes, or HWC ndarray (encoded to `fmt`).

        fmt=".jpg" (round 5) ships compressed JPEG — the reference's actual
        wire format (ClusterServing PreProcessing consumed base64 JPEG) and
        ~10-20x smaller than raw floats on network queues.  device_uint8
        keeps the DECODED image uint8 all the way onto the accelerator
        (engine QuantizedTensor path, 4x less host->device transfer than
        f32); the model must then accept raw 0..255 inputs."""
        if isinstance(image, str):
            with open(image, "rb") as f:
                data = f.read()
        elif isinstance(image, (bytes, bytearray)):
            data = bytes(image)
        else:
            import cv2
            opts = ([int(cv2.IMWRITE_JPEG_QUALITY), int(quality)]
                    if fmt.lower() in (".jpg", ".jpeg") else [])
            ok, buf = cv2.imencode(fmt, np.asarray(image), opts)
            if not ok:
                raise ValueError(f"failed to encode image as {fmt}")
            data = buf.tobytes()
        record = {"uri": uri, "image": base64.b64encode(data).decode()}
        if resize is not None:
            record["resize"] = list(resize)
        if device_uint8:
            record["u8"] = 1
        return self.queue.xadd(record)

    def enqueue_tensor(self, uri: str, tensor: np.ndarray,
                       wire: str = "f32") -> str:
        """Raw little-endian bytes, base64-wrapped (the reference's
        b64-encoded tensor wire format, serving/http style) — a Python-list
        round trip here cost ~5 ms/record to encode and ~10x that to decode,
        capping serving throughput at ~16 rec/s regardless of the model.

        wire="int8" (round 5): symmetric per-tensor int8 quantization
        (scale = absmax/127) — 4x fewer bytes on the queue AND, because the
        engine keeps the tensor int8 until it is on the accelerator
        (InferenceModel.do_predict scales path, dequantized on device),
        4x less host->device transfer, which is the binding constraint when
        the device link is the bottleneck."""
        if wire == "int8":
            a = np.asarray(tensor, np.float32)
            scale = float(np.max(np.abs(a)) / 127.0) or 1.0
            q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
            return self.queue.xadd({
                "uri": uri,
                "b64": base64.b64encode(
                    np.ascontiguousarray(q).tobytes()).decode("ascii"),
                "dtype": "<i1",
                "scale": scale,
                "shape": list(q.shape)})
        if wire != "f32":
            raise ValueError(f"unknown wire format {wire!r} "
                             "(expected 'f32' or 'int8')")
        arr = np.ascontiguousarray(np.asarray(tensor, "<f4"))
        return self.queue.xadd({
            "uri": uri,
            "b64": base64.b64encode(arr.tobytes()).decode("ascii"),
            "dtype": "<f4",
            "shape": list(arr.shape)})


class OutputQueue:
    def __init__(self, queue: BaseQueue):
        self.queue = queue

    def query(self, uri: str, timeout_s: float = 0.0,
              poll_s: float = 0.01) -> Optional[Dict]:
        """Poll for the record's result until `timeout_s`.  A quarantined
        record resolves to an ``{"error": ...}`` dict (engine dead-letter
        path) — callers should check `is_error` rather than blocking on a
        value that will never arrive."""
        deadline = Deadline(timeout_s)
        while True:
            res = self.queue.get_result(uri)
            if res is not None or deadline.expired():
                return res
            time.sleep(min(poll_s, max(deadline.remaining(), 0.001)))

    def dequeue(self, uris) -> Dict[str, Dict]:
        return {u: self.queue.get_result(u) for u in uris}

    @staticmethod
    def is_error(result: Optional[Dict]) -> bool:
        """True when a result is a dead-letter error marker."""
        return isinstance(result, dict) and "error" in result

    def dead_letters(self) -> List[Dict]:
        """Quarantined records (uri + error + offending record when small)."""
        return self.queue.dead_letters()
