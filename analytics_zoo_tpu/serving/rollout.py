"""Rollout policy (PR 16): canary judging + rollout state, as PURE logic.

The supervisor (`manager.py:_run_supervisor`) owns the processes; this
module owns the decisions, so the judge and the state machine are unit
testable without forking a fleet:

- :class:`RolloutParams` — the ``rollout:`` config block (dwell window,
  burn-rate divergence knobs, error-rate ceiling, auto-rollback switch).
- :func:`judge` — one canary-vs-incumbents comparison over the
  per-replica health docs the supervisor already reads each pass.
  Returns ``None`` (healthy so far) or a human-readable divergence
  reason (→ auto-rollback).
- :func:`load_state` / :func:`save_state` — the supervisor's rollout
  state file (``<pidfile>.rollout.state.json``): phase, target/prior
  versions and the PER-REPLICA version assignments.  The assignments are
  the respawn pin: a replica that crashes mid-rollout respawns at its
  ASSIGNED version (incumbent or canary), never blindly at ``latest``.

Divergence policy: the canary is a fresh process, so its counters start
at zero and cumulative == since-canary-start.  It diverges when either

- its error fraction ``dead_lettered / (served + dead_lettered)`` exceeds
  ``error_rate_max`` (after ``min_records`` records, so one early
  quarantine can't condemn a version), or
- its windowed SLO burn rate exceeds ``max(burn_min,
  burn_factor * worst incumbent burn)`` — worse than the fleet AND bad in
  absolute terms, so a globally-degraded fleet doesn't scapegoat the
  canary.

Crash counting stays supervisor-side (it owns the wait() status); it
feeds :func:`judge` through ``canary_crashes``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

PHASES = ("idle", "canary", "rolling", "rollback")


class RolloutParams:
    """Parsed ``rollout:`` config block (all knobs optional)."""

    def __init__(self, canary_dwell_s: float = 30.0,
                 ready_timeout_s: float = 120.0,
                 burn_factor: float = 2.0,
                 burn_min: float = 1.0,
                 error_rate_max: float = 0.1,
                 min_records: int = 8,
                 crash_limit: int = 2,
                 auto_rollback: bool = True,
                 prewarm: bool = True):
        self.canary_dwell_s = float(canary_dwell_s)
        self.ready_timeout_s = float(ready_timeout_s)
        self.burn_factor = float(burn_factor)
        self.burn_min = float(burn_min)
        self.error_rate_max = float(error_rate_max)
        self.min_records = int(min_records)
        self.crash_limit = int(crash_limit)
        self.auto_rollback = bool(auto_rollback)
        self.prewarm = bool(prewarm)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "RolloutParams":
        d = d if isinstance(d, dict) else {}
        kw = {}
        for key in ("canary_dwell_s", "ready_timeout_s", "burn_factor",
                    "burn_min", "error_rate_max", "min_records",
                    "crash_limit", "auto_rollback", "prewarm"):
            if key in d and d[key] is not None:
                kw[key] = d[key]
        return cls(**kw)


def _error_fraction(doc: dict) -> tuple:
    """(errors, seen, fraction) from one health doc."""
    errors = int(doc.get("dead_lettered") or 0)
    served = int(doc.get("total_records") or 0)
    seen = errors + served
    return errors, seen, (errors / seen if seen else 0.0)


def _burn(doc: dict) -> float:
    slo = doc.get("slo") or {}
    try:
        return float(slo.get("burn_rate") or 0.0)
    except (TypeError, ValueError):
        return 0.0


def judge(canary: Optional[dict], incumbents: List[dict],
          params: RolloutParams, canary_crashes: int = 0) -> Optional[str]:
    """One judging pass.  ``canary`` is the canary replica's health doc
    (None when its snapshot is not readable yet — not a verdict),
    ``incumbents`` the remaining old-version replicas'.  Returns a
    divergence reason string, or None."""
    if canary_crashes > params.crash_limit:
        return (f"canary crashed {canary_crashes}x "
                f"(limit {params.crash_limit})")
    if canary is None:
        return None
    errors, seen, frac = _error_fraction(canary)
    if seen >= params.min_records and frac > params.error_rate_max:
        return (f"canary error rate {frac:.2f} "
                f"({errors}/{seen} records) > {params.error_rate_max:g}")
    cburn = _burn(canary)
    iburn = max([_burn(d) for d in incumbents], default=0.0)
    if cburn > max(params.burn_min, params.burn_factor * iburn):
        return (f"canary SLO burn {cburn:.2f} > "
                f"max({params.burn_min:g}, "
                f"{params.burn_factor:g} x incumbent {iburn:.2f})")
    return None


# -- rollout state file ------------------------------------------------------

def idle_state() -> dict:
    return {"phase": "idle", "target": None, "prior": None,
            "canary_index": None, "assignments": {}, "history": []}


def state_path(pidfile: str) -> str:
    return pidfile + ".rollout.state.json"


def request_path(pidfile: str) -> str:
    """`manager rollout <version>` writes the REQUEST here; the
    supervisor polls it (file-not-signal, same rationale as the scale
    file: survives a supervisor restart, inspectable)."""
    return pidfile + ".rollout.json"


def load_state(pidfile: str) -> dict:
    try:
        with open(state_path(pidfile)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return idle_state()
    base = idle_state()
    base.update(doc if isinstance(doc, dict) else {})
    # json keys are strings; assignments are index -> version
    base["assignments"] = {int(k): v for k, v in
                           (base.get("assignments") or {}).items()}
    return base


def save_state(pidfile: str, state: dict) -> None:
    path = state_path(pidfile)
    tmp = path + ".tmp"
    doc = dict(state)
    doc["assignments"] = {str(k): v for k, v in
                          (state.get("assignments") or {}).items()}
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
    except OSError:
        pass


def read_request(pidfile: str) -> Optional[dict]:
    try:
        with open(request_path(pidfile)) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def write_request(pidfile: str, target: str, ts: float) -> None:
    path = request_path(pidfile)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"target": target, "ts": ts}, f)
    os.replace(tmp, path)
