"""Deterministic, config-gated fault injection (PR 16) — the chaos half
of the rollout subsystem.

The rollback path must be exercised by REAL failures, not mocks: a
``params.faults`` block arms named fault points inside a live replica, and
every point is gated on the replica's ``model_version`` so a canary at v2
misbehaves while its v1 incumbents stay healthy — exactly the divergence
the canary judge must catch.

Config shape (``params.faults`` in config.yaml)::

    faults:
      predict_error:            # do_predict raises (rows quarantine)
        version: v2             # "*" = every version, absent = never
        after: 0                # records served cleanly before failing
      predict_slow:             # do_predict sleeps first (burn-rate fault)
        version: v2
        ms: 250
      warmup_crash:             # process exits mid-warm-up (os._exit) —
        version: v2             # a crash, not an exception, so the
                                # supervisor's respawn path is exercised
      readyz_delay:             # /readyz held not-ready after start
        version: v2
        seconds: 10
      claim_stall:              # read loop stalls before claiming (PR 17
        version: v2             # overload chaos: a backlog forms without
        seconds: 0.5            # real saturation)
        count: 10               # stalls injected before the point disarms
      admission_reject:         # admission gate rejects the next N
        version: v2             # requests with reason "fault"
        count: 5
        priority: best_effort   # optional: only this class is rejected
      decode_crash_after_n_tokens:   # process exits (os._exit) once the
        version: "*"                 # generation plane has produced n
        n: 12                        # tokens (PR 20 resume chaos)
        once: /tmp/crash.marker      # optional marker file: created at
                                     # fire, and any process that SEES it
                                     # skips the fault — exactly one crash
                                     # per deployment even under
                                     # supervisor respawn
      snapshot_corrupt:         # generation checkpoints are written with
        version: "*"            # a broken integrity checksum, so resume
                                # must detect + fall back loudly (PR 20)

Every knob is deterministic: no randomness, no time-of-day dependence —
the same config and record sequence produce the same failures, so the
acceptance tests assert exact outcomes.

:func:`corrupt_store_leaf` is the offline companion: it truncates one leaf
of a published weight store in place, the "corrupt store" fault the
registry's integrity verification must reject loudly.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)


class FaultError(RuntimeError):
    """Raised by an armed ``predict_error`` fault point — the message
    names the fault and version so quarantine markers are attributable."""


def _gate(spec, model_version: Optional[str]) -> Optional[dict]:
    """A fault point's config, iff it is armed for this replica's
    version.  ``version: "*"`` arms it everywhere; a missing/empty
    version selector never fires (faults are strictly opt-in)."""
    if not isinstance(spec, dict):
        return None
    sel = spec.get("version")
    if not sel:
        return None
    if sel != "*" and sel != (model_version or ""):
        return None
    return spec


class FaultInjector:
    """Holds the armed fault points for ONE replica (its parsed
    ``params.faults`` dict + its ``model_version``).  Inactive injectors
    (no faults config, or nothing gated to this version) cost nothing:
    the engine only wires a fault point when ``active`` is true for it."""

    def __init__(self, faults: Optional[dict],
                 model_version: Optional[str] = None):
        faults = faults if isinstance(faults, dict) else {}
        self.model_version = model_version
        self._predict_error = _gate(faults.get("predict_error"),
                                    model_version)
        self._predict_slow = _gate(faults.get("predict_slow"),
                                   model_version)
        self._warmup_crash = _gate(faults.get("warmup_crash"),
                                   model_version)
        self._readyz_delay = _gate(faults.get("readyz_delay"),
                                   model_version)
        self._claim_stall = _gate(faults.get("claim_stall"),
                                  model_version)
        self._admission_reject = _gate(faults.get("admission_reject"),
                                       model_version)
        self._decode_crash = _gate(
            faults.get("decode_crash_after_n_tokens"), model_version)
        self._snapshot_corrupt = _gate(faults.get("snapshot_corrupt"),
                                       model_version)
        self._predict_calls = 0
        self._claim_stalls_left = int(
            (self._claim_stall or {}).get("count", 1))
        self._admission_rejects_left = int(
            (self._admission_reject or {}).get("count", 1))

    # -- introspection -------------------------------------------------------
    @property
    def predict_active(self) -> bool:
        return (self._predict_error is not None
                or self._predict_slow is not None)

    @property
    def readyz_active(self) -> bool:
        return self._readyz_delay is not None

    @property
    def claim_active(self) -> bool:
        return self._claim_stall is not None

    @property
    def admission_active(self) -> bool:
        return self._admission_reject is not None

    @property
    def decode_crash_active(self) -> bool:
        return self._decode_crash is not None

    @property
    def snapshot_corrupt_active(self) -> bool:
        return self._snapshot_corrupt is not None

    @property
    def any_active(self) -> bool:
        return (self.predict_active or self.readyz_active
                or self.claim_active or self.admission_active
                or self._warmup_crash is not None
                or self.decode_crash_active
                or self.snapshot_corrupt_active)

    def describe(self) -> list:
        """Armed fault-point names (rides the health doc so an armed
        replica is visible from the outside)."""
        out = []
        if self._predict_error is not None:
            out.append("predict_error")
        if self._predict_slow is not None:
            out.append("predict_slow")
        if self._warmup_crash is not None:
            out.append("warmup_crash")
        if self._readyz_delay is not None:
            out.append("readyz_delay")
        if self._claim_stall is not None:
            out.append("claim_stall")
        if self._admission_reject is not None:
            out.append("admission_reject")
        if self._decode_crash is not None:
            out.append("decode_crash_after_n_tokens")
        if self._snapshot_corrupt is not None:
            out.append("snapshot_corrupt")
        return out

    # -- fault points ---------------------------------------------------------
    def wrap_predict(self, fn: Callable) -> Callable:
        """Wrap ``do_predict``: sleep first when ``predict_slow`` is
        armed, then raise :class:`FaultError` once ``predict_error``'s
        ``after`` budget of clean calls is spent.  The wrapper is
        instance-patched onto the model, which the engine's dispatch
        fallback keeps on the hot path (same mechanism the chaos tests
        use), so the injected failure flows through the REAL quarantine /
        bisect machinery."""

        def _predict(tensors, scales=None, **kw):
            self._predict_calls += 1
            slow = self._predict_slow
            if slow is not None:
                time.sleep(float(slow.get("ms", 100)) / 1000.0)
            err = self._predict_error
            if err is not None and \
                    self._predict_calls > int(err.get("after", 0)):
                raise FaultError(
                    f"injected predict_error (version "
                    f"{self.model_version or '*'}, call "
                    f"#{self._predict_calls})")
            return fn(tensors, scales=scales, **kw)

        return _predict

    def check_warmup(self) -> None:
        """``warmup_crash``: kill the PROCESS (not an exception — the
        warm-up loop catches those and degrades gracefully; the fault
        must look like a real crash so the supervisor's
        respawn-at-assigned-version path is what gets tested)."""
        if self._warmup_crash is not None:
            logger.error("faults: injected warmup_crash (version %s) — "
                         "exiting", self.model_version)
            os._exit(3)

    def take_claim_stall(self) -> float:
        """``claim_stall`` (PR 17): seconds the read loop should stall
        before this claim, 0.0 when disarmed or the ``count`` budget is
        spent.  The ENGINE sleeps (not this method) so tests can call it
        without waiting."""
        if self._claim_stall is None or self._claim_stalls_left <= 0:
            return 0.0
        self._claim_stalls_left -= 1
        return max(0.0, float(self._claim_stall.get("seconds", 0.5)))

    def take_admission_reject(self, priority: Optional[str] = None) -> bool:
        """``admission_reject`` (PR 17): True when the admission gate
        must reject THIS request (reason "fault").  An optional
        ``priority`` selector restricts the fault to one class; the
        ``count`` budget makes outcomes exact."""
        spec = self._admission_reject
        if spec is None or self._admission_rejects_left <= 0:
            return False
        want = spec.get("priority")
        if want and priority is not None and str(want) != str(priority):
            return False
        self._admission_rejects_left -= 1
        return True

    def take_decode_crash(self, generated_tokens: int) -> bool:
        """``decode_crash_after_n_tokens`` (PR 20): True when the process
        must die NOW — the generation plane has produced at least ``n``
        tokens and the optional ``once`` marker has not been claimed.
        Creating the marker BEFORE returning makes the crash
        exactly-once per deployment: the supervisor's respawn (and every
        sibling replica) sees the marker and skips the fault, so the
        chaos test gets ONE mid-decode kill instead of a crash loop.
        The ENGINE exits (``os._exit``, the ``warmup_crash`` pattern) so
        tests can call this without dying."""
        spec = self._decode_crash
        if spec is None:
            return False
        if generated_tokens < int(spec.get("n", 1)):
            return False
        marker = spec.get("once")
        if marker:
            try:
                # O_CREAT|O_EXCL: atomic claim — two replicas crossing
                # the threshold in the same tick still crash only once
                fd = os.open(str(marker),
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
            except FileExistsError:
                return False
            except OSError:
                return False               # unwritable marker: stay safe
        return True

    def readyz_block_reason(self, uptime_s: float) -> Optional[str]:
        """``readyz_delay``: a not-ready reason until ``seconds`` of
        uptime have passed (exercises the rollout's wait-for-ready
        timeout without harming served traffic)."""
        d = self._readyz_delay
        if d is None:
            return None
        hold = float(d.get("seconds", 10))
        if uptime_s < hold:
            return (f"fault-injected readyz_delay "
                    f"({uptime_s:.1f}/{hold:g}s)")
        return None


def corrupt_store_leaf(store_dir: str, leaf_index: int = 0,
                       truncate_to: int = 0) -> str:
    """Truncate one leaf file of a weight store IN PLACE (the manifest is
    left intact, so only integrity verification — not a directory listing
    — can tell).  Returns the corrupted file's path.  Test/bench helper
    for the "corrupt store leaf" fault: ``registry.verify`` must report
    it and the rollout must refuse the version."""
    import json
    with open(os.path.join(store_dir, "manifest.json")) as f:
        manifest = json.load(f)
    files = sorted({m["file"] for m in manifest["leaves"].values()})
    if not files:
        raise ValueError(f"{store_dir!r}: store has no leaves")
    target = os.path.join(store_dir, files[leaf_index % len(files)])
    with open(target, "r+b") as f:
        f.truncate(truncate_to)
    return target
