"""Cross-replica metrics aggregation (PR 10).

One replica's registry answers "how is THIS engine doing"; an elastic
deployment needs the FLEET view — the same numbers the autoscaler feeds
its policy and the operator asks ``manager metrics --all-replicas`` for.
This module is that aggregation, shared by both consumers:

- ``replica_docs(pidfile, ...)`` — one health document per replica slot:
  scraped over HTTP from the replica's probe port (``http_port + i``, the
  exact document ``/healthz`` serves) with the ``<pidfile>.r<i>.health.json``
  snapshot as the fallback when the port is unreachable (gateway off, or
  the replica just died — the snapshot then reports a stale heartbeat
  instead of vanishing silently).
- ``aggregate_health(docs)`` — the fleet snapshot: cumulative counters
  SUMMED across replicas, queue depth/pending taken as the MAX (every
  replica reports the same shared queue — summing would multiply it by N),
  per-replica heartbeat ages, and the conservative (max) cross-replica
  stage p99s.
- ``fleet_metrics(docs)`` — the ``manager metrics --all-replicas`` JSON
  document: the PR 2/3 per-engine metrics shape, fleet-wide, with a
  per-replica breakdown.
- ``scrape_prometheus(...)`` / ``merge_prometheus(texts)`` — fleet-wide
  Prometheus exposition: per-series SUM across replicas (counters and
  histogram ``_bucket``/``_sum``/``_count`` series add correctly), with
  the shared-queue gauges (``serving_queue_depth``,
  ``serving_dead_letters``) merged as MAX for the same reason as above.

Pure stdlib: importable from the manager CLI and the autoscaler without
dragging in jax.
"""

from __future__ import annotations

import json
import os
import re
import time
import urllib.error
import urllib.request
from typing import Dict, Iterable, List, Optional, Tuple

# gauges that report a SHARED resource (the one queue every replica reads)
# or a RATIO (the SLO burn rate, PR 13 — summing per-replica burn rates
# would overstate the fleet's budget spend; the max is the conservative
# fleet verdict): merged as MAX, never summed
SHARED_MAX_METRICS = frozenset({"serving_queue_depth",
                                "serving_dead_letters",
                                "serving_slo_burn_rate",
                                "serving_slo_latency_objective_ms",
                                # PR 17: a ladder STAGE is an ordinal,
                                # not a quantity — the fleet's brownout
                                # verdict is its worst replica's
                                "serving_brownout_stage"})


def read_scale(pidfile: str, default: int = 0) -> int:
    """The supervisor's desired replica count from ``<pidfile>.replicas``
    (what ``manager scale N`` writes) — the one reader every consumer
    (fleet scrape, LB membership, ManagerFleet, the metrics CLI) shares."""
    try:
        with open(pidfile + ".replicas") as f:
            return max(0, int(f.read().strip()))
    except (OSError, ValueError):
        return default


def _http_json(url: str, timeout: float = 2.0) -> Optional[Dict]:
    """GET a JSON document; non-2xx responses that still carry a JSON body
    (``/healthz`` answers 503 with the full health doc while draining or
    failed) are parsed too."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            return json.loads(e.read())
        except (ValueError, OSError):
            return None
    except Exception:  # noqa: BLE001 — unreachable / refused / timeout
        return None


def replica_docs(pidfile: str, http_host: str = "127.0.0.1",
                 http_port: Optional[int] = None,
                 count: Optional[int] = None) -> Dict[int, Dict]:
    """Health documents per replica slot.  ``count`` bounds the slots
    probed (defaults to the supervisor's ``<pidfile>.replicas`` target);
    slots with neither a reachable probe port nor a health snapshot are
    simply absent from the result.  Snapshot-sourced docs get their
    ``heartbeat_age_s`` aged by the snapshot's own staleness, so a replica
    that died between snapshots reads as stale, not frozen-fresh."""
    if count is None:
        count = read_scale(pidfile)
    docs: Dict[int, Dict] = {}
    for i in range(max(0, int(count))):
        doc = None
        if http_port:
            doc = _http_json(f"http://{http_host}:{http_port + i}/healthz")
        if doc is None:
            try:
                with open(f"{pidfile}.r{i}.health.json") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                doc = None
            if isinstance(doc, dict):
                staleness = max(0.0, time.time() - float(doc.get("ts", 0)))
                doc["heartbeat_age_s"] = max(
                    float(doc.get("heartbeat_age_s", 0.0)), staleness)
                doc["snapshot_stale_s"] = round(staleness, 3)
        if isinstance(doc, dict):
            docs[i] = doc
    return docs


def _stage_p99(doc: Dict, stage: str) -> Optional[float]:
    try:
        v = doc["stages"][stage]["p99_ms"]
        return None if v is None else float(v)
    except (KeyError, TypeError, ValueError):
        return None


def _opt_max(values: Iterable[Optional[float]]) -> Optional[float]:
    vals = [v for v in values if v is not None]
    return max(vals) if vals else None


def aggregate_health(docs: Dict[int, Dict]) -> Dict:
    """The fleet snapshot the autoscaler consumes (see module docstring
    for the sum-vs-max rules)."""
    served = shed = quarantined = reclaimed = duplicates = restarts = 0
    depth = pending = dead_letters = 0
    hb: Dict[str, float] = {}
    knobs: Optional[Dict] = None
    alive = 0
    warming = 0                      # replicas still compiling (PR 11)
    cold_start: Optional[float] = None   # slowest measured cold start
    slo_burn: Optional[float] = None     # worst replica burn rate (PR 13)
    slo_violations = 0
    # overload armor (PR 17): admission tallies SUM (each replica's gate
    # is its own stream of verdicts); the brownout stage is an ordinal —
    # the fleet is as browned-out as its WORST replica
    admitted = rejected = 0
    rejected_by: Dict[str, int] = {}
    admission_seen = False
    brownout_stage: Optional[int] = None
    # resource accounting (PR 15): HBM components SUM across replicas
    # (each replica pins its own copy), per-process stats sum with a max
    # alongside RSS so one bloated replica stands out
    res = {"weights_bytes": 0, "kv_state_bytes": 0, "executables": 0,
           "executable_code_bytes": 0, "total_bytes": 0}
    res_seen = False
    proc = {"rss_bytes": 0, "rss_max_bytes": 0, "cpu_seconds": 0.0,
            "open_fds": 0, "threads": 0}
    proc_seen = False
    # model-version mix (PR 16): during a rollout the fleet is
    # intentionally heterogeneous — surface version -> replica count so
    # `manager status` shows the canary/rolling split at a glance
    versions: Dict[str, int] = {}
    # paged KV pool (PR 18): block capacity/occupancy and prefix-cache
    # traffic SUM across replicas (each owns its own pool); exhaustion
    # stalls sum so an under-provisioned fleet shows one number
    gen_pool = {"blocks": 0, "free_blocks": 0, "used_blocks": 0,
                "prefix_hits": 0, "prefix_misses": 0,
                "prefix_evictions": 0, "exhausted": 0,
                "active_slots": 0}
    gen_pool_seen = False
    # generation continuity (PR 20): resume traffic SUMS across replicas
    # (a crash on one replica surfaces as a resume on another — fleet
    # totals are the only view where both sides of the handoff meet)
    continuity = {"resumed": 0, "resume_failed": 0, "checkpoints": 0,
                  "snapshot_bytes": 0}
    continuity_seen = False
    # usage attribution (PR 19): per-tenant cumulative totals SUM across
    # replicas (each meters its own traffic; the LB spreads one tenant
    # over many replicas)
    usage_tenants: Dict[str, Dict[str, float]] = {}
    usage_seen = False
    for i, doc in sorted(docs.items()):
        served += int(doc.get("total_records", 0))
        shed += int(doc.get("shed", 0))
        quarantined += int(doc.get("dead_lettered", 0))
        reclaimed += int(doc.get("reclaimed", 0))
        duplicates += int(doc.get("duplicates", 0))
        restarts += sum(w.get("restart_count", 0)
                        for w in (doc.get("workers") or {}).values())
        if doc.get("running"):
            alive += 1
        q = doc.get("queue") or {}
        depth = max(depth, int(q.get("depth", 0) or 0))
        pending = max(pending, int(q.get("pending", 0) or 0))
        dead_letters = max(dead_letters, int(q.get("dead_letters", 0) or 0))
        rid = doc.get("replica_id") or f"replica-{i}"
        try:
            hb[rid] = float(doc.get("heartbeat_age_s", float("inf")))
        except (TypeError, ValueError):
            hb[rid] = float("inf")
        if knobs is None and isinstance(doc.get("knobs"), dict):
            knobs = doc["knobs"]
        mv = doc.get("model_version")
        if mv is not None:
            versions[str(mv)] = versions.get(str(mv), 0) + 1
        w = doc.get("warmup") or {}
        if w.get("state") in ("pending", "warming"):
            warming += 1
        cs = doc.get("cold_start_s")
        if isinstance(cs, (int, float)):
            cold_start = cs if cold_start is None else max(cold_start, cs)
        slo = doc.get("slo") or {}
        br = slo.get("burn_rate")
        if isinstance(br, (int, float)):
            slo_burn = br if slo_burn is None else max(slo_burn, br)
        wv = slo.get("window_violations")
        if isinstance(wv, int):
            slo_violations += wv
        adm = doc.get("admission") or {}
        if isinstance(adm.get("admitted"), int):
            admission_seen = True
            admitted += int(adm.get("admitted") or 0)
            rejected += int(adm.get("rejected") or 0)
            for reason, n in (adm.get("rejected_by_reason") or {}).items():
                if isinstance(n, int):
                    rejected_by[reason] = rejected_by.get(reason, 0) + n
        bo = doc.get("brownout") or {}
        if isinstance(bo.get("stage"), int):
            brownout_stage = bo["stage"] if brownout_stage is None \
                else max(brownout_stage, bo["stage"])
        r = doc.get("resources") or {}
        if isinstance(r.get("weights_bytes"), (int, float)):
            res_seen = True
            res["weights_bytes"] += int(r.get("weights_bytes") or 0)
            res["kv_state_bytes"] += int(r.get("kv_state_bytes") or 0)
            res["total_bytes"] += int(r.get("total_bytes") or 0)
            exes = r.get("executables") or {}
            res["executables"] += int(exes.get("count") or 0)
            res["executable_code_bytes"] += int(exes.get("code_bytes")
                                                or 0)
        g = doc.get("generation") or {}
        gp = g.get("pool") or {}
        if isinstance(gp.get("blocks"), int):
            gen_pool_seen = True
            for k in ("blocks", "free_blocks", "used_blocks",
                      "prefix_hits", "prefix_misses", "prefix_evictions",
                      "exhausted"):
                gen_pool[k] += int(gp.get(k) or 0)
            gen_pool["active_slots"] += int(g.get("active_slots") or 0)
        if isinstance(g.get("resumed"), int):
            continuity_seen = True
            for k in continuity:
                continuity[k] += int(g.get(k) or 0)
        u = doc.get("usage") or {}
        if isinstance(u.get("tenants"), dict):
            usage_seen = True
            for tenant, vals in u["tenants"].items():
                dst = usage_tenants.setdefault(str(tenant), {})
                if isinstance(vals, dict):
                    for k, v in vals.items():
                        if isinstance(v, (int, float)):
                            dst[k] = dst.get(k, 0) + v
        pr = doc.get("process") or {}
        if isinstance(pr.get("rss_bytes"), (int, float)):
            proc_seen = True
            proc["rss_bytes"] += int(pr.get("rss_bytes") or 0)
            proc["rss_max_bytes"] = max(proc["rss_max_bytes"],
                                        int(pr.get("rss_bytes") or 0))
            proc["cpu_seconds"] += float(pr.get("cpu_seconds") or 0.0)
            proc["open_fds"] += int(pr.get("open_fds") or 0)
            proc["threads"] += int(pr.get("threads") or 0)
    return {"replicas_total": len(docs),
            "replicas_alive": alive,
            "replicas_warming": warming,
            "cold_start_s": cold_start,
            "served": served, "shed": shed, "quarantined": quarantined,
            "reclaimed": reclaimed, "duplicates": duplicates,
            "restarts": restarts,
            "queue_depth": depth, "pending": pending,
            "dead_letters": dead_letters,
            "heartbeat_ages": hb,
            "e2e_p99_ms": _opt_max(_stage_p99(d, "e2e")
                                   for d in docs.values()),
            "preprocess_p99_ms": _opt_max(_stage_p99(d, "preprocess")
                                          for d in docs.values()),
            "predict_p99_ms": _opt_max(_stage_p99(d, "predict")
                                       for d in docs.values()),
            # SLO attribution (PR 13): worst replica burn rate + windowed
            # violation count — the signal a per-model autoscaler
            # (ROADMAP item 1) will judge overload on
            "slo_burn_rate": slo_burn,
            "slo_window_violations": slo_violations,
            # overload armor (PR 17): summed gate verdicts + the worst
            # replica's brownout stage (None = no replica reports them)
            "admitted": admitted if admission_seen else None,
            "rejected": rejected if admission_seen else None,
            "rejected_by_reason": rejected_by if admission_seen else None,
            "brownout_stage": brownout_stage,
            # resource accounting (PR 15): fleet HBM decomposition +
            # summed per-process resources (None when no replica reports
            # them yet — old snapshots mid-rolling-upgrade)
            # version mix (PR 16): None while every replica is
            # unversioned (pre-registry deployments)
            "versions": versions or None,
            "resources": res if res_seen else None,
            # paged KV (PR 18): summed pool capacity/occupancy + prefix
            # traffic (None when no replica runs a paged batcher)
            "kv_pool": dict(gen_pool, occupancy=round(
                gen_pool["used_blocks"] / max(1, gen_pool["blocks"]), 4))
            if gen_pool_seen else None,
            # generation continuity (PR 20): summed resume/checkpoint
            # traffic (None when no replica runs a generation plane)
            "continuity": dict(continuity) if continuity_seen else None,
            "process": dict(proc, cpu_seconds=round(proc["cpu_seconds"],
                                                    3))
            if proc_seen else None,
            # usage attribution (PR 19): summed per-tenant totals (None
            # when no replica reports a usage block — pre-PR-19 snapshots)
            "usage": {t: {k: (round(v, 6) if isinstance(v, float)
                              and v != int(v) else int(v))
                          for k, v in sorted(vals.items())}
                      for t, vals in sorted(usage_tenants.items())}
            if usage_seen else None,
            "knobs": knobs}


def fleet_metrics(docs: Dict[int, Dict], lb: Optional[Dict] = None) -> Dict:
    """``manager metrics --all-replicas`` JSON: the familiar per-engine
    metrics document shape, fleet-wide, plus a per-replica breakdown so an
    imbalanced fleet is visible at a glance.  ``lb`` (PR 13 satellite): the
    front door's telemetry snapshot (``lb_snapshot``) — its
    requests/retries/member gauges join the document instead of staying
    invisible in the supervisor process."""
    agg = aggregate_health(docs)
    per_replica = {}
    for i, doc in sorted(docs.items()):
        e2e = (doc.get("stages") or {}).get("e2e") or {}
        member = {
            "served": doc.get("total_records", 0),
            "shed": doc.get("shed", 0),
            "quarantined": doc.get("dead_lettered", 0),
            "reclaimed": doc.get("reclaimed", 0),
            "running": bool(doc.get("running")),
            "heartbeat_age_s": doc.get("heartbeat_age_s"),
            "p99_ms": e2e.get("p99_ms")}
        if doc.get("model_version") is not None:
            member["model_version"] = doc["model_version"]
        # warm-up visibility (PR 11): a replica that exists but is not
        # taking traffic yet shows `warming (k/n)` here, so `manager
        # metrics --all-replicas` explains the gap between desired and
        # serving capacity
        w = doc.get("warmup") or {}
        if w.get("state") and w["state"] != "off":
            member["warmup"] = {k: w.get(k) for k in
                                ("state", "compiled", "total", "seconds")}
        if doc.get("cold_start_s") is not None:
            member["cold_start_s"] = doc["cold_start_s"]
        pr = doc.get("process") or {}
        if isinstance(pr.get("rss_bytes"), (int, float)):
            member["rss_bytes"] = int(pr["rss_bytes"])
        r = doc.get("resources") or {}
        if isinstance(r.get("total_bytes"), (int, float)):
            member["hbm_bytes"] = int(r["total_bytes"])
        per_replica[doc.get("replica_id") or f"replica-{i}"] = member
    out = {"replicas": {"total": agg["replicas_total"],
                        "alive": agg["replicas_alive"],
                        "warming": agg["replicas_warming"]},
            "cold_start_s": agg["cold_start_s"],
            "served": agg["served"],
            "quarantined": agg["quarantined"],
            "shed": agg["shed"],
            "reclaimed": agg["reclaimed"],
            "duplicates": agg["duplicates"],
            "restarts": agg["restarts"],
            "queue_depth": agg["queue_depth"],
            "pending": agg["pending"],
            "dead_letters": agg["dead_letters"],
            "latency_ms": {"p50": _opt_max(
                (d.get("stages", {}).get("e2e") or {}).get("p50_ms")
                for d in docs.values()),
                "p99": agg["e2e_p99_ms"]},
           "per_replica": per_replica}
    if agg.get("slo_burn_rate") is not None:
        out["slo"] = {"burn_rate": agg["slo_burn_rate"],
                      "window_violations": agg["slo_window_violations"]}
    if agg.get("admitted") is not None:
        out["admission"] = {
            "admitted": agg["admitted"],
            "rejected": agg["rejected"],
            "rejected_by_reason": agg["rejected_by_reason"]}
    if agg.get("brownout_stage") is not None:
        out["brownout_stage"] = agg["brownout_stage"]
    # version mix (PR 16): which model versions the fleet is serving —
    # heterogeneous exactly while a rollout is in flight
    if agg.get("versions"):
        out["versions"] = agg["versions"]
    # resource accounting (PR 15): the fleet HBM decomposition + summed
    # per-process stats ride the metrics doc next to the SLO block
    if agg.get("resources") is not None:
        out["resources"] = agg["resources"]
    if agg.get("process") is not None:
        out["process"] = agg["process"]
    # usage attribution (PR 19): the fleet-summed per-tenant block —
    # `manager metrics --all-replicas` shows who used what
    if agg.get("usage") is not None:
        out["usage"] = agg["usage"]
    summary = lb_summary(lb)
    if summary is not None:
        out["lb"] = summary
    return out


# -- Prometheus exposition merge ------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)$")


def scrape_prometheus(count: int, http_host: str = "127.0.0.1",
                      http_port: Optional[int] = None,
                      timeout: float = 2.0) -> List[str]:
    """One Prometheus text exposition per reachable replica probe port."""
    texts: List[str] = []
    if not http_port:
        return texts
    for i in range(max(0, int(count))):
        url = f"http://{http_host}:{http_port + i}/metrics?format=prom"
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                texts.append(resp.read().decode())
        except Exception:  # noqa: BLE001 — dead slot: skip
            continue
    return texts


def merge_prometheus(texts: Iterable[str],
                     max_names: frozenset = SHARED_MAX_METRICS) -> str:
    """Merge N replicas' text expositions into one fleet exposition:
    identical series (same name + label set) SUM — counters add, histogram
    ``_bucket``/``_sum``/``_count`` series add into a valid fleet
    histogram — except the shared-resource gauges in ``max_names``, which
    take the MAX (every replica reports the same queue).  Series unique to
    one replica (e.g. per-replica heartbeat gauges) pass through.  HELP /
    TYPE lines keep their first-seen text; series keep first-seen order."""
    help_type: Dict[str, List[str]] = {}
    family_order: List[str] = []
    series: Dict[Tuple[str, str], float] = {}
    series_order: Dict[str, List[Tuple[str, str]]] = {}
    series_family: Dict[Tuple[str, str], str] = {}

    def family_of(sample_name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if sample_name.endswith(suffix) and \
                    sample_name[: -len(suffix)] in help_type:
                return sample_name[: -len(suffix)]
        return sample_name

    for text in texts:
        for line in (text or "").splitlines():
            line = line.rstrip()
            if not line:
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                    name = parts[2]
                    if name not in help_type:
                        help_type[name] = []
                        family_order.append(name)
                        series_order[name] = []
                    if len(help_type[name]) < 2:
                        # first replica's HELP+TYPE pair wins
                        prefix = f"# {parts[1]} {name}"
                        if not any(h.startswith(prefix)
                                   for h in help_type[name]):
                            help_type[name].append(line)
                continue
            m = _SAMPLE_RE.match(line)
            if not m:
                continue
            name, labels, raw = m.group(1), m.group(2) or "", m.group(3)
            try:
                value = float(raw)
            except ValueError:
                continue
            fam = family_of(name)
            if fam not in series_order:
                family_order.append(fam)
                series_order[fam] = []
                help_type.setdefault(fam, [])
            key = (name, labels)
            if key not in series:
                series[key] = value
                series_order[fam].append(key)
                series_family[key] = fam
            elif value == value:           # skip NaN contributions
                if series[key] != series[key]:
                    series[key] = value
                elif fam in max_names:
                    series[key] = max(series[key], value)
                else:
                    series[key] += value
    out: List[str] = []
    for fam in family_order:
        out.extend(help_type.get(fam, []))
        for name, labels in series_order.get(fam, []):
            v = series[(name, labels)]
            if v != v:
                sval = "NaN"
            elif v in (float("inf"), float("-inf")):
                sval = "+Inf" if v > 0 else "-Inf"
            elif float(v) == int(v):
                sval = str(int(v))
            else:
                sval = repr(float(v))
            out.append(f"{name}{labels} {sval}")
    return "\n".join(out) + "\n"


def lb_snapshot(pidfile: str) -> Optional[Dict]:
    """The LB telemetry snapshot the supervisor persists each pass
    (``<pidfile>.lb.json``: registry snapshot + Prometheus exposition) —
    how ``manager metrics --all-replicas`` sees the front door without
    reaching into the supervisor process."""
    try:
        with open(pidfile + ".lb.json") as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def lb_summary(snap: Optional[Dict]) -> Optional[Dict]:
    """Compact LB block for the fleet metrics document (PR 13 satellite):
    requests by endpoint/code, re-routes, member rotation state — the
    series that were invisible to the fleet doc while they lived only in
    the supervisor's in-process registry."""
    if not isinstance(snap, dict):
        return None
    reg = snap.get("snapshot") or {}

    def values(name):
        return (reg.get(name) or {}).get("values") or []

    requests: Dict[str, float] = {}
    total = 0.0
    for v in values("lb_requests_total"):
        labels = v.get("labels") or {}
        key = f"{labels.get('endpoint', '?')}:{labels.get('code', '?')}"
        val = float(v.get("value", 0) or 0)
        requests[key] = requests.get(key, 0.0) + val
        total += val
    retries = sum(float(v.get("value", 0) or 0)
                  for v in values("lb_retries_total"))

    def gauge(name):
        vals = values(name)
        return float(vals[0].get("value", 0) or 0) if vals else None

    return {"url": snap.get("url"),
            "ts": snap.get("ts"),
            "requests_total": total,
            "requests": requests,
            "retries_total": retries,
            "members_total": gauge("lb_members_total"),
            "members_ready": gauge("lb_members_ready")}


def autoscaler_snapshot(pidfile: str) -> Optional[Dict]:
    """The controller snapshot the supervisor persists each tick
    (``<pidfile>.autoscaler.json``) — decision counters, target gauges and
    the decision log — so ``manager metrics`` can surface controller
    activity without reaching into the supervisor process."""
    try:
        with open(pidfile + ".autoscaler.json") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
