"""Serving queues — the Redis-stream transport with pluggable backends.

Reference parity: Cluster Serving's Redis streams (`image_stream` XADD in the client,
result HSET table — serving/ClusterServing.scala:106-307, pyzoo client.py:62-160).
Backends:
- `InProcQueue`  — same-process deque (tests, embedded serving)
- `FileQueue`    — spool-directory stream + result table (cross-process, no deps)
- `RedisQueue`   — real Redis when the `redis` package + server are available

All share the same four calls: xadd / read_batch / put_result / get_result,
plus the dead-letter side channel (PR 1 resilience): `put_error` quarantines a
poisoned record — it writes an `{"error": ...}` result under the record's key
(so a waiting client unblocks and SEES the failure instead of hanging) and
appends `{"uri", "error", "record"?}` to a dead-letter stream that
`dead_letters()` exposes for inspection/replay.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional, Tuple


class BaseQueue:
    def xadd(self, record: Dict) -> str:
        raise NotImplementedError

    def read_batch(self, max_items: int, timeout_s: float = 0.1) -> List[Tuple[str, Dict]]:
        raise NotImplementedError

    def put_result(self, key: str, value: Dict) -> None:
        raise NotImplementedError

    def get_result(self, key: str) -> Optional[Dict]:
        raise NotImplementedError

    def result_count(self) -> int:
        raise NotImplementedError

    # -- dead-letter side channel (PR 1 resilience) --------------------------
    def put_error(self, key: str, error: str,
                  record: Optional[Dict] = None) -> None:
        """Quarantine one poisoned record: write an error RESULT the client
        can see (same key it is polling) and append a dead-letter entry."""
        raise NotImplementedError

    def dead_letters(self) -> List[Dict]:
        """All quarantined entries, oldest first."""
        raise NotImplementedError

    def dead_letter_count(self) -> int:
        return len(self.dead_letters())

    def trim(self, max_len: int) -> None:
        """Memory guard (ClusterServing.scala:134-140 XTRIM analog)."""


def _dead_letter_entry(key: str, error: str,
                       record: Optional[Dict]) -> Dict:
    entry = {"uri": key, "error": str(error)}
    if record is not None:
        entry["record"] = record
    return entry


class InProcQueue(BaseQueue):
    def __init__(self):
        self._stream = deque()
        self._results: Dict[str, Dict] = {}
        self._dead: List[Dict] = []
        self._lock = threading.Lock()

    def xadd(self, record):
        rid = record.get("uri") or str(uuid.uuid4())
        with self._lock:
            self._stream.append((rid, record))
        return rid

    def read_batch(self, max_items, timeout_s=0.1):
        deadline = time.time() + timeout_s
        out = []
        while len(out) < max_items:
            with self._lock:
                while self._stream and len(out) < max_items:
                    out.append(self._stream.popleft())
            if out or time.time() > deadline:
                break
            time.sleep(0.005)
        return out

    def put_result(self, key, value):
        with self._lock:
            self._results[key] = value

    def get_result(self, key):
        with self._lock:
            return self._results.get(key)

    def result_count(self):
        with self._lock:
            return len(self._results)

    def put_error(self, key, error, record=None):
        with self._lock:
            self._results[key] = {"error": str(error)}
            self._dead.append(_dead_letter_entry(key, error, record))

    def dead_letters(self):
        with self._lock:
            return list(self._dead)

    def trim(self, max_len):
        with self._lock:
            while len(self._stream) > max_len:
                self._stream.popleft()


class FileQueue(BaseQueue):
    """Spool-dir stream: records are json files named <seq>-<id>.json in stream/,
    results live in results/<key>.json.  Safe for one consumer, many producers."""

    def __init__(self, root: str):
        self.root = root
        self.stream_dir = os.path.join(root, "stream")
        self.result_dir = os.path.join(root, "results")
        self.dead_dir = os.path.join(root, "dead-letter")
        os.makedirs(self.stream_dir, exist_ok=True)
        os.makedirs(self.result_dir, exist_ok=True)
        os.makedirs(self.dead_dir, exist_ok=True)

    def xadd(self, record):
        rid = record.get("uri") or str(uuid.uuid4())
        seq = f"{time.time_ns()}"
        tmp = os.path.join(self.stream_dir, f".{seq}-{rid}.tmp")
        dst = os.path.join(self.stream_dir, f"{seq}-{rid}.json")
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.rename(tmp, dst)
        return rid

    def read_batch(self, max_items, timeout_s=0.1):
        deadline = time.time() + timeout_s
        out = []
        while len(out) < max_items:
            files = sorted(f for f in os.listdir(self.stream_dir)
                           if f.endswith(".json"))
            for fname in files[:max_items - len(out)]:
                path = os.path.join(self.stream_dir, fname)
                try:
                    with open(path) as f:
                        rec = json.load(f)
                    os.remove(path)
                except (FileNotFoundError, json.JSONDecodeError):
                    continue
                rid = fname.split("-", 1)[1][:-5]
                out.append((rid, rec))
            if out or time.time() > deadline:
                break
            time.sleep(0.01)
        return out

    def put_result(self, key, value):
        tmp = os.path.join(self.result_dir, f".{key}.tmp")
        with open(tmp, "w") as f:
            json.dump(value, f)
        os.rename(tmp, os.path.join(self.result_dir, f"{key}.json"))

    def get_result(self, key):
        path = os.path.join(self.result_dir, f"{key}.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def result_count(self):
        return len(os.listdir(self.result_dir))

    def put_error(self, key, error, record=None):
        self.put_result(key, {"error": str(error)})
        seq = f"{time.time_ns()}"
        tmp = os.path.join(self.dead_dir, f".{seq}-{key}.tmp")
        with open(tmp, "w") as f:
            json.dump(_dead_letter_entry(key, error, record), f)
        os.rename(tmp, os.path.join(self.dead_dir, f"{seq}-{key}.json"))

    def dead_letters(self):
        out = []
        for fname in sorted(f for f in os.listdir(self.dead_dir)
                            if f.endswith(".json")):
            try:
                with open(os.path.join(self.dead_dir, fname)) as f:
                    out.append(json.load(f))
            except (FileNotFoundError, json.JSONDecodeError):
                continue
        return out

    def trim(self, max_len):
        files = sorted(f for f in os.listdir(self.stream_dir)
                       if f.endswith(".json"))
        for fname in files[:max(0, len(files) - max_len)]:
            try:
                os.remove(os.path.join(self.stream_dir, fname))
            except FileNotFoundError:
                pass


class RedisQueue(BaseQueue):
    """Real Redis streams (requires the `redis` package + a server)."""

    def __init__(self, host="localhost", port=6379, stream="image_stream",
                 result_table="result"):
        import redis
        self.r = redis.Redis(host=host, port=port)
        self.stream = stream
        self.table = result_table
        self.dead_stream = stream + ":dead-letter"
        self._last_id = "0"

    def xadd(self, record):
        rid = record.get("uri") or str(uuid.uuid4())
        self.r.xadd(self.stream, {"data": json.dumps(record)})
        return rid

    def read_batch(self, max_items, timeout_s=0.1):
        resp = self.r.xread({self.stream: self._last_id}, count=max_items,
                            block=int(timeout_s * 1000))
        out = []
        for _, entries in resp:
            for eid, fields in entries:
                self._last_id = eid
                rec = json.loads(fields[b"data"])
                out.append((rec.get("uri", eid.decode()), rec))
        return out

    def put_result(self, key, value):
        self.r.hset(self.table, key, json.dumps(value))

    def get_result(self, key):
        v = self.r.hget(self.table, key)
        return json.loads(v) if v else None

    def result_count(self):
        return self.r.hlen(self.table)

    def put_error(self, key, error, record=None):
        self.r.hset(self.table, key, json.dumps({"error": str(error)}))
        self.r.xadd(self.dead_stream,
                    {"data": json.dumps(_dead_letter_entry(key, error,
                                                           record))})

    def dead_letters(self):
        return [json.loads(fields[b"data"])
                for _, fields in self.r.xrange(self.dead_stream)]

    def trim(self, max_len):
        self.r.xtrim(self.stream, maxlen=max_len)
        self.r.xtrim(self.dead_stream, maxlen=max_len)


def make_queue(kind: str = "inproc", **kwargs) -> BaseQueue:
    if kind == "inproc":
        return InProcQueue()
    if kind == "file":
        return FileQueue(kwargs["root"])
    if kind == "redis":
        return RedisQueue(**kwargs)
    raise ValueError(f"unknown queue kind {kind!r}")
