"""Serving queues — the Redis-stream transport with pluggable backends.

Reference parity: Cluster Serving's Redis streams (`image_stream` XADD in the client,
result HSET table — serving/ClusterServing.scala:106-307, pyzoo client.py:62-160).
Backends:
- `InProcQueue`  — same-process deque (tests, embedded serving)
- `FileQueue`    — spool-directory stream + result table (cross-process, no deps)
- `RedisQueue`   — real Redis when the `redis` package + server are available

All share the same four calls: xadd / read_batch / put_result / get_result,
plus the dead-letter side channel (PR 1 resilience): `put_error` quarantines a
poisoned record — it writes an `{"error": ...}` result under the record's key
(so a waiting client unblocks and SEES the failure instead of hanging) and
appends `{"uri", "error", "record"?}` to a dead-letter stream that
`dead_letters()` exposes for inspection/replay.

Availability layer (PR 2):
- admission control — `max_depth` caps the stream; `xadd` raises `QueueFull`
  instead of growing unboundedly, and `close_admission()` (graceful drain)
  raises `QueueClosed` for new records.
- `depth()` / `health()` feed the engine's `/readyz` probe.
- `replay_dead_letters()` re-enqueues quarantined records after a fix and
  clears them (and their stale error results) from the dead-letter store.
- RedisQueue reads (`read_batch`/`get_result`) go through RetryPolicy + a
  read-side CircuitBreaker: an outage degrades to empty batches (readiness
  flips) instead of crash-looping the supervised preprocess worker.

Throughput data plane (PR 3):
- `put_results(pairs)` / `get_results(keys)` — batched result I/O: one
  backend round-trip per micro-batch (Redis `hset` mapping / `hmget`,
  FileQueue batch spool with a single directory fsync / single listing,
  InProc one lock).  The defaults loop the single-record calls so custom
  backends stay correct; writes are idempotent per key so the engine's
  per-record fallback after a failed batch write cannot duplicate results.

Lease-based claiming (PR 5 tentpole — horizontal serving replicas):
`read_batch` no longer DELETES records on consume.  Every backend now
CLAIMS them under a lease (the Kafka consumer-group / Redis Streams
XAUTOCLAIM shape), so N replicas can share one queue and a SIGKILLed
replica's in-flight records are recoverable instead of silently stranded:

- a delivered record moves to a per-backend PENDING store stamped with the
  claiming ``consumer`` (the replica id) and the claim time;
- ``ack(rids)`` — called by the engine AFTER the result is durably written
  — removes it from pending (and, for Redis, XACK+XDELs the entry);
- ``reclaim(min_idle_s)`` re-claims pending entries whose lease has been
  idle past ``min_idle_s`` (their replica died, or is stuck) and
  re-delivers them to the caller with a delivery count — InProc walks its
  pending table, FileQueue atomically renames the claim file, Redis uses
  ``XAUTOCLAIM``;
- ``pending_count()`` reports in-flight claims (rides ``health()``).

The contract is AT-LEAST-ONCE: a record is redelivered until some replica
acks it.  Result writes are idempotent per key and the engine suppresses
redelivered records that already have a result, so the client-visible
contract stays "exactly one result per record".  The lease must exceed the
worst-case single-record service time, or a replica's own slow in-flight
work gets re-claimed out from under it (same caveat as any lease system).

Binary wire format (PR 7 tentpole): ``xadd`` accepts a BINARY FRAME
(``serving/wire.py`` — magic + version + length-prefixed header JSON + raw
tensor payload) alongside the legacy record dict, and every backend carries
it natively: InProcQueue passes the frame buffer by reference (the
consumer's payload view aliases the producer's bytes), FileQueue spools the
frame verbatim as ``<seq>-<rid>.bin`` (no JSON round-trip), RedisQueue
ships it as raw stream-field bytes.  ``read_batch`` hands the engine a
record DICT either way — frames are decoded at the consume boundary into
``{uri, trace_id, deadline_ns, dtype, shape, payload: memoryview, ...}`` —
so the lease/ack/reclaim/dead-letter machinery above is format-blind, and
legacy base64-JSON records already sitting in a queue keep decoding
unchanged through an upgrade.  A malformed frame (bad magic, truncation,
payload-length mismatch) quarantines ALONE, exactly like a malformed JSON
entry.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
import uuid
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple, Union

from analytics_zoo_tpu.serving import wire as _wire

logger = logging.getLogger(__name__)

# what xadd accepts: a legacy record dict, or a binary frame buffer
Record = Union[Dict, bytes, bytearray, memoryview]


def _frame_rid(frame) -> str:
    """Record id for a binary frame: the header's uri (raises FrameError on
    a malformed frame — producers get a typed rejection at enqueue, the
    queue never stores a frame it cannot identify)."""
    uri = _wire.decode_header(frame).get("uri")
    return str(uri) if uri else str(uuid.uuid4())


class QueueFull(RuntimeError):
    """Admission rejected: the stream is at `max_depth`.  Enqueue callers
    should back off / shed, not retry in a tight loop."""


class QueueClosed(QueueFull):
    """Admission rejected: the queue is draining (graceful shutdown)."""


class BaseQueue:
    # admission control (PR 2): None = unbounded; `xadd` implementations call
    # `_check_admission()` before accepting a record.  The cap is exact for
    # InProcQueue (checked inside the append's lock) and BEST-EFFORT for the
    # cross-process backends: k concurrent producer processes can overshoot
    # by up to k-1 records per admission cycle (check-then-write without a
    # cross-process lock) — the cap bounds growth, it is not a hard ceiling
    max_depth: Optional[int] = None
    admission_open: bool = True

    def __init__(self):
        # per-handle consumer identity (PR 5): the engine aligns this with
        # its replica_id so claims are attributable across replicas
        self.consumer = f"c-{uuid.uuid4().hex[:8]}"

    def xadd(self, record: Dict) -> str:
        raise NotImplementedError

    def read_batch(self, max_items: int, timeout_s: float = 0.1) -> List[Tuple[str, Dict]]:
        """Deliver up to ``max_items`` records, CLAIMING them under this
        handle's ``consumer`` lease (they stay in the pending store until
        ``ack``ed — crash-safe, at-least-once)."""
        raise NotImplementedError

    # -- lease-based claiming (PR 5 horizontal replicas) ---------------------
    def ack(self, rids: List[str]) -> None:
        """Acknowledge processed records: their results are durably written,
        drop them from the pending store so they are never redelivered.  The
        default is a no-op so pre-PR-5 custom backends (destructive reads,
        nothing pending) stay correct."""

    def reclaim(self, min_idle_s: float,
                max_items: int = 64) -> List[Tuple[str, Dict, int]]:
        """Re-claim pending records whose lease has been idle for at least
        ``min_idle_s`` (their replica crashed mid-flight, or wedged) and
        re-deliver them to THIS consumer.  Returns ``(rid, record,
        deliveries)`` triples — ``deliveries >= 2`` marks a redelivery so
        the engine can suppress records that already have a result.  The
        default returns nothing (destructive-read backends have no
        pending)."""
        return []

    def pending_count(self) -> int:
        """In-flight claims (delivered, not yet acked) — the lease-side
        sibling of ``depth()``."""
        return 0

    def put_result(self, key: str, value: Dict) -> None:
        raise NotImplementedError

    def get_result(self, key: str) -> Optional[Dict]:
        raise NotImplementedError

    # -- batched result I/O (PR 3 throughput) --------------------------------
    def put_results(self, pairs: List[Tuple[str, Dict]]) -> None:
        """Write one micro-batch of results in a single backend round-trip
        where the backend supports it (Redis `hset` mapping, FileQueue batch
        spool, InProc bulk append under one lock).  The default loops
        `put_result` so custom backends stay correct.  Writes are idempotent
        per key: re-running a partially-committed batch cannot duplicate a
        result, which is what lets the engine fall back to per-record writes
        when a batch write fails mid-way."""
        for key, value in pairs:
            self.put_result(key, value)

    def get_results(self, keys) -> Dict[str, Optional[Dict]]:
        """Batched result lookup (client polling): one round-trip for N keys
        where the backend supports it (Redis `hmget`, FileQueue single
        directory listing, InProc one lock).  Missing keys map to None."""
        return {key: self.get_result(key) for key in keys}

    # -- streamed partials (PR 20 generation continuity) ---------------------
    def put_partial(self, key: str, value: Dict) -> bool:
        """Write a STREAMED partial result — refuses to overwrite a terminal.
        A dead owner's partial may race the resumed terminal onto the same
        key from two processes; terminals must win, so a partial write is
        check-then-write (atomic where the backend can make it so, see
        `InProcQueue`; File/Redis accept the tiny window because the loser
        there is still a *newer* partial of the same lineage, never a
        terminal being shadowed — partial writers stop at finish).  Returns
        False when a terminal already occupies the key."""
        prior = self.get_result(key)
        if isinstance(prior, dict) and not prior.get("partial"):
            return False
        self.put_result(key, value)
        return True

    # -- lease annotations (PR 20 generation continuity) ---------------------
    def annotate(self, rid: str, meta: Dict) -> None:
        """Attach small JSON metadata to an in-flight record's lease lineage
        (the snapshot-spool pointer + generation epoch).  Annotations ride
        the queue — NOT the record — so a reclaim on a different replica can
        find the dead owner's resume state by rid alone.  They survive the
        claim itself (a reclaim re-annotates) and are dropped at ``ack``.
        The default is a no-op so custom backends without resume support
        stay correct."""

    def annotation(self, rid: str) -> Optional[Dict]:
        """The current annotation for ``rid``, or None."""
        return None

    def result_count(self) -> int:
        raise NotImplementedError

    def delete_result(self, key: str) -> None:
        """Drop a stale result (replay path: the old error marker must not
        shadow the re-enqueued record's fresh result)."""
        raise NotImplementedError

    # -- admission control (PR 2 availability) -------------------------------
    def depth(self) -> int:
        """Records waiting in the stream (readiness + admission signal)."""
        raise NotImplementedError

    def close_admission(self) -> None:
        """Graceful drain: reject new records with `QueueClosed` while the
        engine flushes in-flight work."""
        self.admission_open = False

    def open_admission(self) -> None:
        self.admission_open = True

    def _admission_closed_externally(self) -> bool:
        """Cross-process admission signal: the drain runs in the serving
        daemon, but producers hold their OWN queue handles — File/Redis
        backends persist the closure (marker file / redis key) so every
        handle rejects during a drain, not just the engine's."""
        return False

    def _check_admission(self) -> None:
        if not self.admission_open or self._admission_closed_externally():
            raise QueueClosed("queue draining: admission closed")
        if self.max_depth is not None:
            depth = self.depth()       # once: rejection happens mid-flood,
            if depth >= self.max_depth:  # don't double the backend load
                raise QueueFull(
                    f"queue depth {depth} >= max_depth {self.max_depth}")

    def reachable(self) -> bool:
        """Backend liveness (readiness probe); in-process backends are always
        reachable, RedisQueue pings the server."""
        return True

    def read_path_healthy(self) -> bool:
        """True when an EMPTY read_batch really means the stream is empty —
        the graceful-drain exit gate.  RedisQueue reports False while its
        read breaker is not closed (an outage also reads as an empty batch,
        but the backlog is still out there)."""
        return True

    def health(self) -> Dict:
        """Queue-side readiness document consumed by the engine's
        `/readyz` probe and the manager health snapshot."""
        try:
            depth = self.depth()
        except Exception:  # noqa: BLE001 — backend down
            depth = -1
        try:
            dead = self.dead_letter_count()
        except Exception:  # noqa: BLE001
            dead = -1
        try:
            closed_ext = self._admission_closed_externally()
        except Exception:  # noqa: BLE001 — backend down
            closed_ext = False
        try:
            pending = self.pending_count()
        except Exception:  # noqa: BLE001 — backend down
            pending = -1
        return {"backend": type(self).__name__,
                "depth": depth,
                "pending": pending,
                "max_depth": self.max_depth,
                "admission_open": self.admission_open and not closed_ext,
                "reachable": self.reachable(),
                "dead_letters": dead}

    # -- dead-letter side channel (PR 1 resilience) --------------------------
    def put_error(self, key: str, error: str,
                  record: Optional[Dict] = None,
                  trace_id: Optional[str] = None) -> None:
        """Quarantine one poisoned record: write an error RESULT the client
        can see (same key it is polling) and append a dead-letter entry.
        ``trace_id`` (PR 4, falls back to ``record["trace_id"]``) rides both
        the error result and the dead-letter entry, so a quarantine is
        correlatable with its trace spans from either side."""
        raise NotImplementedError

    def dead_letters(self) -> List[Dict]:
        """All quarantined entries, oldest first."""
        raise NotImplementedError

    def dead_letter_count(self) -> int:
        return len(self.dead_letters())

    # -- dead-letter replay (PR 2 availability / ROADMAP open item) ----------
    def replay_dead_letters(
            self, filter: Optional[Callable[[Dict], bool]] = None) -> Dict:
        """Re-enqueue quarantined records after a fix: for each dead-letter
        entry (optionally narrowed by ``filter(entry) -> bool``) that still
        carries its original ``record``, drop the stale error result, xadd the
        record back onto the stream, and clear the entry from the dead-letter
        store.  Entries without a record payload (e.g. predict-stage
        quarantines) cannot be replayed and are left in place.

        Returns ``{"replayed": [uris], "skipped": [uris]}``.  Stops early on
        `QueueFull` so replay respects admission control."""
        replayed: List[str] = []
        skipped: List[str] = []
        for token, entry in self._dead_letter_items():
            if filter is not None and not filter(entry):
                continue
            record = entry.get("record")
            if not isinstance(record, dict) or \
                    not ({"image", "b64", "data"} & set(record)):
                # no payload, or not a real record (e.g. a malformed-entry
                # quarantine keeping only {'raw': ...}): re-enqueueing it
                # would just churn it straight back into quarantine
                skipped.append(entry.get("uri", "?"))
                continue
            if "deadline_ns" in record:
                # the original budget is long gone: shipped verbatim the
                # engine would shed the replayed record as deadline-exceeded
                # the moment it is read — replay grants a fresh (unbounded)
                # budget instead
                record = {k: v for k, v in record.items()
                          if k != "deadline_ns"}
            uri = entry.get("uri") or record.get("uri")
            try:
                self._check_admission()
            except QueueFull:
                break                      # respect admission; retry later
            # drop the stale error marker BEFORE re-enqueueing — the engine
            # may answer the replayed record at any point after xadd, and a
            # late delete would destroy the fresh result
            if uri:
                try:
                    self.delete_result(uri)
                except Exception:  # noqa: BLE001 — stale marker best-effort
                    pass
            try:
                self.xadd(record)
            except Exception:  # noqa: BLE001 — admission race OR backend
                # died mid-replay: either way the marker was already
                # deleted — restore it so a polling client still sees the
                # quarantine error, then stop with the partial report
                if uri:
                    try:
                        self.put_result(uri, _error_result(
                            entry.get("error", "quarantined (replay "
                                               "pending)"),
                            record, entry.get("trace_id")))
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
                break
            self._remove_dead_letter(token)
            replayed.append(uri or "?")
        return {"replayed": replayed, "skipped": skipped}

    def _dead_letter_items(self) -> List[Tuple[object, Dict]]:
        """(opaque-token, entry) pairs; the token feeds
        ``_remove_dead_letter``."""
        raise NotImplementedError

    def _remove_dead_letter(self, token) -> None:
        raise NotImplementedError

    def trim(self, max_len: int) -> None:
        """Memory guard (ClusterServing.scala:134-140 XTRIM analog)."""


def _dead_letter_entry(key: str, error: str, record: Optional[Dict],
                       trace_id: Optional[str] = None) -> Dict:
    entry = {"uri": key, "error": str(error)}
    if record is not None:
        # binary records carry a memoryview payload: re-encode it as b64 so
        # the entry is JSON-serializable on every backend AND replayable
        # through the legacy decode path
        entry["record"] = _wire.sanitize_record(record)
    tid = trace_id or (record or {}).get("trace_id")
    if tid is not None:
        entry["trace_id"] = tid
    return entry


def _error_result(error: str, record: Optional[Dict],
                  trace_id: Optional[str] = None) -> Dict:
    out = {"error": str(error)}
    tid = trace_id or (record or {}).get("trace_id")
    if tid is not None:
        out["trace_id"] = tid
    return out


class InProcQueue(BaseQueue):
    def __init__(self, max_depth: Optional[int] = None):
        super().__init__()
        self._stream = deque()
        self._results: Dict[str, Dict] = {}
        self._dead: List[Dict] = []
        # lease-based pending table (PR 5): rid -> {record, claim_ts,
        # consumer, deliveries}.  read_batch moves records here instead of
        # destroying them; ack() removes; reclaim() re-delivers expired ones.
        self._pending: Dict[str, Dict] = {}
        # lease annotations (PR 20): rid -> resume-state pointer.  Engines
        # under test share ONE InProcQueue instance, so this dict IS the
        # cross-"replica" channel the File/Redis backends get from disk.
        self._annotations: Dict[str, Dict] = {}
        self._lock = threading.Lock()
        self.max_depth = max_depth

    def xadd(self, record):
        # binary frame: identified by its header uri, stored AS the buffer
        # (passed by reference — the consumer's payload view aliases these
        # very bytes, zero queue-side copies)
        rid = _frame_rid(record) if not isinstance(record, dict) \
            else (record.get("uri") or str(uuid.uuid4()))
        with self._lock:
            # admission check INSIDE the append's critical section so
            # concurrent producers cannot both pass at depth == cap - 1
            if not self.admission_open:
                raise QueueClosed("queue draining: admission closed")
            if self.max_depth is not None and \
                    len(self._stream) >= self.max_depth:
                raise QueueFull(f"queue depth {len(self._stream)} >= "
                                f"max_depth {self.max_depth}")
            self._stream.append((rid, record))
        return rid

    def depth(self):
        with self._lock:
            return len(self._stream)

    def read_batch(self, max_items, timeout_s=0.1):
        deadline = time.time() + timeout_s
        out = []
        while len(out) < max_items:
            raw = []
            with self._lock:
                while self._stream and len(raw) + len(out) < max_items:
                    rid, rec = self._stream.popleft()
                    # claim in the SAME critical section as the pop:
                    # stream + pending counts stay conserved, so a
                    # concurrent observer (health snapshot, drain check)
                    # never sees records vanish into an in-flight decode
                    self._pending[rid] = {"record": rec,
                                          "claim_ts": time.monotonic(),
                                          "consumer": self.consumer,
                                          "deliveries": 1}
                    raw.append((rid, rec))
            for rid, rec in raw:
                if not isinstance(rec, dict):
                    # binary frame: decode at the consume boundary; the
                    # payload memoryview aliases the producer's buffer
                    # (by-reference hand-off, no copy)
                    try:
                        rec = _wire.frame_to_record(rec)
                    except _wire.FrameError as e:
                        with self._lock:
                            self._pending.pop(rid, None)
                        self.put_error(rid, f"read_batch: malformed "
                                            f"frame: {e}")
                        continue
                    with self._lock:
                        entry = self._pending.get(rid)
                        if entry is not None:
                            entry["record"] = rec
                out.append((rid, rec))
            if out or time.time() > deadline:
                break
            time.sleep(0.005)
        return out

    def ack(self, rids):
        with self._lock:
            for rid in rids:
                self._pending.pop(rid, None)
                self._annotations.pop(rid, None)

    def reclaim(self, min_idle_s, max_items=64):
        now = time.monotonic()
        out, bad = [], []
        with self._lock:
            for rid, entry in list(self._pending.items()):
                if len(out) >= max_items:
                    break
                if now - entry["claim_ts"] < min_idle_s:
                    continue
                rec = entry["record"]
                if not isinstance(rec, dict):
                    # a raw frame claimed by a reader that died between
                    # the claim and its decode (read_batch claims first
                    # so stream+pending stay conserved): decode at THIS
                    # consume boundary — the engine's read loop assumes
                    # dict records
                    try:
                        rec = _wire.frame_to_record(rec)
                    except _wire.FrameError as e:
                        bad.append((rid, str(e)))
                        del self._pending[rid]
                        continue
                    entry["record"] = rec
                entry["claim_ts"] = now
                entry["consumer"] = self.consumer
                entry["deliveries"] += 1
                out.append((rid, rec, entry["deliveries"]))
        for rid, err in bad:     # put_error takes the lock: outside it
            self.put_error(rid, f"reclaim: malformed frame: {err}")
        return out

    def pending_count(self):
        with self._lock:
            return len(self._pending)

    def put_result(self, key, value):
        with self._lock:
            self._results[key] = value

    def put_results(self, pairs):
        # bulk append: one lock acquisition for the whole micro-batch
        with self._lock:
            for key, value in pairs:
                self._results[key] = value

    def put_partial(self, key, value):
        # check-then-write inside ONE critical section: a partial can
        # never shadow a terminal even with racing writer threads
        with self._lock:
            prior = self._results.get(key)
            if isinstance(prior, dict) and not prior.get("partial"):
                return False
            self._results[key] = value
            return True

    def annotate(self, rid, meta):
        with self._lock:
            self._annotations[rid] = dict(meta)

    def annotation(self, rid):
        with self._lock:
            ann = self._annotations.get(rid)
            return dict(ann) if ann is not None else None

    def get_result(self, key):
        with self._lock:
            return self._results.get(key)

    def get_results(self, keys):
        with self._lock:
            return {key: self._results.get(key) for key in keys}

    def result_count(self):
        with self._lock:
            return len(self._results)

    def delete_result(self, key):
        with self._lock:
            self._results.pop(key, None)

    def put_error(self, key, error, record=None, trace_id=None):
        with self._lock:
            self._results[key] = _error_result(error, record, trace_id)
            self._dead.append(_dead_letter_entry(key, error, record,
                                                 trace_id))

    def dead_letters(self):
        with self._lock:
            return list(self._dead)

    def dead_letter_count(self):
        with self._lock:
            return len(self._dead)

    def _dead_letter_items(self):
        with self._lock:
            return [(id(e), e) for e in self._dead]

    def _remove_dead_letter(self, token):
        with self._lock:
            self._dead = [e for e in self._dead if id(e) != token]

    def trim(self, max_len):
        with self._lock:
            while len(self._stream) > max_len:
                self._stream.popleft()


class FileQueue(BaseQueue):
    """Spool-dir stream: records are json files named <seq>-<id>.json in
    stream/, results live in results/<key>.json.  Safe for MANY consumers and
    many producers (PR 5): consuming a record is an atomic claim-rename into
    claims/ — the rename either succeeds (this replica owns the record until
    it acks) or raises FileNotFoundError (another replica won the race), so
    no record can be delivered twice inside one lease window.  The PR 3
    cached-listing optimization is gone with the single-consumer model it
    depended on: a stale cached name now simply loses the claim race instead
    of papering over it, and every poll lists the spool fresh.

    Claim files are named ``<claim_ns>.<deliveries>.<consumer>.<orig>`` so a
    reclaim sweep can recover a dead replica's orphans by filename alone —
    no shared state beyond the directory."""

    def __init__(self, root: str, max_depth: Optional[int] = None):
        super().__init__()
        self.root = root
        self.stream_dir = os.path.join(root, "stream")
        self.claim_dir = os.path.join(root, "claims")
        self.result_dir = os.path.join(root, "results")
        self.dead_dir = os.path.join(root, "dead-letter")
        os.makedirs(self.stream_dir, exist_ok=True)
        os.makedirs(self.claim_dir, exist_ok=True)
        os.makedirs(self.result_dir, exist_ok=True)
        os.makedirs(self.dead_dir, exist_ok=True)
        self.max_depth = max_depth
        # rid -> claim-file path for records THIS handle claimed (ack needs
        # the current claim name); guarded — the engine reads on one worker
        # thread and acks on another
        self._claims: Dict[str, str] = {}
        self._claims_lock = threading.Lock()

    # stream entries: legacy JSON records spool as .json, binary frames
    # (PR 7) spool verbatim as .bin — one file either way, same claim and
    # lease machinery
    _STREAM_EXTS = (".json", ".bin")

    def depth(self):
        return sum(1 for f in os.listdir(self.stream_dir)
                   if f.endswith(self._STREAM_EXTS))

    def reachable(self):
        return os.path.isdir(self.stream_dir)

    # cross-process drain: the closure is a marker file every handle sees
    def _admission_marker(self):
        return os.path.join(self.root, "admission-closed")

    def close_admission(self):
        super().close_admission()
        with open(self._admission_marker(), "w"):
            pass

    def open_admission(self):
        super().open_admission()
        try:
            os.remove(self._admission_marker())
        except FileNotFoundError:
            pass

    def _admission_closed_externally(self):
        return os.path.exists(self._admission_marker())

    def xadd(self, record):
        self._check_admission()
        seq = f"{time.time_ns()}"
        if not isinstance(record, dict):
            # binary frame: spooled verbatim — the payload bytes hit disk
            # once, with no JSON/base64 round-trip
            frame = bytes(record) if not isinstance(record, bytes) \
                else record
            rid = _frame_rid(frame)
            tmp = os.path.join(self.stream_dir, f".{seq}-{rid}.tmp")
            dst = os.path.join(self.stream_dir, f"{seq}-{rid}.bin")
            with open(tmp, "wb") as f:
                f.write(frame)
            _wire.COPY_STATS.record("spool_write", len(frame))
            os.rename(tmp, dst)
            return rid
        rid = record.get("uri") or str(uuid.uuid4())
        tmp = os.path.join(self.stream_dir, f".{seq}-{rid}.tmp")
        dst = os.path.join(self.stream_dir, f"{seq}-{rid}.json")
        with open(tmp, "w") as f:
            json.dump(record, f)
            _wire.COPY_STATS.record("spool_write", f.tell())
        os.rename(tmp, dst)
        return rid

    @staticmethod
    def _rid_of(orig_name: str) -> str:
        stem = os.path.splitext(orig_name)[0]
        return stem.split("-", 1)[1] if "-" in stem else stem

    def _claim_name(self, orig_name: str, deliveries: int) -> str:
        # dots delimit the claim metadata, so the consumer id must not
        # carry any (replica ids are free-form)
        consumer = re.sub(r"[^A-Za-z0-9_-]", "-", str(self.consumer))
        return f"{time.time_ns()}.{deliveries}.{consumer}.{orig_name}"

    def _load_claim(self, claim_path: str,
                    orig_name: str) -> Optional[Tuple[str, Dict]]:
        """Parse a just-claimed record; a corrupt payload (crash mid-write
        outside the tmp/rename path, disk error) is quarantined ALONE and
        its claim file dropped — left in place it would be re-claimed and
        re-parsed every reclaim sweep forever."""
        rid = self._rid_of(orig_name)
        try:
            if orig_name.endswith(".bin"):
                # binary frame: one read, decoded at the consume boundary
                with open(claim_path, "rb") as f:
                    frame = f.read()
                _wire.COPY_STATS.record("spool_read", len(frame))
                rec = _wire.frame_to_record(frame)
            else:
                with open(claim_path) as f:
                    rec = json.load(f)
                    _wire.COPY_STATS.record("spool_read", f.tell())
        except FileNotFoundError:
            return None                    # raced a reclaiming replica
        except (json.JSONDecodeError, _wire.FrameError) as e:
            try:
                os.remove(claim_path)
            except FileNotFoundError:
                pass
            try:
                self.put_error(rid, f"read_batch: malformed entry: {e}")
            except Exception:  # noqa: BLE001 — best-effort
                pass
            return None
        with self._claims_lock:
            self._claims[rid] = claim_path
        return rid, rec

    def read_batch(self, max_items, timeout_s=0.1):
        deadline = time.time() + timeout_s
        out = []
        while len(out) < max_items:
            for fname in sorted(f for f in os.listdir(self.stream_dir)
                                if f.endswith(self._STREAM_EXTS)):
                if len(out) >= max_items:
                    break
                claim_path = os.path.join(
                    self.claim_dir, self._claim_name(fname, deliveries=1))
                try:
                    # the claim-rename IS the consume: atomic, exactly one
                    # replica wins, and the record survives a crash as a
                    # lease-stamped claim file instead of vanishing
                    os.rename(os.path.join(self.stream_dir, fname),
                              claim_path)
                except FileNotFoundError:
                    continue               # another replica claimed it
                loaded = self._load_claim(claim_path, fname)
                if loaded is not None:
                    out.append(loaded)
            if out or time.time() > deadline:
                break
            time.sleep(0.01)
        return out

    def ack(self, rids):
        # the ann dir only exists once some engine annotated (PR 20), so
        # non-generation deployments pay zero extra stats per ack
        drop_ann = os.path.isdir(self._ann_dir())
        for rid in rids:
            with self._claims_lock:
                path = self._claims.pop(rid, None)
            if path:
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass                   # reclaimed past our lease
            if drop_ann:
                try:
                    os.remove(self._ann_path(rid))
                except FileNotFoundError:
                    pass

    def reclaim(self, min_idle_s, max_items=64):
        now_ns = time.time_ns()
        out = []
        for fname in sorted(os.listdir(self.claim_dir)):
            if len(out) >= max_items:
                break
            parts = fname.split(".", 3)
            if len(parts) != 4:
                continue                   # foreign file in the claims dir
            try:
                claim_ns, deliveries = int(parts[0]), int(parts[1])
            except ValueError:
                continue
            if now_ns - claim_ns < min_idle_s * 1e9:
                continue                   # lease still live
            orig = parts[3]
            new_path = os.path.join(
                self.claim_dir, self._claim_name(orig, deliveries + 1))
            try:
                os.rename(os.path.join(self.claim_dir, fname), new_path)
            except FileNotFoundError:
                continue                   # another replica reclaimed first
            loaded = self._load_claim(new_path, orig)
            if loaded is not None:
                out.append((loaded[0], loaded[1], deliveries + 1))
        return out

    def pending_count(self):
        return sum(1 for f in os.listdir(self.claim_dir)
                   if f.endswith(self._STREAM_EXTS))

    # -- lease annotations (PR 20): <root>/ann/<rid>.json, created lazily
    # so non-generation deployments never grow the extra directory
    def _ann_dir(self):
        return os.path.join(self.root, "ann")

    def _ann_path(self, rid):
        safe = re.sub(r"[^A-Za-z0-9_-]", "-", str(rid))
        return os.path.join(self._ann_dir(), f"{safe}.json")

    def annotate(self, rid, meta):
        os.makedirs(self._ann_dir(), exist_ok=True)
        path = self._ann_path(rid)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.rename(tmp, path)

    def annotation(self, rid):
        try:
            with open(self._ann_path(rid)) as f:
                return json.load(f)
        except (FileNotFoundError, NotADirectoryError):
            return None
        except json.JSONDecodeError:
            return None                    # torn write: resume falls back

    def put_result(self, key, value):
        tmp = os.path.join(self.result_dir, f".{key}.tmp")
        with open(tmp, "w") as f:
            json.dump(value, f)
        os.rename(tmp, os.path.join(self.result_dir, f"{key}.json"))

    def put_results(self, pairs):
        # batch spool: write every tmp file, rename them all, then pay ONE
        # directory fsync for the whole micro-batch — the durability point
        # moves from per-record to per-batch without losing the atomic
        # tmp/rename visibility contract readers depend on
        renames = []
        for key, value in pairs:
            tmp = os.path.join(self.result_dir, f".{key}.tmp")
            with open(tmp, "w") as f:
                json.dump(value, f)
            renames.append((tmp, os.path.join(self.result_dir,
                                              f"{key}.json")))
        for tmp, dst in renames:
            os.rename(tmp, dst)
        try:
            fd = os.open(self.result_dir, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass                           # fsync is best-effort (e.g. NFS)

    def get_result(self, key):
        path = os.path.join(self.result_dir, f"{key}.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    # below this many keys, per-key stats beat listing the result dir —
    # which only ever grows over a deployment's lifetime
    _LIST_THRESHOLD = 32

    def get_results(self, keys):
        # one directory listing instead of N existence probes for BIG key
        # sets (absent keys, the common case while polling, cost a set
        # lookup instead of a stat); small key sets — absolutely, or
        # relative to the last observed directory size (a mature
        # deployment's result dir can dwarf any key set) — keep the
        # per-key path
        keys = list(keys)
        if len(keys) < self._LIST_THRESHOLD or \
                len(keys) * 8 < getattr(self, "_result_dir_size", 0):
            return {key: self.get_result(key) for key in keys}
        try:
            present = set(os.listdir(self.result_dir))
            self._result_dir_size = len(present)
        except OSError:
            return {key: None for key in keys}
        out = {}
        for key in keys:
            if f"{key}.json" in present:
                try:
                    with open(os.path.join(self.result_dir,
                                           f"{key}.json")) as f:
                        out[key] = json.load(f)
                except (OSError, json.JSONDecodeError):
                    out[key] = None        # raced a writer: poll again
            else:
                out[key] = None
        return out

    def result_count(self):
        # only committed results: put_result writes `.{key}.tmp` then renames,
        # so in-flight tmp files must not inflate the count
        return sum(1 for f in os.listdir(self.result_dir)
                   if f.endswith(".json"))

    def delete_result(self, key):
        try:
            os.remove(os.path.join(self.result_dir, f"{key}.json"))
        except FileNotFoundError:
            pass

    def put_error(self, key, error, record=None, trace_id=None):
        self.put_result(key, _error_result(error, record, trace_id))
        seq = f"{time.time_ns()}"
        tmp = os.path.join(self.dead_dir, f".{seq}-{key}.tmp")
        with open(tmp, "w") as f:
            json.dump(_dead_letter_entry(key, error, record, trace_id), f)
        os.rename(tmp, os.path.join(self.dead_dir, f"{seq}-{key}.json"))

    def dead_letters(self):
        return [e for _, e in self._dead_letter_items()]

    def dead_letter_count(self):
        # probes call this every few seconds: count filenames, don't parse
        return sum(1 for f in os.listdir(self.dead_dir)
                   if f.endswith(".json"))

    def _dead_letter_items(self):
        out = []
        for fname in sorted(f for f in os.listdir(self.dead_dir)
                            if f.endswith(".json")):
            try:
                with open(os.path.join(self.dead_dir, fname)) as f:
                    out.append((fname, json.load(f)))
            except (FileNotFoundError, json.JSONDecodeError):
                continue
        return out

    def _remove_dead_letter(self, token):
        try:
            os.remove(os.path.join(self.dead_dir, token))
        except FileNotFoundError:
            pass

    def trim(self, max_len):
        files = sorted(f for f in os.listdir(self.stream_dir)
                       if f.endswith(self._STREAM_EXTS))
        for fname in files[:max(0, len(files) - max_len)]:
            try:
                os.remove(os.path.join(self.stream_dir, fname))
            except FileNotFoundError:
                pass


class RedisQueue(BaseQueue):
    """Real Redis streams (requires the `redis` package + a server).

    Self-healing read path (PR 2): `read_batch`/`get_result` run through a
    RetryPolicy + a read-side CircuitBreaker — an outage degrades to empty
    batches / None results (the engine's `/readyz` flips not-ready via
    `health()`) instead of crash-looping the supervised preprocess worker;
    after `read_breaker_cooldown_s` a half-open probe reconnects
    automatically.  A malformed stream entry dead-letters ALONE: the rest of
    the already-consumed batch is still delivered.

    Horizontal replicas (PR 5): reads go through a CONSUMER GROUP
    (``XGROUP CREATE`` at id 0 / ``XREADGROUP >``), so N replicas share the
    stream with server-side fan-out, each delivered entry sits in the
    group's pending-entries list under this handle's ``consumer`` name
    until ``ack()`` (XACK + XDEL — served entries leave XLEN, keeping
    depth == backlog), and ``reclaim()`` is ``XAUTOCLAIM``: entries idle
    past the lease are re-claimed from dead replicas and redelivered."""

    GROUP = "serving"

    def __init__(self, host="localhost", port=6379, stream="image_stream",
                 result_table="result", max_depth: Optional[int] = None,
                 client=None, group: str = GROUP,
                 read_retries: int = 2,
                 read_backoff_s: float = 0.05,
                 read_breaker_threshold: int = 5,
                 read_breaker_cooldown_s: float = 1.0):
        super().__init__()
        if client is None:
            import redis
            client = redis.Redis(host=host, port=port)
        self.r = client
        self.stream = stream
        self.table = result_table
        self.dead_stream = stream + ":dead-letter"
        self.group = group
        self._group_ready = False
        # rid -> stream entry id for records THIS handle has claimed (XACK
        # needs the entry id); guarded — the engine reads on one worker
        # thread and acks on another
        self._claimed: Dict[str, bytes] = {}
        self._claimed_lock = threading.Lock()
        # Redis < 6.2 has consumer groups but not XAUTOCLAIM: flip this on
        # the first "unknown command" so reclaim degrades to a no-op once
        # instead of repeatedly failing through the shared read breaker
        # (which would blind XREADGROUP too)
        self._reclaim_unsupported = False
        # set by annotate() (PR 20): gates annotation cleanup in ack so
        # engines that never checkpoint pay no extra HDELs
        self._ann_used = False
        self.max_depth = max_depth
        from analytics_zoo_tpu.common.resilience import (CircuitBreaker,
                                                         RetryPolicy)
        self._read_retry = RetryPolicy(max_retries=read_retries,
                                       base_delay_s=read_backoff_s)
        self._read_breaker = CircuitBreaker(
            failure_threshold=read_breaker_threshold,
            cooldown_s=read_breaker_cooldown_s, name="redis-read")
        self._last_read_failed = False

    @staticmethod
    def _decode(v):
        return v.decode() if isinstance(v, (bytes, bytearray)) else str(v)

    def _guarded_read(self, fn, *args, **kwargs):
        """One read against Redis with retry + breaker; raises
        `_ReadUnavailable` (internal) when the backend is down."""
        from analytics_zoo_tpu.common.resilience import (CircuitBreakerOpen,
                                                         RetryExhausted)
        try:
            return self._read_breaker.call(self._read_retry.call, fn,
                                           *args, **kwargs)
        except (CircuitBreakerOpen, RetryExhausted) as e:
            raise _ReadUnavailable(str(e)) from e

    def xadd(self, record):
        self._check_admission()
        if not isinstance(record, dict):
            # binary frame: the stream field value is the raw frame bytes —
            # Redis fields are binary-safe, so no base64/JSON inflation
            frame = bytes(record) if not isinstance(record, bytes) \
                else record
            rid = _frame_rid(frame)
            self.r.xadd(self.stream, {"data": frame})
            return rid
        rid = record.get("uri") or str(uuid.uuid4())
        self.r.xadd(self.stream, {"data": json.dumps(record)})
        return rid

    # -- consumer-group plumbing (PR 5) --------------------------------------
    def _ensure_group(self):
        if self._group_ready:
            return
        try:
            # id "0": records enqueued before the first replica starts are
            # still delivered (the pre-PR-5 read-from-0 semantics)
            self.r.xgroup_create(self.stream, self.group, id="0",
                                 mkstream=True)
        except Exception as e:  # noqa: BLE001 — BUSYGROUP = already exists
            if "BUSYGROUP" not in str(e):
                raise
        self._group_ready = True

    def _with_group(self, fn):
        """Run one group read, recovering ONCE from NOGROUP (the stream was
        deleted/trimmed out from under the group) by re-creating it."""
        self._ensure_group()
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — inspect for NOGROUP
            if "NOGROUP" not in str(e):
                raise
            self._group_ready = False
            self._ensure_group()
            return fn()

    def depth(self):
        # backlog = entries on the stream minus claimed-in-flight ones: the
        # admission cap and /readyz threshold must not count records that a
        # replica is actively serving (acked entries are XDELed, so they
        # leave XLEN entirely)
        try:
            return max(0, int(self.r.xlen(self.stream))
                       - self.pending_count())
        except Exception:  # noqa: BLE001 — outage: admission stays open,
            return 0       # the write itself will surface the error

    def pending_count(self):
        try:
            info = self.r.xpending(self.stream, self.group)
            if isinstance(info, dict):
                return int(info.get("pending", 0))
            return int(info[0])            # raw [count, min, max, consumers]
        except Exception:  # noqa: BLE001 — no group yet / outage
            return 0

    def reachable(self):
        try:
            return bool(self.r.ping())
        except Exception:  # noqa: BLE001
            return False

    # cross-process drain: the closure is a redis key every handle sees
    # (one EXISTS round-trip per xadd — the write itself already pays one)
    def _admission_key(self):
        return self.stream + ":admission-closed"

    def close_admission(self):
        super().close_admission()
        try:
            self.r.set(self._admission_key(), "1")
        except Exception:  # noqa: BLE001 — backend down: local flag holds
            pass

    def open_admission(self):
        super().open_admission()
        try:
            self.r.delete(self._admission_key())
        except Exception:  # noqa: BLE001
            pass

    def _admission_closed_externally(self):
        try:
            return bool(self.r.exists(self._admission_key()))
        except Exception:  # noqa: BLE001 — outage: the xadd will fail loudly
            return False

    def read_path_healthy(self):
        # _last_read_failed covers the breaker's warm-up window: the very
        # first failed read already means an empty batch is NOT "stream
        # empty", before the failure streak reaches the trip threshold
        from analytics_zoo_tpu.common.resilience import CircuitBreaker
        return (not self._last_read_failed
                and self._read_breaker.state == CircuitBreaker.CLOSED
                and self.reachable())

    def health(self):
        h = super().health()
        h["read_breaker"] = self._read_breaker.health()
        return h

    def _parse_delivery(self, eid, fields,
                        out: List[Tuple[str, Dict]]) -> Optional[str]:
        """Parse one delivered entry into ``out``, registering its claim;
        a malformed entry is quarantined ALONE (and acked away, so it never
        haunts the pending list) while the rest of the batch proceeds.
        Returns the rid on success."""
        try:
            data = fields[b"data"]
            if _wire.is_frame(data):
                # binary frame: decoded at the consume boundary (the
                # payload view aliases the client library's reply buffer)
                rec = _wire.frame_to_record(data)
            else:
                rec = json.loads(data)
        except (KeyError, ValueError, TypeError) as e:
            key = self._decode(eid)
            raw = fields.get(b"data", b"")
            try:
                self.put_error(
                    key, f"read_batch: malformed entry: "
                         f"{type(e).__name__}: {e}",
                    record={"raw": repr(bytes(raw)[:128])
                            if _wire.is_frame(raw)
                            else self._decode(raw)})
            except Exception:  # noqa: BLE001 — best-effort
                pass
            try:
                self.r.xack(self.stream, self.group, eid)
                self.r.xdel(self.stream, eid)
            except Exception:  # noqa: BLE001 — reclaim will re-land here
                pass
            return None
        rid = rec.get("uri", self._decode(eid))
        with self._claimed_lock:
            self._claimed[rid] = eid
        out.append((rid, rec))
        return rid

    def read_batch(self, max_items, timeout_s=0.1):
        try:
            # block floor of 1 ms: Redis treats BLOCK 0 as "block forever",
            # which a sub-millisecond coalescing remainder must NOT become.
            # XREADGROUP ">" delivers only never-delivered entries and puts
            # them on this consumer's pending list (the claim)
            resp = self._guarded_read(
                lambda: self._with_group(
                    lambda: self.r.xreadgroup(
                        self.group, self.consumer, {self.stream: ">"},
                        count=max_items,
                        block=max(1, int(timeout_s * 1000)))))
        except _ReadUnavailable:
            self._last_read_failed = True
            return []                      # degrade: readiness reports it
        self._last_read_failed = False
        out: List[Tuple[str, Dict]] = []
        for _, entries in resp or []:
            for eid, fields in entries:
                self._parse_delivery(eid, fields, out)
        return out

    def ack(self, rids):
        eids = []
        with self._claimed_lock:
            for rid in rids:
                eid = self._claimed.pop(rid, None)
                if eid is not None:
                    eids.append(eid)
        if self._ann_used and rids:
            # annotation cleanup (PR 20) only once this handle annotated,
            # so non-generation deployments pay zero extra round-trips
            try:
                self.r.hdel(self._ann_table(), *list(rids))
            except Exception:  # noqa: BLE001 — best-effort
                pass
        if not eids:
            return
        # XACK releases the claim; XDEL drops the served entry from the
        # stream so XLEN keeps measuring backlog (the delete-on-consume
        # depth semantics, moved to the ack side of the lease)
        self.r.xack(self.stream, self.group, *eids)
        try:
            self.r.xdel(self.stream, *eids)
        except Exception:  # noqa: BLE001 — trim() still bounds memory
            pass

    def reclaim(self, min_idle_s, max_items=64):
        if self._reclaim_unsupported:
            return []
        try:
            resp = self._guarded_read(
                lambda: self._with_group(
                    lambda: self.r.xautoclaim(
                        self.stream, self.group, self.consumer,
                        int(min_idle_s * 1000), start_id="0-0",
                        count=max_items)))
        except _ReadUnavailable as e:
            # walk the cause chain (RetryExhausted wraps the original):
            # an "unknown command" server is a capability gap, not an
            # outage — disable reclaim on this handle rather than letting
            # every sweep re-fail through the shared read breaker
            msgs, cause = [str(e)], e.__cause__
            while cause is not None:
                msgs.append(str(cause))
                cause = cause.__cause__
            if any("unknown command" in m.lower() for m in msgs):
                self._reclaim_unsupported = True
                logger.warning(
                    "RedisQueue: server lacks XAUTOCLAIM (Redis < 6.2); "
                    "lease reclaim disabled on this handle — records "
                    "orphaned by dead replicas will NOT be auto-recovered")
            return []
        # redis-py >= 4 returns (next_start, entries, deleted_ids); older
        # servers omit the third element
        entries = resp[1] if isinstance(resp, (tuple, list)) \
            and len(resp) >= 2 else []
        out3: List[Tuple[str, Dict, int]] = []
        for eid, fields in entries:
            if fields is None:
                continue                   # entry XDELed under the claim
            parsed: List[Tuple[str, Dict]] = []
            rid = self._parse_delivery(eid, fields, parsed)
            if rid is not None:
                # XAUTOCLAIM does not return the delivery counter, but the
                # PEL does: one XPENDING range probe per reclaimed entry
                # (reclaims are rare) recovers the TRUE count so the
                # engine's max_deliveries poison parking (PR 10) can trip.
                # 2 stays the honest floor when the probe fails.
                out3.append((rid, parsed[0][1],
                             max(2, self._delivery_count(eid))))
        return out3

    def _delivery_count(self, eid) -> int:
        """times_delivered for one PEL entry (already bumped by the
        XAUTOCLAIM that just reclaimed it); 0 when unavailable — callers
        floor it themselves."""
        try:
            rows = self.r.xpending_range(self.stream, self.group,
                                         min=eid, max=eid, count=1)
            if rows:
                return int(rows[0].get("times_delivered", 0))
        except Exception:  # noqa: BLE001 — old server/library: floor wins
            pass
        return 0

    # -- lease annotations (PR 20): one hash next to the result table
    def _ann_table(self):
        return f"{self.stream}:ann"

    def annotate(self, rid, meta):
        self._ann_used = True
        self.r.hset(self._ann_table(), rid, json.dumps(meta))

    def annotation(self, rid):
        try:
            v = self._guarded_read(self.r.hget, self._ann_table(), rid)
        except _ReadUnavailable:
            return None                    # resume falls back to restart
        try:
            return json.loads(v) if v else None
        except (json.JSONDecodeError, TypeError):
            return None

    def put_result(self, key, value):
        self.r.hset(self.table, key, json.dumps(value))

    def put_results(self, pairs):
        # one HSET with a field mapping: a whole micro-batch of results
        # costs one round-trip instead of len(pairs) — the Redis-pipeline
        # analog of the reference's bulk result-table writes
        if not pairs:
            return
        self.r.hset(self.table,
                    mapping={key: json.dumps(value) for key, value in pairs})

    def get_result(self, key):
        try:
            v = self._guarded_read(self.r.hget, self.table, key)
        except _ReadUnavailable:
            return None                    # poller keeps waiting; readiness
        return json.loads(v) if v else None

    def get_results(self, keys):
        # one HMGET for N keys, behind the same retry + read breaker as
        # single reads: an outage degrades to all-None (pollers keep
        # waiting, readiness flips) instead of raising
        keys = list(keys)
        if not keys:
            return {}
        try:
            vals = self._guarded_read(self.r.hmget, self.table, keys)
        except _ReadUnavailable:
            return {key: None for key in keys}
        return {key: (json.loads(v) if v else None)
                for key, v in zip(keys, vals)}

    def result_count(self):
        return self.r.hlen(self.table)

    def delete_result(self, key):
        self.r.hdel(self.table, key)

    def put_error(self, key, error, record=None, trace_id=None):
        self.r.hset(self.table, key,
                    json.dumps(_error_result(error, record, trace_id)))
        self.r.xadd(self.dead_stream,
                    {"data": json.dumps(_dead_letter_entry(key, error,
                                                           record,
                                                           trace_id))})

    def dead_letters(self):
        return [e for _, e in self._dead_letter_items()]

    def dead_letter_count(self):
        # probes call this every few seconds: XLEN, not a full XRANGE+parse
        try:
            return int(self.r.xlen(self.dead_stream))
        except Exception:  # noqa: BLE001 — outage
            return -1

    def _dead_letter_items(self):
        out = []
        for eid, fields in self.r.xrange(self.dead_stream):
            try:
                out.append((eid, json.loads(fields[b"data"])))
            except (KeyError, ValueError, TypeError):
                continue
        return out

    def _remove_dead_letter(self, token):
        self.r.xdel(self.dead_stream, token)

    def trim(self, max_len):
        self.r.xtrim(self.stream, maxlen=max_len)
        self.r.xtrim(self.dead_stream, maxlen=max_len)


class _ReadUnavailable(RuntimeError):
    """Internal: the guarded Redis read path is down (retry exhausted or
    breaker open) — callers degrade instead of crashing the worker."""


def make_queue(kind: str = "inproc", **kwargs) -> BaseQueue:
    if kind == "inproc":
        return InProcQueue(max_depth=kwargs.get("max_depth"))
    if kind == "file":
        return FileQueue(kwargs["root"], max_depth=kwargs.get("max_depth"))
    if kind == "redis":
        return RedisQueue(**kwargs)
    raise ValueError(f"unknown queue kind {kind!r}")
