"""Continuous batching for autoregressive serving (PR 12 tentpole).

The serving engine was batch-in/batch-out end to end: a generation request
batch held every member hostage until the SLOWEST decode finished, and a
new request arriving one step after a batch dispatched waited a full
rollout.  This module is the token-level scheduler that fixes both — the
Orca (OSDI '22) / vLLM continuous-batching shape, built on the step-wise
decode API the generation models now expose
(``init_decode``/``decode_step``, models/seq2seq.py and
models/textmodels.py):

- **slot map** — decode runs over fixed ``(max_active, bucket)``-shaped
  state buffers ("lanes", one per pow-2 capacity bucket).  Requests CLAIM a
  free slot at a decode-step boundary (prefill via ``init_decode`` on a
  pow-2-padded prompt, inserted with ``.at[slot].set``), generate one token
  per step, and FREE the slot the moment they hit EOS / their token budget
  / their deadline — the freed slot is refilled at the next boundary, so
  one slow request never gates its neighbours.
- **compile-once programs** — every device program (one prefill per
  (prompt-bucket, lane), one decode step + one insert per lane) has a fixed
  shape, is compiled once through ``jax.jit(...).lower().compile()`` and
  cached; steady-state serving performs ZERO retraces no matter how
  requests churn (asserted via ``inference/aot.py`` ``COMPILE_STATS``).
  ``warm()`` pre-compiles the whole set from the same
  ``aot.generation_manifest`` the serving warm-up manifest carries, so a
  warm replica serves its first token with zero compiles.
- **mesh placement** — lane state buffers are committed with a
  ``NamedSharding`` over the PR 6 serving mesh when the model is sharded
  (slot axis over ``data`` when it divides, replicated otherwise), so the
  decode step partitions like the rest of the predict plane.
- **events, not policy** — ``step()`` returns a list of ``GenEvent``s
  (first_token / partial / finish / shed / quarantine); the engine turns
  them into result writes, acks, quarantines and metrics, so the existing
  per-record contracts (tracing, deadlines, lease ack, dead-letter) ride
  unchanged.  A poisoned request (over-long or junk prompt, prefill
  failure) quarantines ITS SLOT only: rows are independent in every lane
  program, so neighbours' outputs are bitwise identical with or without
  the poison.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)


def _pow2_ceil(n: int) -> int:
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


def _pow2_ladder(lo: int, hi: int) -> List[int]:
    """Pow-2 values in [lo, hi] (hi rounded up), smallest first."""
    out = []
    b = _pow2_ceil(lo)
    hi = _pow2_ceil(hi)
    while b <= hi:
        out.append(b)
        b *= 2
    return out


@dataclass
class GenerationParams:
    """``ServingParams.generation`` surface (config.yaml ``generation:``
    section).

    - ``max_active_slots`` — decode slots per lane: the in-flight batch
      width of the compiled decode-step program.
    - ``max_tokens`` — per-request generation budget (records may lower it
      via ``{"gen": {"max_tokens": n}}``, never raise it).
    - ``eos_id`` — stop token (None = budget-only stopping);
      ``start_id`` — first decoder token for encoder/decoder models whose
      prefill yields no logits (Seq2seq).
    - ``max_prompt_len`` — longest accepted prompt; longer quarantines.
    - ``bucket_lens`` — the pow-2 capacity ladder: one decode lane per
      value, a request lands in the smallest lane holding
      ``prompt + max_tokens``.  Default: one lane at
      ``pow2(max_prompt_len + max_tokens)``.
    - ``prefill_buckets`` — pow-2 prompt padding ladder (default 8 ..
      pow2(max_prompt_len)); one compiled prefill program per (bucket,
      lane) pair.
    - ``stream_interval`` — tokens between partial-result flushes
      (``OutputQueue`` partials / ``GET /v1/result`` tokens-so-far);
      0 disables streaming.
    - ``decode_quantum`` — tokens decoded per scheduler boundary: the
      decode program scans this many steps internally, so the per-call
      dispatch/sync overhead is paid once per ``decode_quantum`` tokens
      instead of per token (the CPU/host analog of GPU graph capture).
      Requests still join/leave at boundaries; a request finishing
      mid-quantum wastes at most ``decode_quantum - 1`` row-steps (its
      post-EOS tokens are discarded on host).  1 = pure per-token
      scheduling.
    - ``paged`` — paged KV mode (PR 18): KV lives in a fixed block POOL
      instead of per-slot monolithic lanes; each slot holds a block
      table, admission is bounded by free blocks, and prompts sharing a
      registered prefix share its resident pages.  Needs a model with
      the paged decode API (``models/textmodels.TransformerLM``).
    - ``block_len`` — tokens per pool block (pow-2).
    - ``pool_blocks`` — usable pool blocks (default: enough for every
      slot at full lane capacity, i.e. ``max_active_slots * bucket /
      block_len`` — sized DOWN is how paged mode oversubscribes HBM).
    - ``kv_quant`` — ``off`` | ``int8``: int8 pool blocks with
      per-(block, head) scales, dequantized in-kernel at decode.
    - ``prefix_cache`` — share resident full-block prompt prefixes
      across requests (LRU index, evicted when the pool runs dry).
    - ``checkpoint_interval`` — generation continuity (PR 20): snapshot
      every active slot's resume state each time it accrues this many
      new tokens (0 = off).  Snapshots are collected at step boundaries
      and spooled by the engine off the hot path.
    - ``resume`` — admit reclaimed records carrying a valid snapshot as
      RESUMES: prefill over prompt + generated-so-far, continue decoding
      at the exact token position (greedy decode makes the continuation
      token-exact — streamed partials are always a prefix of the
      terminal).  Needs a cache model; bare-state models downgrade
      loudly to restart-from-0.
    """

    max_active_slots: int = 8
    max_tokens: int = 32
    eos_id: Optional[int] = None
    start_id: int = 1
    max_prompt_len: int = 64
    bucket_lens: Optional[List[int]] = None
    prefill_buckets: Optional[List[int]] = None
    stream_interval: int = 8
    decode_quantum: int = 4
    paged: bool = False
    block_len: int = 16
    pool_blocks: Optional[int] = None
    kv_quant: str = "off"
    prefix_cache: bool = True
    # generation continuity (PR 20): checkpoint active slots' resume
    # state every `checkpoint_interval` generated tokens (0 = off) and
    # admit reclaimed records with a valid snapshot as resumes
    checkpoint_interval: int = 0
    resume: bool = False

    def __post_init__(self):
        self.max_active_slots = max(1, int(self.max_active_slots))
        self.max_tokens = max(1, int(self.max_tokens))
        self.start_id = int(self.start_id)
        self.max_prompt_len = max(1, int(self.max_prompt_len))
        self.stream_interval = max(0, int(self.stream_interval))
        self.decode_quantum = max(1, int(self.decode_quantum))
        self.paged = bool(self.paged)
        self.prefix_cache = bool(self.prefix_cache)
        self.block_len = _pow2_ceil(self.block_len)
        if self.kv_quant not in ("off", "int8"):
            raise ValueError(
                f"kv_quant must be 'off' or 'int8', got {self.kv_quant!r}")
        if self.pool_blocks is not None:
            self.pool_blocks = max(1, int(self.pool_blocks))
        if self.eos_id is not None:
            self.eos_id = int(self.eos_id)
        if self.bucket_lens is None:
            self.bucket_lens = [
                _pow2_ceil(self.max_prompt_len + self.max_tokens)]
        self.bucket_lens = sorted({_pow2_ceil(b) for b in self.bucket_lens})
        if self.prefill_buckets is None:
            self.prefill_buckets = _pow2_ladder(
                min(8, _pow2_ceil(self.max_prompt_len)),
                self.max_prompt_len)
        self.prefill_buckets = sorted(
            {_pow2_ceil(b) for b in self.prefill_buckets})
        # a user-supplied ladder must still cover every ADMISSIBLE prompt
        # (<= max_prompt_len), or valid requests would have no prefill
        # program to land in
        cap = _pow2_ceil(self.max_prompt_len)
        if self.prefill_buckets[-1] < cap:
            self.prefill_buckets.append(cap)
        self.checkpoint_interval = max(0, int(self.checkpoint_interval))
        self.resume = bool(self.resume)
        if self.resume:
            # a resume re-prefills over prompt + generated_so_far, which
            # can reach max_prompt_len + max_tokens — extend the ladder so
            # the resume prefill is a warmed program, never a steady-state
            # compile (warmup_manifest walks prefill_buckets; the AOT
            # manifest filters pb > lane automatically)
            rcap = _pow2_ceil(self.max_prompt_len + self.max_tokens)
            last = self.prefill_buckets[-1]
            while last < rcap:
                last *= 2
                self.prefill_buckets.append(last)

    @classmethod
    def from_dict(cls, d: Optional[Dict]) -> "GenerationParams":
        if not isinstance(d, dict):
            return cls()
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        return cls(**{k: v for k, v in d.items() if k in known})


class GenRequest:
    """One admitted generation request (engine-internal)."""

    __slots__ = ("rid", "prompt", "deadline_ns", "trace_id", "t_read",
                 "max_tokens", "t_submit", "tenant", "resume_tokens",
                 "epoch")

    def __init__(self, rid: str, prompt: np.ndarray,
                 deadline_ns: Optional[int] = None,
                 trace_id: Optional[str] = None,
                 t_read: Optional[float] = None,
                 max_tokens: Optional[int] = None,
                 tenant: Optional[str] = None,
                 resume_tokens: Optional[List[int]] = None,
                 epoch: int = 0):
        self.rid = rid
        self.prompt = prompt            # ORIGINAL prompt, resume or not
        self.deadline_ns = deadline_ns
        self.trace_id = trace_id
        self.t_read = t_read
        self.max_tokens = max_tokens
        self.tenant = tenant
        # generation continuity (PR 20): tokens a dead owner already
        # produced — admission pre-seeds the slot with them and prefills
        # over prompt + resume_tokens; epoch counts ownership handoffs
        self.resume_tokens = resume_tokens
        self.epoch = int(epoch)
        self.t_submit = time.monotonic()


@dataclass
class GenEvent:
    """One scheduler outcome the engine must act on.

    ``kind``: ``first_token`` (TTFT stamp), ``partial`` (stream
    tokens-so-far), ``finish`` (terminal result), ``shed``
    (deadline-exceeded at a step boundary), ``quarantine`` (poisoned
    request isolated), ``resume_failed`` (PR 20: a resume prefix could
    not be replayed — the request restarts from token 0, loudly;
    ``tokens`` carries the wasted prefix, ``error`` the reason)."""

    kind: str
    rid: str
    trace_id: Optional[str] = None
    tokens: Optional[List[int]] = None
    finish_reason: Optional[str] = None
    error: Optional[str] = None
    ttft_s: Optional[float] = None
    t_read: Optional[float] = None
    wall_s: Optional[float] = None
    tenant: Optional[str] = None       # attribution (PR 19)


class _Slot:
    __slots__ = ("req", "generated", "t_first", "last_stream", "budget",
                 "ckpt_mark")

    def __init__(self, req: GenRequest, budget: int):
        self.req = req
        self.generated: List[int] = []
        self.t_first: Optional[float] = None
        self.last_stream = 0
        self.budget = budget
        # tokens-generated count at the last checkpoint (PR 20)
        self.ckpt_mark = 0


class _Lane:
    """One capacity bucket: fixed (max_active, bucket) state buffers plus
    the host-side slot map."""

    def __init__(self, bucket: int, max_active: int):
        self.bucket = int(bucket)
        self.max_active = int(max_active)
        self.slots: List[Optional[_Slot]] = [None] * self.max_active
        self.free: deque = deque(range(self.max_active))
        self.state = None                  # device pytree, lazily allocated
        self.tokens = np.zeros((self.max_active,), np.int32)

    @property
    def active(self) -> int:
        return self.max_active - len(self.free)


class _PagedLane(_Lane):
    """Paged-KV lane (PR 18): ``state`` holds the POOL pytree instead of
    per-slot caches, and the per-slot cache geometry lives in host-side
    block tables.  Inactive slots keep their table row zeroed (every
    entry -> the trash block), so their in-program decode writes land
    harmlessly."""

    def __init__(self, bucket: int, max_active: int, block_len: int):
        super().__init__(bucket, max_active)
        self.ntab = bucket // block_len
        self.tables = np.zeros((max_active, self.ntab), np.int32)
        self.pos = np.zeros((max_active,), np.int32)
        # per-slot owned block ids (shared-prefix refs + private), for
        # release on free
        self.blocks: List[Optional[List[int]]] = [None] * max_active


class ContinuousBatcher:
    """Token-level decode scheduler over an ``InferenceModel`` whose inner
    layer exposes ``init_decode``/``decode_step`` (see module docstring).

    Thread contract: ``submit`` may be called from any thread (bounded
    waiting deque); ``step``/``warm`` must run on ONE thread (the engine's
    ``serving-generate`` worker)."""

    MAX_WAITING = 1024

    def __init__(self, model, gen: GenerationParams):
        inner = getattr(model, "_model", None)
        if inner is None or not hasattr(inner, "init_decode") \
                or not hasattr(inner, "decode_step"):
            raise ValueError(
                "generation needs a model whose topology implements "
                "init_decode/decode_step (models/seq2seq.Seq2seq, "
                "models/textmodels.TransformerLM)")
        self.model = model
        self.inner = inner
        self.gen = gen
        import inspect
        sig = inspect.signature(inner.init_decode)
        # cache models (fixed-length KV caches) take cache_len and their
        # prefill yields first-token logits; bare-state models (LSTM
        # stacks) take neither and start from gen.start_id
        self._cache_model = "cache_len" in sig.parameters
        self._vocab = int(getattr(inner, "vocab_size", 0) or 0)
        model_cap = int(getattr(inner, "max_len", 0) or 0)
        # a cache lane must fit under the model's max_len AND hold at
        # least the smallest prefill bucket (prefill allocates the cache
        # at lane capacity, so cache_len >= prompt bucket must hold)
        usable = [
            b for b in gen.bucket_lens
            if not (self._cache_model
                    and ((model_cap and b > model_cap)
                         or b < gen.prefill_buckets[0]))]
        if not usable:
            raise ValueError(
                f"no usable decode lane: bucket_lens={gen.bucket_lens} "
                f"all exceed the model's max_len={model_cap} or fall "
                f"below the smallest prefill bucket "
                f"{gen.prefill_buckets[0]}")
        if len(usable) < len(gen.bucket_lens):
            logger.warning(
                "generate: dropped %d unusable decode lane(s) from "
                "bucket_lens=%s (model max_len=%s, smallest prefill "
                "bucket %d)", len(gen.bucket_lens) - len(usable),
                gen.bucket_lens, model_cap or "n/a",
                gen.prefill_buckets[0])
        self._pool = None
        self._prefix = None
        self.pool_exhausted = 0
        self._exhausted_boundary = False
        if gen.paged:
            missing = [m for m in ("prefill_kv", "prefill_shared",
                                   "decode_paged", "init_paged_pools")
                       if not hasattr(inner, m)]
            if missing:
                raise ValueError(
                    "generation.paged=true needs a model with the paged "
                    "decode API (models/textmodels.TransformerLM); "
                    f"missing: {missing}")
            bucket = max(usable)
            if gen.block_len > bucket:
                raise ValueError(
                    f"block_len={gen.block_len} > lane capacity {bucket}")
            # ONE paged lane at the largest capacity: block tables make
            # per-request capacity a table-width concern, not a lane
            # concern, so the bucket ladder collapses
            lane = _PagedLane(bucket, gen.max_active_slots, gen.block_len)
            self._lanes = [lane]
            from analytics_zoo_tpu.serving.kvpool import (BlockPool,
                                                          PrefixIndex)
            n_pool = gen.pool_blocks if gen.pool_blocks is not None \
                else gen.max_active_slots * lane.ntab
            self._pool = BlockPool(n_pool, gen.block_len)
            if gen.prefix_cache:
                self._prefix = PrefixIndex(self._pool)
        else:
            self._lanes = [_Lane(b, gen.max_active_slots) for b in usable]
        self._waiting: deque = deque()
        self._waiting_lock = threading.Lock()
        # per-boundary decode accounting (PR 13 tracing): after each
        # step(), (rid, trace_id, tokens_emitted_this_boundary) for every
        # slot that ran a decode step — the engine turns these into the
        # per-boundary decode spans TTFT decomposition needs
        self.last_boundary: List[Tuple] = []
        self.last_admitted = 0       # admissions at the last boundary
        # compiled programs: ("prefill", pb, lane_bucket) |
        # ("decode_step", lane_bucket) | ("insert", lane_bucket)
        self._programs: Dict[tuple, object] = {}
        # per-program execution counts (PR 15 resource accounting):
        # scheduler-thread-only, keyed by the manifest-style program name
        self._exec_counts: Dict[str, int] = {}
        self.compiles = 0
        self.decode_steps = 0
        self.generated_tokens = 0
        self.admitted = 0
        self.finished = 0
        self.quarantined = 0
        self.shed = 0
        # generation continuity (PR 20): resume admissions, loud
        # downgrades to restart-from-0, and checkpoints collected at step
        # boundaries (the engine drains + spools them off the hot path);
        # snapshot_bytes mirrors the spool size for the ResourceLedger
        self.resumed = 0
        self.resume_failed = 0
        self.checkpoints = 0
        self.snapshot_bytes = 0
        self.pending_checkpoints: List[Dict] = []
        # COMPILE_STATS listeners: steady-state zero-compile evidence
        from analytics_zoo_tpu.inference import aot
        aot.install_compile_listeners()
        # lane buffers allocated EAGERLY: the warm-up thread and the
        # generate worker both touch lane.state, and lazy allocation would
        # let one overwrite the other's freshly-inserted request state.
        # (Program compiles stay lock-free — a rare duplicate compile is
        # benign, and serializing them would queue a live request behind
        # the whole warm-up set.)
        for lane in self._lanes:
            self._ensure_lane_state(lane)

    # -- program construction (compile-once) ----------------------------------
    def _params(self):
        return self.model._params

    def _jit_key_fns(self, lane_bucket: int):
        import jax
        inner = self.inner

        if self._cache_model:
            def prefill(p, prompt, lengths):
                return inner.init_decode(p, prompt, lengths,
                                         cache_len=lane_bucket)
        else:
            def prefill(p, prompt, lengths):
                return inner.init_decode(p, prompt, lengths)

        K = self.gen.decode_quantum

        def step(p, state, tokens):
            # K decode steps under one lax.scan: one dispatch + one host
            # sync per K tokens.  No in-program EOS logic — the host sees
            # all K tokens per slot and discards everything past a row's
            # EOS/budget; a freed slot's state is fully overwritten by the
            # next insert, so post-finish garbage never leaks.
            def body(carry, _):
                st, tok = carry
                logits, st2 = inner.decode_step(p, st, tok)
                nxt = jax.numpy.argmax(logits, axis=-1).astype("int32")
                return (st2, nxt), nxt

            (st, _), toks = jax.lax.scan(body, (state, tokens), None,
                                         length=K)
            return toks, st            # toks: (K, max_active)

        def insert(state, sub, row, slot):
            # one admitted request: copy `sub` row `row` (an admission
            # batch member) into lane slot `slot`
            return jax.tree.map(lambda L, s: L.at[slot].set(s[row]),
                                state, sub)

        return (jax.jit(prefill), jax.jit(step), jax.jit(insert))

    def _lane_fns(self, lane: _Lane):
        key = ("fns", lane.bucket)
        fns = self._programs.get(key)
        if fns is None:
            fns = self._jit_key_fns(lane.bucket)
            self._programs[key] = fns
        return fns

    def _paged_fns(self):
        """The three paged-mode jit functions (PR 18): ``pprefill``
        (prompt forward + block commit in ONE program, so raw prompt K/V
        never leaves the device), ``pshared`` (suffix-only prefill over
        pool-resident prefix blocks + commit) and ``pdecode``
        (decode_quantum paged decode steps under one scan)."""
        key = ("pfns",)
        fns = self._programs.get(key)
        if fns is not None:
            return fns
        import jax
        import jax.numpy as jnp
        from analytics_zoo_tpu.inference.quantize import (kv_pack_int8,
                                                          kv_unpack_int8)
        inner = self.inner
        bl = self.gen.block_len
        kq = self.gen.kv_quant
        K = self.gen.decode_quantum

        def commit(pools, ks, vs, lengths, dest, slots):
            """Scatter the batch's (length-masked) K/V into pool blocks:
            row j's block t lands at pool id ``dest[j, t]`` (0 = trash,
            for padding rows and blocks past the row's fill).  int8 mode
            quantizes per block and parks each row's partial TAIL block
            in its slot's f32 staging buffer (``slots``; the sentinel
            ``max_active`` drops padding rows), so decode appends
            re-quantize from exact values."""
            npb = dest.shape[1]
            bb, pb, nh, hd = ks[0].shape
            pad = npb * bl
            valid = (jnp.arange(pb)[None, :]
                     < lengths[:, None])[..., None, None]
            out = {k2: list(v2) for k2, v2 in pools.items()}
            tb = jnp.minimum(lengths // bl, npb - 1)
            tsel = tb[:, None, None, None, None]
            for li in range(len(ks)):
                k = jnp.where(valid, ks[li], 0.0)
                v = jnp.where(valid, vs[li], 0.0)
                if pad > pb:
                    z = jnp.zeros((bb, pad - pb, nh, hd), jnp.float32)
                    k = jnp.concatenate([k, z], axis=1)
                    v = jnp.concatenate([v, z], axis=1)
                kb = k.reshape(bb, npb, bl, nh, hd)
                vb = v.reshape(bb, npb, bl, nh, hd)
                if kq == "int8":
                    qk, sk = kv_pack_int8(kb)
                    qv, sv = kv_pack_int8(vb)
                    out["k"][li] = out["k"][li].at[dest].set(qk)
                    out["v"][li] = out["v"][li].at[dest].set(qv)
                    out["ks"][li] = out["ks"][li].at[dest].set(sk)
                    out["vs"][li] = out["vs"][li].at[dest].set(sv)
                    tk = jnp.take_along_axis(kb, tsel, axis=1)[:, 0]
                    tv = jnp.take_along_axis(vb, tsel, axis=1)[:, 0]
                    out["stk"][li] = out["stk"][li].at[slots].set(
                        tk, mode="drop")
                    out["stv"][li] = out["stv"][li].at[slots].set(
                        tv, mode="drop")
                else:
                    out["k"][li] = out["k"][li].at[dest].set(kb)
                    out["v"][li] = out["v"][li].at[dest].set(vb)
            return out

        def pprefill(p, prompt, lengths, pools, dest, slots):
            ks, vs, logits0 = inner.prefill_kv(p, prompt, lengths)
            return commit(pools, ks, vs, lengths, dest, slots), logits0

        def pshared(p, suffix, slens, prefix_len, ptab, pools, dest,
                    slots):
            npb = ptab.shape[1]
            bb = suffix.shape[0]
            pk, pv = [], []
            for li in range(len(pools["k"])):
                k = jnp.take(pools["k"][li], ptab, axis=0)
                v = jnp.take(pools["v"][li], ptab, axis=0)
                if kq == "int8":
                    k = kv_unpack_int8(
                        k, jnp.take(pools["ks"][li], ptab, axis=0))
                    v = kv_unpack_int8(
                        v, jnp.take(pools["vs"][li], ptab, axis=0))
                sh = k.shape
                pk.append(k.astype(jnp.float32)
                          .reshape(bb, npb * bl, *sh[3:]))
                pv.append(v.astype(jnp.float32)
                          .reshape(bb, npb * bl, *sh[3:]))
            ks, vs, logits0 = inner.prefill_shared(p, suffix, slens,
                                                   prefix_len, pk, pv)
            return commit(pools, ks, vs, slens, dest, slots), logits0

        def pdecode(p, pools, tables, pos, tokens):
            def body(carry, _):
                pl_, po_, tok = carry
                logits, pl2 = inner.decode_paged(
                    p, pl_, tables, po_, tok, block_len=bl, kv_quant=kq)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (pl2, po_ + 1, nxt), nxt

            (pools2, _, _), toks = jax.lax.scan(
                body, (pools, jnp.asarray(pos, jnp.int32), tokens), None,
                length=K)
            return toks, pools2           # toks: (K, max_active)

        fns = (jax.jit(pprefill), jax.jit(pshared), jax.jit(pdecode))
        self._programs[key] = fns
        return fns

    def _compiled(self, key: tuple, fn, *args):
        """AOT-compiled executable for one fixed-shape program, compiled
        exactly once; ``warm()`` walks the same path, so a warmed program
        is the very executable the hot path runs."""
        exe = self._programs.get(key)
        if exe is None:
            exe = fn.lower(*args).compile()
            self._programs[key] = exe
            self.compiles += 1
        return exe

    @staticmethod
    def _program_name(key: tuple) -> str:
        """Manifest-style label for one compiled scheduler program
        (PR 15 per-program exec accounting)."""
        if key[0] == "prefill":
            return f"prefill:b{key[1]}xp{key[2]}@{key[3]}"
        if key[0] == "insert":
            return f"insert:b{key[1]}@{key[2]}"
        if key[0] == "decode_step":
            return f"decode_step@{key[1]}"
        if key[0] == "pprefill":
            return f"paged_prefill:b{key[1]}xp{key[2]}"
        if key[0] == "pshared":
            return f"paged_shared:b{key[1]}xs{key[2]}xn{key[3]}"
        if key[0] == "pdecode":
            return f"paged_decode@{key[1]}"
        return ":".join(str(k) for k in key)

    def _count_exec(self, key: tuple) -> None:
        # scheduler-thread-only (step/admit run on one thread)
        label = self._program_name(key)
        self._exec_counts[label] = self._exec_counts.get(label, 0) + 1

    def _commit_state(self, state):
        """Commit a lane state buffer over the serving mesh (PR 6): slot
        axis over ``data`` when it divides, replicated otherwise.
        Single-chip models pass through."""
        mesh = getattr(self.model, "_mesh", None)
        if mesh is None:
            return state
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        dd = int(mesh.shape.get("data", 1))
        A = self.gen.max_active_slots
        shard_rows = dd > 1 and A % dd == 0

        def place(a):
            spec = P("data", *([None] * (a.ndim - 1))) \
                if (shard_rows and a.ndim >= 1) else P()
            return jax.device_put(a, NamedSharding(mesh, spec))

        return jax.tree.map(place, state)

    def _ensure_lane_state(self, lane: _Lane):
        if lane.state is not None:
            return
        import jax
        if isinstance(lane, _PagedLane):
            # pool pytree: +1 block for the reserved trash row; placed
            # whole (no slot axis to shard — the pool IS the point)
            pools = self.inner.init_paged_pools(
                self._pool.n_blocks + 1, self.gen.block_len,
                lane.max_active, self.gen.kv_quant)
            lane.state = jax.device_put(pools)
            return
        pb = self.gen.prefill_buckets[0]
        prefill, _, _ = self._lane_fns(lane)
        A = lane.max_active
        shapes = jax.eval_shape(
            prefill, self._params(),
            jax.ShapeDtypeStruct((A, pb), np.int32),
            jax.ShapeDtypeStruct((A,), np.int32))
        state_shapes = shapes[0] if self._is_pair(shapes) else shapes
        lane.state = self._commit_state(jax.tree.map(
            lambda sd: np.zeros(sd.shape, sd.dtype), state_shapes))
        lane.state = jax.device_put(lane.state) \
            if getattr(self.model, "_mesh", None) is None else lane.state

    @staticmethod
    def _is_pair(res) -> bool:
        """(state, logits) vs bare state: cache models return a 2-tuple
        whose second element is a rank-2 logits array."""
        return (isinstance(res, tuple) and len(res) == 2
                and hasattr(res[1], "shape")
                and getattr(res[1], "ndim", 0) == 2)

    # -- admission ------------------------------------------------------------
    def submit(self, req: GenRequest) -> bool:
        """Queue one request for the next step boundary.  False = waiting
        room full (caller should leave the record staged / backpressure)."""
        with self._waiting_lock:
            if len(self._waiting) >= self.MAX_WAITING:
                return False
            self._waiting.append(req)
            return True

    @property
    def waiting(self) -> int:
        with self._waiting_lock:
            return len(self._waiting)

    @property
    def active(self) -> int:
        return sum(lane.active for lane in self._lanes)

    @property
    def slots_total(self) -> int:
        return sum(lane.max_active for lane in self._lanes)

    def _req_budget(self, req: GenRequest) -> int:
        """Per-request token budget: the deployment cap, lowerable (never
        raisable) by the record's own max_tokens.  The ONE clamp both
        lane selection and the slot budget use — they must agree, or a
        request could land in a lane too small for its budget."""
        budget = self.gen.max_tokens
        if req.max_tokens is not None:
            budget = max(1, min(int(req.max_tokens), budget))
        return budget

    def _budget_for(self, req: GenRequest, lane: _Lane) -> int:
        budget = self._req_budget(req)
        if self._cache_model:
            budget = min(budget, lane.bucket - len(req.prompt))
        return max(1, budget)

    def _pick_lane(self, req: GenRequest) -> Optional[_Lane]:
        """Smallest lane whose capacity holds prompt + budget AND the
        prompt's padded prefill bucket (prefill allocates the cache at
        the lane capacity, so ``cache_len >= prefill bucket`` must hold);
        bare-state models (no length axis) use the first lane.  A resume
        (PR 20) prefills over prompt + resume prefix, so its prefill
        bucket is computed from the CONCAT length; total cache occupancy
        is still prompt + budget (the prefix counts against the budget)."""
        if not self._cache_model:
            return self._lanes[0]
        want = len(req.prompt) + self._req_budget(req)
        pb = self._prefill_bucket(len(req.prompt)
                                  + len(req.resume_tokens or ()))
        if pb is not None:
            want = max(want, pb)
        for lane in self._lanes:
            if lane.bucket >= want:
                return lane
        return None

    # -- resume admission (PR 20) ---------------------------------------------
    def _concat_prompt(self, req: GenRequest) -> np.ndarray:
        """Prefill input: the original prompt, plus — for a resume — the
        tokens the dead owner already produced (replaying them through
        prefill rebuilds the exact cache a continuous decode would hold,
        and greedy decode over it continues token-exactly)."""
        p = np.asarray(req.prompt).astype(np.int32).reshape(-1)
        if not req.resume_tokens:
            return p
        return np.concatenate([p, np.asarray(req.resume_tokens,
                                             np.int32)])

    def _downgrade_resume(self, req: GenRequest, reason: str,
                          events: List[GenEvent]) -> None:
        """Fall back LOUDLY to restart-from-0: the wasted prefix rides
        the event so the engine can meter it."""
        toks = [int(t) for t in req.resume_tokens or ()
                if isinstance(t, (int, float, np.integer))]
        req.resume_tokens = None
        self.resume_failed += 1
        events.append(GenEvent(
            "resume_failed", req.rid, trace_id=req.trace_id,
            tokens=toks, error=reason, t_read=req.t_read,
            tenant=req.tenant))

    def _take_resume(self, req: GenRequest,
                     events: List[GenEvent]) -> None:
        """Normalize a reclaimed request's resume prefix, downgrading to
        restart-from-0 when it cannot be replayed: bare-state models
        rebuild no cache at prefill (continuing would NOT be a prefix of
        an uninterrupted run), and a malformed or out-of-vocab prefix
        would poison the decode state."""
        rt = req.resume_tokens
        if not rt:
            req.resume_tokens = None
            return
        try:
            toks = [int(t) for t in rt]
        except (TypeError, ValueError):
            self._downgrade_resume(req, "malformed resume prefix", events)
            return
        if not self._cache_model:
            self._downgrade_resume(
                req, "bare-state model cannot replay decode state",
                events)
            return
        if self._vocab and toks and (min(toks) < 0
                                     or max(toks) >= self._vocab):
            self._downgrade_resume(
                req, "resume token id out of vocab range", events)
            return
        cap = self._req_budget(req) - 1
        if cap < 1:
            self._downgrade_resume(req, "token budget already consumed",
                                   events)
            return
        # a prefix at/over budget should have finished at the old owner;
        # keep budget-1 so the resumed slot still decodes >= 1 token
        req.resume_tokens = toks[:cap]

    def _seed_resume(self, info: _Slot) -> None:
        """Pre-seed a just-admitted slot with its resume prefix: the
        terminal token list stays the full generation (partials remain a
        prefix of it), while `last_stream`/`ckpt_mark` start past the
        prefix so streaming cadence and checkpoint cadence resume where
        the dead owner left off.  `step()`'s boundary accounting reports
        only post-admission deltas, so the engine meters delta tokens
        only — no double-billing across the resume epoch."""
        rt = info.req.resume_tokens
        if not rt:
            return
        info.generated = [int(t) for t in rt]
        info.last_stream = len(info.generated)
        info.ckpt_mark = len(info.generated)
        self.resumed += 1

    def _validate(self, req: GenRequest) -> Optional[str]:
        p = np.asarray(req.prompt)
        if p.ndim != 1 or p.size == 0:
            return f"prompt must be a non-empty 1-D token sequence, got " \
                   f"shape {p.shape}"
        if p.size > self.gen.max_prompt_len:
            return f"prompt length {p.size} > max_prompt_len " \
                   f"{self.gen.max_prompt_len}"
        if not np.all(np.isfinite(p)):
            return "prompt contains non-finite token ids"
        ids = p.astype(np.int64)
        if self._vocab and (ids.min() < 0 or ids.max() >= self._vocab):
            return f"token id out of range [0, {self._vocab})"
        return None

    def _prefill_bucket(self, n: int) -> Optional[int]:
        for b in self.gen.prefill_buckets:
            if b >= n:
                return b
        return None

    def _batch_bucket(self, n: int) -> int:
        """Admission-batch bucket: smallest pow-2 >= n, capped at the
        slot-count bucket (the grab loop never claims more than a lane's
        slots anyway)."""
        return min(_pow2_ceil(n), _pow2_ceil(self.gen.max_active_slots))

    def _admit_batch(self, lane: _Lane, pb: int, members, events) -> int:
        """Prefill + insert a same-(lane, prompt-bucket) admission group
        in ONE device call.  ``members``: (req, slot) pairs, slots already
        claimed.  B=1 prefill costs ~the same wall as B=8 (call overhead
        dominates at serving widths), so batching admissions is what keeps
        a churning request mix from spending its steps on prefill calls.
        Padding rows replicate row 0's prompt (any valid prompt works —
        their states are computed and discarded, never inserted).

        A failing batch falls back to singleton admission so a poisoned
        request that slipped past validation quarantines ALONE."""
        n = len(members)
        bb = self._batch_bucket(n)
        padded = np.zeros((bb, pb), np.int32)
        lengths = np.ones((bb,), np.int32)
        for j, (req, _) in enumerate(members):
            prompt = self._concat_prompt(req)
            padded[j, :prompt.size] = prompt
            lengths[j] = prompt.size
        for j in range(n, bb):
            padded[j] = padded[0]
            lengths[j] = lengths[0]
        prefill, _, insert = self._lane_fns(lane)
        try:
            self._ensure_lane_state(lane)
            exe = self._compiled(("prefill", bb, pb, lane.bucket), prefill,
                                 self._params(), padded, lengths)
            res = exe(self._params(), padded, lengths)
            self._count_exec(("prefill", bb, pb, lane.bucket))
            if self._is_pair(res):
                sub, logits0 = res
                # host-side argmax (matches the paged path): an eager
                # jnp.argmax would XLA-compile once per batch bucket —
                # a steady-state compile the admission path must not pay
                toks0 = np.asarray(logits0).argmax(axis=-1)
            else:
                sub, toks0 = res, None
            ins = self._compiled(("insert", bb, lane.bucket), insert,
                                 lane.state, sub, np.int32(0), np.int32(0))
        except Exception as e:  # noqa: BLE001 — batch-level failure
            if n == 1:
                req, slot = members[0]
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error=f"{type(e).__name__}: {e}", t_read=req.t_read,
                    tenant=req.tenant))
                lane.free.append(slot)
                return 0
            # isolate the poison: singleton admissions, per-slot blast
            # radius — neighbours' state buffers were never touched
            return sum(self._admit_batch(lane, pb, [mem], events)
                       for mem in members)
        admitted = 0
        for j, (req, slot) in enumerate(members):
            try:
                lane.state = ins(lane.state, sub, np.int32(j),
                                 np.int32(slot))
                self._count_exec(("insert", bb, lane.bucket))
            except Exception as e:  # noqa: BLE001 — per-row insert failure
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error=f"{type(e).__name__}: {e}", t_read=req.t_read,
                    tenant=req.tenant))
                lane.free.append(slot)
                continue
            info = _Slot(req, budget=self._budget_for(req, lane))
            self._seed_resume(info)
            lane.slots[slot] = info
            self.admitted += 1
            admitted += 1
            if toks0 is not None:
                # cache models emit their first token AT prefill: TTFT
                # stops here, and the token feeds the first decode step
                info.t_first = time.monotonic()
                events.append(GenEvent(
                    "first_token", req.rid, trace_id=req.trace_id,
                    ttft_s=info.t_first - req.t_submit,
                    t_read=req.t_read, tenant=req.tenant))
                lane.tokens[slot] = int(toks0[j])
                self._account_token(lane, slot, info, int(toks0[j]),
                                    events)
            else:
                lane.tokens[slot] = self.gen.start_id
        return admitted

    # -- paged admission (PR 18) ----------------------------------------------
    def _reserve(self, lane: "_PagedLane", req: GenRequest):
        """Claim pool blocks for one request: the longest registered
        prompt prefix rides shared (referenced) pages, the rest allocates
        private blocks — evicting LRU prefix-cache entries if the pool
        runs dry.  Returns ``(k_shared, shared_ids, private_ids, plen)``
        or None (pool exhausted: the caller requeues and a typed
        ``kv_pool_exhausted`` flight-recorder event explains the stall)."""
        prompt = self._concat_prompt(req)
        plen = int(prompt.size)
        bl = self.gen.block_len
        # a resume's prefix tokens count against the budget, so blocks
        # for (concat - prefix) + budget == original prompt + budget
        need = (plen - len(req.resume_tokens or ())
                + self._budget_for(req, lane) + bl - 1) // bl
        need = min(need, lane.ntab)
        ksh, shared = 0, []
        if self._prefix is not None:
            # cap leaves >= 1 suffix token: first-token logits need at
            # least one position to actually prefill
            ksh, shared = self._prefix.lookup(
                prompt, max_blocks=(plen - 1) // bl)
        priv = self._pool.alloc(need - ksh)
        if priv is None and self._prefix is not None:
            self._prefix.evict_for(need - ksh)
            priv = self._pool.alloc(need - ksh)
        if priv is None:
            if shared:
                self._pool.release(shared)
            if not self._exhausted_boundary:
                self._exhausted_boundary = True
                self.pool_exhausted += 1
                from analytics_zoo_tpu.common.observability import \
                    get_recorder
                get_recorder().record(
                    "kv_pool_exhausted", rid=req.rid,
                    need_blocks=int(need - ksh),
                    free_blocks=int(self._pool.free_blocks),
                    active_slots=int(self.active),
                    waiting=int(self.waiting))
            return None
        return ksh, shared, priv, plen

    def _release_resv(self, resv) -> None:
        ksh, shared, priv, _ = resv
        if shared:
            self._pool.release(shared)
        if priv:
            self._pool.release(priv)

    def _admit_paged(self, events: List[GenEvent]) -> int:
        """Paged admission: like ``_admit`` but gated on pool blocks as
        well as free slots, grouped into prefix-MISS batches (full
        prefill, one program per (batch, prompt bucket)) and prefix-HIT
        batches (suffix-only prefill, one program per (batch, suffix
        bucket, prefix-table bucket))."""
        lane: _PagedLane = self._lanes[0]
        bl = self.gen.block_len
        grabbed: List[tuple] = []        # (req, slot, resv)
        while True:
            with self._waiting_lock:
                req = self._waiting.popleft() if self._waiting else None
            if req is None:
                break
            if self._expired(req.deadline_ns):
                self.shed += 1
                events.append(GenEvent(
                    "shed", req.rid, trace_id=req.trace_id,
                    t_read=req.t_read, tenant=req.tenant))
                continue
            err = self._validate(req)
            if err is not None:
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error=f"ValueError: {err}", t_read=req.t_read,
                    tenant=req.tenant))
                continue
            if req.resume_tokens:
                self._take_resume(req, events)
            if self._pick_lane(req) is None and req.resume_tokens:
                self._downgrade_resume(
                    req, "resume prefix exceeds lane capacity", events)
            if self._pick_lane(req) is None:
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error="ValueError: no decode lane holds prompt + "
                          f"max_tokens (buckets {self.gen.bucket_lens})",
                    t_read=req.t_read, tenant=req.tenant))
                continue
            if not lane.free:
                with self._waiting_lock:
                    self._waiting.appendleft(req)
                break
            resv = self._reserve(lane, req)
            if resv is None:
                with self._waiting_lock:
                    self._waiting.appendleft(req)
                break
            grabbed.append((req, lane.free.popleft(), resv))
        if not grabbed:
            return 0
        miss: Dict[int, list] = {}
        hit: Dict[tuple, list] = {}
        for req, slot, resv in grabbed:
            ksh, _, _, plen = resv
            pb = self._prefill_bucket(plen - ksh * bl)
            if pb is None:               # defensive, as in _admit
                self._release_resv(resv)
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error=f"ValueError: no prefill bucket holds prompt "
                          f"length {plen} (buckets "
                          f"{self.gen.prefill_buckets})",
                    t_read=req.t_read, tenant=req.tenant))
                lane.free.append(slot)
                continue
            if ksh:
                hit.setdefault((pb, _pow2_ceil(ksh)), []).append(
                    (req, slot, resv))
            else:
                miss.setdefault(pb, []).append((req, slot, resv))
        return sum(self._admit_paged_batch(lane, pb, members, events)
                   for pb, members in miss.items()) \
            + sum(self._admit_paged_batch(lane, sb, members, events,
                                          shared=npb)
                  for (sb, npb), members in hit.items())

    def _admit_paged_batch(self, lane: "_PagedLane", pb: int, members,
                           events, shared: Optional[int] = None) -> int:
        """Prefill + commit one same-bucket paged admission group in ONE
        device call.  ``shared`` = prefix-table bucket for prefix-HIT
        groups (None = full prefill).  Mirrors ``_admit_batch``'s
        singleton fallback so a poisoned request quarantines alone."""
        import jax
        bl = self.gen.block_len
        A = lane.max_active
        n = len(members)
        bb = self._batch_bucket(n)
        npb_dest = (pb + bl - 1) // bl
        padded = np.zeros((bb, pb), np.int32)
        lengths = np.ones((bb,), np.int32)
        dest = np.zeros((bb, npb_dest), np.int32)
        slots_arr = np.full((bb,), A, np.int32)     # A = drop sentinel
        if shared is not None:
            ptab = np.zeros((bb, shared), np.int32)
            plens = np.zeros((bb,), np.int32)
        for j, (req, slot, resv) in enumerate(members):
            ksh, shared_ids, priv, plen = resv
            prompt = self._concat_prompt(req)
            table = list(shared_ids) + list(priv)
            if shared is not None:
                suffix = prompt[ksh * bl:]
                padded[j, :suffix.size] = suffix
                lengths[j] = suffix.size
                ptab[j, :ksh] = shared_ids
                plens[j] = ksh * bl
                nfill = (suffix.size + bl - 1) // bl
                dest[j, :nfill] = table[ksh:ksh + nfill]
            else:
                padded[j, :plen] = prompt
                lengths[j] = plen
                nfill = (plen + bl - 1) // bl
                dest[j, :nfill] = table[:nfill]
            slots_arr[j] = slot
        for j in range(n, bb):
            # padding rows replicate row 0's prompt; their dest stays at
            # the trash block and their slot at the drop sentinel, so
            # nothing they compute is ever committed
            padded[j] = padded[0]
            lengths[j] = lengths[0]
            if shared is not None:
                ptab[j] = ptab[0]
                plens[j] = plens[0]
        pprefill, pshared, _ = self._paged_fns()
        try:
            self._ensure_lane_state(lane)
            if shared is None:
                key = ("pprefill", bb, pb)
                exe = self._compiled(key, pprefill, self._params(),
                                     padded, lengths, lane.state, dest,
                                     slots_arr)
                lane.state, logits0 = exe(self._params(), padded, lengths,
                                          lane.state, dest, slots_arr)
            else:
                key = ("pshared", bb, pb, shared)
                exe = self._compiled(key, pshared, self._params(),
                                     padded, lengths, plens, ptab,
                                     lane.state, dest, slots_arr)
                lane.state, logits0 = exe(self._params(), padded, lengths,
                                          plens, ptab, lane.state, dest,
                                          slots_arr)
            self._count_exec(key)
            toks0 = np.asarray(logits0).argmax(axis=-1)
        except Exception as e:  # noqa: BLE001 — batch-level failure
            if n == 1:
                req, slot, resv = members[0]
                self._release_resv(resv)
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error=f"{type(e).__name__}: {e}", t_read=req.t_read,
                    tenant=req.tenant))
                lane.free.append(slot)
                return 0
            return sum(self._admit_paged_batch(lane, pb, [m], events,
                                               shared=shared)
                       for m in members)
        admitted = 0
        for j, (req, slot, resv) in enumerate(members):
            ksh, shared_ids, priv, plen = resv
            table = list(shared_ids) + list(priv)
            lane.tables[slot, :] = 0
            lane.tables[slot, :len(table)] = table
            lane.pos[slot] = plen
            lane.blocks[slot] = table
            info = _Slot(req, budget=self._budget_for(req, lane))
            self._seed_resume(info)
            lane.slots[slot] = info
            self.admitted += 1
            admitted += 1
            if self._prefix is not None and ksh == 0:
                # park the prompt's FULL blocks for future sharers (the
                # partial tail block keeps being written by decode, so
                # it can never be shared); a resume registers the CONCAT
                # prefix — that is what its resident pages actually hold
                full = plen // bl
                if full:
                    prompt = self._concat_prompt(req)
                    self._prefix.register(prompt[:full * bl],
                                          table[:full])
            info.t_first = time.monotonic()
            events.append(GenEvent(
                "first_token", req.rid, trace_id=req.trace_id,
                ttft_s=info.t_first - req.t_submit, t_read=req.t_read,
                tenant=req.tenant))
            lane.tokens[slot] = int(toks0[j])
            self._account_token(lane, slot, info, int(toks0[j]), events)
        return admitted

    def _admit(self, events: List[GenEvent]) -> int:
        """Claim free slots for waiting requests and admit them in
        batched prefill groups.  Stops at the first head-of-line request
        whose lane is full (FIFO; retried next boundary)."""
        if self._pool is not None:
            return self._admit_paged(events)
        grabbed: List[tuple] = []        # (req, lane, slot)
        while True:
            with self._waiting_lock:
                req = self._waiting.popleft() if self._waiting else None
            if req is None:
                break
            if self._expired(req.deadline_ns):
                self.shed += 1
                events.append(GenEvent(
                    "shed", req.rid, trace_id=req.trace_id,
                    t_read=req.t_read, tenant=req.tenant))
                continue
            err = self._validate(req)
            if err is not None:
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error=f"ValueError: {err}", t_read=req.t_read,
                    tenant=req.tenant))
                continue
            if req.resume_tokens:
                self._take_resume(req, events)
            lane = self._pick_lane(req)
            if lane is None and req.resume_tokens:
                # the concat prefix pushed the prefill bucket past every
                # lane: a VALID request must not quarantine — restart it
                self._downgrade_resume(
                    req, "resume prefix exceeds lane capacity", events)
                lane = self._pick_lane(req)
            if lane is None:
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error="ValueError: no decode lane holds prompt + "
                          f"max_tokens (buckets {self.gen.bucket_lens})",
                    t_read=req.t_read, tenant=req.tenant))
                continue
            if not lane.free:
                # every slot of the right lane busy: the request stays at
                # the head for the next boundary (FIFO per lane is close
                # enough across lanes at this queue depth)
                with self._waiting_lock:
                    self._waiting.appendleft(req)
                break
            grabbed.append((req, lane, lane.free.popleft()))
        if not grabbed:
            return 0
        groups: Dict[tuple, list] = {}
        for req, lane, slot in grabbed:
            prompt_len = int(np.asarray(req.prompt).reshape(-1).size) \
                + len(req.resume_tokens or ())
            pb = self._prefill_bucket(prompt_len)
            if pb is None and req.resume_tokens:
                self._downgrade_resume(
                    req, "no prefill bucket holds resume prefix", events)
                prompt_len = int(np.asarray(req.prompt).reshape(-1).size)
                pb = self._prefill_bucket(prompt_len)
            if pb is None:
                # defensive: __post_init__ extends the ladder to cover
                # max_prompt_len, so this is unreachable from config —
                # but an uncovered prompt must quarantine, not crash the
                # worker with its slot claimed
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error=f"ValueError: no prefill bucket holds prompt "
                          f"length {prompt_len} (buckets "
                          f"{self.gen.prefill_buckets})",
                    t_read=req.t_read, tenant=req.tenant))
                lane.free.append(slot)
                continue
            groups.setdefault((lane.bucket, pb), (lane, pb, []))[2] \
                .append((req, slot))
        return sum(self._admit_batch(lane, pb, members, events)
                   for lane, pb, members in groups.values())

    # -- step boundary --------------------------------------------------------
    @staticmethod
    def _expired(deadline_ns) -> bool:
        if deadline_ns is None:
            return False
        try:
            return time.time_ns() > int(deadline_ns)
        except (TypeError, ValueError, OverflowError):
            return False      # gateway/engine validated upstream

    def _free(self, lane: _Lane, slot: int) -> None:
        if isinstance(lane, _PagedLane):
            if lane.blocks[slot]:
                self._pool.release(lane.blocks[slot])
                lane.blocks[slot] = None
            # zero the table row: the freed slot's in-program writes
            # land in the trash block until the next admission
            lane.tables[slot, :] = 0
            lane.pos[slot] = 0
        lane.slots[slot] = None
        lane.free.append(slot)

    def _finish(self, lane: _Lane, slot: int, info: _Slot, reason: str,
                events: List[GenEvent]) -> None:
        self.finished += 1
        now = time.monotonic()
        events.append(GenEvent(
            "finish", info.req.rid, trace_id=info.req.trace_id,
            tokens=list(info.generated), finish_reason=reason,
            ttft_s=(info.t_first - info.req.t_submit
                    if info.t_first is not None else None),
            t_read=info.req.t_read, wall_s=now - info.req.t_submit,
            tenant=info.req.tenant))
        self._free(lane, slot)

    def _account_token(self, lane: _Lane, slot: int, info: _Slot,
                       tok: int, events: List[GenEvent]) -> None:
        """Fold one emitted token into the slot: EOS / budget finish the
        request immediately (slot freed THIS boundary), stream_interval
        flushes partials."""
        eos = self.gen.eos_id
        if eos is not None and tok == eos:
            self._finish(lane, slot, info, "eos", events)
            return
        info.generated.append(int(tok))
        self.generated_tokens += 1
        if len(info.generated) >= info.budget:
            self._finish(lane, slot, info, "length", events)
            return
        si = self.gen.stream_interval
        if si and len(info.generated) - info.last_stream >= si:
            info.last_stream = len(info.generated)
            events.append(GenEvent(
                "partial", info.req.rid, trace_id=info.req.trace_id,
                tokens=list(info.generated), t_read=info.req.t_read,
                tenant=info.req.tenant))

    def _shed_active(self, events: List[GenEvent]) -> None:
        for lane in self._lanes:
            for slot, info in enumerate(lane.slots):
                if info is None or not self._expired(info.req.deadline_ns):
                    continue
                self.shed += 1
                events.append(GenEvent(
                    "shed", info.req.rid, trace_id=info.req.trace_id,
                    tokens=list(info.generated), t_read=info.req.t_read,
                    tenant=info.req.tenant))
                self._free(lane, slot)

    def step(self) -> List[GenEvent]:
        """One decode-step boundary: shed expired, admit into free slots,
        run one token step per non-empty lane, fold the emitted tokens.
        Returns the events the engine must act on; an idle scheduler
        returns [] without touching the device."""
        events: List[GenEvent] = []
        self.last_boundary = []
        self._exhausted_boundary = False
        self._shed_active(events)
        self.last_admitted = self._admit(events)
        for lane in self._lanes:
            if lane.active == 0:
                continue
            tokens = lane.tokens
            if isinstance(lane, _PagedLane):
                _, _, pdecode = self._paged_fns()
                key = ("pdecode", lane.bucket)
                exe = self._compiled(key, pdecode, self._params(),
                                     lane.state, lane.tables, lane.pos,
                                     tokens)
                block, lane.state = exe(self._params(), lane.state,
                                        lane.tables, lane.pos, tokens)
                self._count_exec(key)
                block = np.asarray(block)
                # host cursors advance with the in-scan carry; idle rows
                # clamp at lane capacity (their writes target the trash
                # block regardless).  MUST run before the token fold —
                # _free zeroes a finishing row's cursor.
                lane.pos = np.minimum(
                    lane.pos + np.int32(block.shape[0]),
                    np.int32(lane.bucket)).astype(np.int32)
            else:
                _, step, _ = self._lane_fns(lane)
                key = ("decode_step", lane.bucket)
                exe = self._compiled(key, step,
                                     self._params(), lane.state, tokens)
                block, lane.state = exe(self._params(), lane.state,
                                        tokens)
                self._count_exec(key)
                block = np.asarray(block)      # (decode_quantum, A)
            self.decode_steps += int(block.shape[0])   # token-level steps
            now = time.monotonic()
            for slot, info in enumerate(lane.slots):
                if info is None:
                    continue
                if info.t_first is None:
                    info.t_first = now
                    events.append(GenEvent(
                        "first_token", info.req.rid,
                        trace_id=info.req.trace_id,
                        ttft_s=info.t_first - info.req.t_submit,
                        t_read=info.req.t_read, tenant=info.req.tenant))
                n0 = len(info.generated)
                for k in range(block.shape[0]):
                    self._account_token(lane, slot, info,
                                        int(block[k, slot]), events)
                    if lane.slots[slot] is not info:
                        break      # finished mid-quantum: discard the rest
                # boundary accounting for the per-boundary decode spans
                # and per-tenant token charging (valid whether the
                # request finished this boundary or not — `info` outlives
                # the slot free)
                self.last_boundary.append(
                    (info.req.rid, info.req.trace_id,
                     len(info.generated) - n0, info.req.tenant))
            # copy: the device block is read-only, and the next boundary's
            # admission writes freshly-claimed slots into this row
            lane.tokens = np.array(block[-1])
        if self.gen.checkpoint_interval > 0 and self._cache_model:
            self._collect_checkpoints()
        return events

    def _collect_checkpoints(self) -> None:
        """Queue resume-state snapshots for slots that crossed the
        checkpoint interval since their last mark.  Host-side list work
        only — the engine drains `pending_checkpoints` and spools them
        OFF this thread, so the decode hot path never waits on disk.
        Bare-state models are skipped entirely: their decode state cannot
        be rebuilt by prefill, so a snapshot could never be resumed."""
        interval = self.gen.checkpoint_interval
        now = time.monotonic()
        for lane in self._lanes:
            for info in lane.slots:
                if info is None:
                    continue
                n = len(info.generated)
                if n - info.ckpt_mark < interval:
                    continue
                req = info.req
                prompt = np.asarray(req.prompt).reshape(-1)
                self.pending_checkpoints.append({
                    "rid": req.rid,
                    "epoch": req.epoch,
                    "prompt": [int(t) for t in prompt],
                    "tokens": list(info.generated),
                    "n": n,
                    "tenant": req.tenant,
                    "trace_id": req.trace_id,
                    "deadline_ns": req.deadline_ns,
                    "max_tokens": req.max_tokens,
                    # greedy argmax decode: the "RNG stream" is the
                    # degenerate deterministic one — recorded so a future
                    # sampling decode can refuse to resume across a
                    # sampler change instead of silently diverging
                    "sampler": "greedy",
                    "ts": now,
                })
                info.ckpt_mark = n
                self.checkpoints += 1

    def drain_checkpoints(self) -> List[Dict]:
        """Hand the queued snapshots to the engine (scheduler thread
        only, like `step`)."""
        out, self.pending_checkpoints = self.pending_checkpoints, []
        return out

    @property
    def idle(self) -> bool:
        return self.active == 0 and self.waiting == 0

    # -- warm-up (PR 11 integration) ------------------------------------------
    def warmup_manifest(self):
        """The (prefill-bucket x decode-step) program set for this
        deployment — delegated to ``aot.generation_manifest`` so the
        serving warm-up and ``manager warmup`` derive the same set."""
        from analytics_zoo_tpu.inference import aot
        prefix_blocks: Sequence[int] = ()
        if self._prefix is not None:
            max_sh = (self.gen.max_prompt_len - 1) // self.gen.block_len
            if max_sh >= 1:
                prefix_blocks = _pow2_ladder(1, max_sh)
        return aot.generation_manifest(
            self.gen.prefill_buckets,
            [lane.bucket for lane in self._lanes],
            prefill_batches=_pow2_ladder(1, self.gen.max_active_slots),
            cache_model=self._cache_model,
            paged=self._pool is not None,
            prefix_blocks=prefix_blocks)

    def warm(self, manifest=None, progress: Optional[Callable] = None,
             stop: Optional[Callable[[], bool]] = None) -> Dict:
        """Compile every scheduler program ahead of traffic.  Same stats
        document shape as ``aot.warm_up`` so the engine's warm-up thread
        and ``/readyz`` progress machinery drive either."""
        from analytics_zoo_tpu.inference import aot
        if manifest is None:
            manifest = self.warmup_manifest()
        before = aot.COMPILE_STATS.snapshot()
        t0 = time.monotonic()
        compiled = skipped = failed = 0
        stopped = False
        lanes = {lane.bucket: lane for lane in self._lanes}
        for i, entry in enumerate(manifest):
            if stop is not None and stop():
                stopped = True
                break
            try:
                fresh = self._warm_entry(entry, lanes)
                compiled += 1 if fresh else 0
                skipped += 0 if fresh else 1
            except Exception as e:  # noqa: BLE001 — one bad entry must not
                failed += 1         # strand the set; the live path compiles
                logger.warning("generate: warm-up entry %s failed (%s: %s)",
                               entry, type(e).__name__, e)
            if progress is not None:
                progress(i + 1, len(manifest), entry)
        after = aot.COMPILE_STATS.snapshot()
        return {"programs": len(manifest), "compiled": compiled,
                "skipped": skipped, "failed": failed, "stopped": stopped,
                "seconds": round(time.monotonic() - t0, 3),
                "compile_stats": {k: round(after[k] - before[k], 3)
                                  for k in after}}

    def _warm_entry(self, entry, lanes: Dict[int, "_Lane"]) -> bool:
        import jax
        lane = lanes.get(entry.lane_bucket)
        if lane is None:
            raise ValueError(f"no lane with bucket {entry.lane_bucket}")
        self._ensure_lane_state(lane)
        if entry.kind.startswith("paged_"):
            # compile-only (lower().compile() never executes), so the
            # dummy operands only fix shapes — pools stay untouched
            bl = self.gen.block_len
            A = lane.max_active
            pprefill, pshared, pdecode = self._paged_fns()
            bb = int(entry.prefill_batch or 1)
            if entry.kind == "paged_decode":
                key = ("pdecode", lane.bucket)
                fresh = key not in self._programs
                self._compiled(key, pdecode, self._params(), lane.state,
                               lane.tables, lane.pos, lane.tokens)
                return fresh
            pb = int(entry.prefill_bucket)
            npb_dest = (pb + bl - 1) // bl
            dummy = (np.zeros((bb, pb), np.int32),
                     np.ones((bb,), np.int32))
            dest = np.zeros((bb, npb_dest), np.int32)
            slots = np.full((bb,), A, np.int32)
            if entry.kind == "paged_prefill":
                key = ("pprefill", bb, pb)
                fresh = key not in self._programs
                self._compiled(key, pprefill, self._params(), *dummy,
                               lane.state, dest, slots)
                return fresh
            if entry.kind == "paged_shared":
                npb = int(entry.prefix_blocks or 1)
                key = ("pshared", bb, pb, npb)
                fresh = key not in self._programs
                self._compiled(key, pshared, self._params(), *dummy,
                               np.zeros((bb,), np.int32),
                               np.zeros((bb, npb), np.int32),
                               lane.state, dest, slots)
                return fresh
            raise ValueError(f"unknown warm-up entry kind {entry.kind!r}")
        prefill, step, insert = self._lane_fns(lane)
        if entry.kind == "prefill":
            pb = int(entry.prefill_bucket)
            bb = int(entry.prefill_batch or 1)
            key = ("prefill", bb, pb, lane.bucket)
            fresh = key not in self._programs
            dummy = np.zeros((bb, pb), np.int32)
            self._compiled(key, prefill, self._params(), dummy,
                           np.ones((bb,), np.int32))
            return fresh
        if entry.kind == "decode_step":
            key = ("decode_step", lane.bucket)
            fresh = key not in self._programs
            self._compiled(key, step, self._params(), lane.state,
                           lane.tokens)
            return fresh
        if entry.kind == "insert":
            # insert needs a prefilled sub-state: derive it abstractly so
            # warming never runs a real prefill
            bb = int(entry.prefill_batch or 1)
            key = ("insert", bb, lane.bucket)
            fresh = key not in self._programs
            pb = self.gen.prefill_buckets[0]
            shapes = jax.eval_shape(
                prefill, self._params(),
                jax.ShapeDtypeStruct((bb, pb), np.int32),
                jax.ShapeDtypeStruct((bb,), np.int32))
            sub_shapes = shapes[0] if self._is_pair(shapes) else shapes
            sub = jax.tree.map(lambda sd: np.zeros(sd.shape, sd.dtype),
                               sub_shapes)
            self._compiled(key, insert, lane.state, sub, np.int32(0),
                           np.int32(0))
            return fresh
        raise ValueError(f"unknown warm-up entry kind {entry.kind!r}")

    # -- observability --------------------------------------------------------
    @staticmethod
    def _leaf_bytes(leaves) -> int:
        total = 0
        for leaf in leaves:
            try:
                total += int(np.prod(leaf.shape)) \
                    * int(np.dtype(leaf.dtype).itemsize)
            except (TypeError, ValueError):
                continue
        return total

    def state_bytes_doc(self) -> Dict:
        """The ``kv_state`` ledger component, decomposed (PR 18):
        ``lanes`` (monolithic per-slot caches + int8 staging buffers —
        everything slot-shaped), ``paged_pool`` (the shared KV block
        pool), ``scales`` (int8 per-block scale planes) and ``aux``
        (per-slot host-side scheduler state: token cursors, block
        tables, position cursors — the PR 18 bugfix: these were never
        counted for unallocated lanes, so the gauge could under-report).
        Derived from leaf shapes/dtypes, so exact wherever jax placed
        the buffers."""
        import jax
        lanes_b = pool_b = scales_b = aux_b = 0
        for lane in self._lanes:
            aux_b += int(lane.tokens.nbytes)
            if isinstance(lane, _PagedLane):
                aux_b += int(lane.tables.nbytes) + int(lane.pos.nbytes)
            if lane.state is None:
                continue
            if isinstance(lane, _PagedLane):
                for part, leaves in lane.state.items():
                    nb = self._leaf_bytes(leaves)
                    if part in ("k", "v"):
                        pool_b += nb
                    elif part in ("ks", "vs"):
                        scales_b += nb
                    else:                # stk/stv: per-slot staging
                        lanes_b += nb
            else:
                lanes_b += self._leaf_bytes(
                    jax.tree_util.tree_leaves(lane.state))
        # snapshot spool bytes (PR 20): host/disk-side, but pinned BY the
        # generation plane — the engine mirrors the spool size here so
        # the ledger's aux component owns continuity state too
        aux_b += int(self.snapshot_bytes)
        return {"lanes": lanes_b, "paged_pool": pool_b,
                "scales": scales_b, "aux": aux_b,
                "total": lanes_b + pool_b + scales_b + aux_b}

    def state_bytes(self) -> int:
        """Bytes pinned by decode state — the ``kv_state`` component of
        the resource ledger (PR 15): lane/pool device buffers plus the
        per-slot host-side scheduler state (see ``state_bytes_doc``)."""
        return int(self.state_bytes_doc()["total"])

    def program_stats(self) -> Dict:
        """Compiled scheduler programs + per-program execution counts
        (PR 15): the generation half of the per-program exec accounting,
        keyed like the ``aot.generation_manifest`` entries
        (``prefill:b<batch>xp<bucket>@<lane>`` etc.)."""
        progs = {k: v for k, v in self._programs.items()
                 if k and k[0] not in ("fns", "pfns")}
        return {"count": len(progs),
                "programs": dict(self._exec_counts)}

    def stats(self) -> Dict:
        d = {"slots_total": self.slots_total,
             "active_slots": self.active,
             "waiting": self.waiting,
             "decode_steps": self.decode_steps,
             "generated_tokens": self.generated_tokens,
             "admitted": self.admitted,
             "finished": self.finished,
             "quarantined": self.quarantined,
             "shed": self.shed,
             "compiles": self.compiles,
             "resumed": self.resumed,
             "resume_failed": self.resume_failed,
             "checkpoints": self.checkpoints,
             "snapshot_bytes": self.snapshot_bytes,
             "can_resume": bool(self._cache_model),
             "lanes": [{"bucket": lane.bucket,
                        "max_active": lane.max_active,
                        "active": lane.active}
                       for lane in self._lanes]}
        if self._pool is not None:
            pool = {"blocks": self._pool.n_blocks,
                    "block_len": self._pool.block_len,
                    "free_blocks": self._pool.free_blocks,
                    "used_blocks": self._pool.used_blocks,
                    "occupancy": round(
                        self._pool.used_blocks
                        / max(1, self._pool.n_blocks), 4),
                    "kv_quant": self.gen.kv_quant,
                    "exhausted": self.pool_exhausted}
            if self._prefix is not None:
                ps = self._prefix.stats()
                pool.update({"prefix_entries": ps["entries"],
                             "prefix_hits": ps["hits"],
                             "prefix_misses": ps["misses"],
                             "prefix_evictions": ps["evictions"]})
            d["pool"] = pool
        return d
