"""Continuous batching for autoregressive serving (PR 12 tentpole).

The serving engine was batch-in/batch-out end to end: a generation request
batch held every member hostage until the SLOWEST decode finished, and a
new request arriving one step after a batch dispatched waited a full
rollout.  This module is the token-level scheduler that fixes both — the
Orca (OSDI '22) / vLLM continuous-batching shape, built on the step-wise
decode API the generation models now expose
(``init_decode``/``decode_step``, models/seq2seq.py and
models/textmodels.py):

- **slot map** — decode runs over fixed ``(max_active, bucket)``-shaped
  state buffers ("lanes", one per pow-2 capacity bucket).  Requests CLAIM a
  free slot at a decode-step boundary (prefill via ``init_decode`` on a
  pow-2-padded prompt, inserted with ``.at[slot].set``), generate one token
  per step, and FREE the slot the moment they hit EOS / their token budget
  / their deadline — the freed slot is refilled at the next boundary, so
  one slow request never gates its neighbours.
- **compile-once programs** — every device program (one prefill per
  (prompt-bucket, lane), one decode step + one insert per lane) has a fixed
  shape, is compiled once through ``jax.jit(...).lower().compile()`` and
  cached; steady-state serving performs ZERO retraces no matter how
  requests churn (asserted via ``inference/aot.py`` ``COMPILE_STATS``).
  ``warm()`` pre-compiles the whole set from the same
  ``aot.generation_manifest`` the serving warm-up manifest carries, so a
  warm replica serves its first token with zero compiles.
- **mesh placement** — lane state buffers are committed with a
  ``NamedSharding`` over the PR 6 serving mesh when the model is sharded
  (slot axis over ``data`` when it divides, replicated otherwise), so the
  decode step partitions like the rest of the predict plane.
- **events, not policy** — ``step()`` returns a list of ``GenEvent``s
  (first_token / partial / finish / shed / quarantine); the engine turns
  them into result writes, acks, quarantines and metrics, so the existing
  per-record contracts (tracing, deadlines, lease ack, dead-letter) ride
  unchanged.  A poisoned request (over-long or junk prompt, prefill
  failure) quarantines ITS SLOT only: rows are independent in every lane
  program, so neighbours' outputs are bitwise identical with or without
  the poison.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)


def _pow2_ceil(n: int) -> int:
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


def _pow2_ladder(lo: int, hi: int) -> List[int]:
    """Pow-2 values in [lo, hi] (hi rounded up), smallest first."""
    out = []
    b = _pow2_ceil(lo)
    hi = _pow2_ceil(hi)
    while b <= hi:
        out.append(b)
        b *= 2
    return out


@dataclass
class GenerationParams:
    """``ServingParams.generation`` surface (config.yaml ``generation:``
    section).

    - ``max_active_slots`` — decode slots per lane: the in-flight batch
      width of the compiled decode-step program.
    - ``max_tokens`` — per-request generation budget (records may lower it
      via ``{"gen": {"max_tokens": n}}``, never raise it).
    - ``eos_id`` — stop token (None = budget-only stopping);
      ``start_id`` — first decoder token for encoder/decoder models whose
      prefill yields no logits (Seq2seq).
    - ``max_prompt_len`` — longest accepted prompt; longer quarantines.
    - ``bucket_lens`` — the pow-2 capacity ladder: one decode lane per
      value, a request lands in the smallest lane holding
      ``prompt + max_tokens``.  Default: one lane at
      ``pow2(max_prompt_len + max_tokens)``.
    - ``prefill_buckets`` — pow-2 prompt padding ladder (default 8 ..
      pow2(max_prompt_len)); one compiled prefill program per (bucket,
      lane) pair.
    - ``stream_interval`` — tokens between partial-result flushes
      (``OutputQueue`` partials / ``GET /v1/result`` tokens-so-far);
      0 disables streaming.
    - ``decode_quantum`` — tokens decoded per scheduler boundary: the
      decode program scans this many steps internally, so the per-call
      dispatch/sync overhead is paid once per ``decode_quantum`` tokens
      instead of per token (the CPU/host analog of GPU graph capture).
      Requests still join/leave at boundaries; a request finishing
      mid-quantum wastes at most ``decode_quantum - 1`` row-steps (its
      post-EOS tokens are discarded on host).  1 = pure per-token
      scheduling.
    """

    max_active_slots: int = 8
    max_tokens: int = 32
    eos_id: Optional[int] = None
    start_id: int = 1
    max_prompt_len: int = 64
    bucket_lens: Optional[List[int]] = None
    prefill_buckets: Optional[List[int]] = None
    stream_interval: int = 8
    decode_quantum: int = 4

    def __post_init__(self):
        self.max_active_slots = max(1, int(self.max_active_slots))
        self.max_tokens = max(1, int(self.max_tokens))
        self.start_id = int(self.start_id)
        self.max_prompt_len = max(1, int(self.max_prompt_len))
        self.stream_interval = max(0, int(self.stream_interval))
        self.decode_quantum = max(1, int(self.decode_quantum))
        if self.eos_id is not None:
            self.eos_id = int(self.eos_id)
        if self.bucket_lens is None:
            self.bucket_lens = [
                _pow2_ceil(self.max_prompt_len + self.max_tokens)]
        self.bucket_lens = sorted({_pow2_ceil(b) for b in self.bucket_lens})
        if self.prefill_buckets is None:
            self.prefill_buckets = _pow2_ladder(
                min(8, _pow2_ceil(self.max_prompt_len)),
                self.max_prompt_len)
        self.prefill_buckets = sorted(
            {_pow2_ceil(b) for b in self.prefill_buckets})
        # a user-supplied ladder must still cover every ADMISSIBLE prompt
        # (<= max_prompt_len), or valid requests would have no prefill
        # program to land in
        cap = _pow2_ceil(self.max_prompt_len)
        if self.prefill_buckets[-1] < cap:
            self.prefill_buckets.append(cap)

    @classmethod
    def from_dict(cls, d: Optional[Dict]) -> "GenerationParams":
        if not isinstance(d, dict):
            return cls()
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        return cls(**{k: v for k, v in d.items() if k in known})


class GenRequest:
    """One admitted generation request (engine-internal)."""

    __slots__ = ("rid", "prompt", "deadline_ns", "trace_id", "t_read",
                 "max_tokens", "t_submit")

    def __init__(self, rid: str, prompt: np.ndarray,
                 deadline_ns: Optional[int] = None,
                 trace_id: Optional[str] = None,
                 t_read: Optional[float] = None,
                 max_tokens: Optional[int] = None):
        self.rid = rid
        self.prompt = prompt
        self.deadline_ns = deadline_ns
        self.trace_id = trace_id
        self.t_read = t_read
        self.max_tokens = max_tokens
        self.t_submit = time.monotonic()


@dataclass
class GenEvent:
    """One scheduler outcome the engine must act on.

    ``kind``: ``first_token`` (TTFT stamp), ``partial`` (stream
    tokens-so-far), ``finish`` (terminal result), ``shed``
    (deadline-exceeded at a step boundary), ``quarantine`` (poisoned
    request isolated)."""

    kind: str
    rid: str
    trace_id: Optional[str] = None
    tokens: Optional[List[int]] = None
    finish_reason: Optional[str] = None
    error: Optional[str] = None
    ttft_s: Optional[float] = None
    t_read: Optional[float] = None
    wall_s: Optional[float] = None


class _Slot:
    __slots__ = ("req", "generated", "t_first", "last_stream", "budget")

    def __init__(self, req: GenRequest, budget: int):
        self.req = req
        self.generated: List[int] = []
        self.t_first: Optional[float] = None
        self.last_stream = 0
        self.budget = budget


class _Lane:
    """One capacity bucket: fixed (max_active, bucket) state buffers plus
    the host-side slot map."""

    def __init__(self, bucket: int, max_active: int):
        self.bucket = int(bucket)
        self.max_active = int(max_active)
        self.slots: List[Optional[_Slot]] = [None] * self.max_active
        self.free: deque = deque(range(self.max_active))
        self.state = None                  # device pytree, lazily allocated
        self.tokens = np.zeros((self.max_active,), np.int32)

    @property
    def active(self) -> int:
        return self.max_active - len(self.free)


class ContinuousBatcher:
    """Token-level decode scheduler over an ``InferenceModel`` whose inner
    layer exposes ``init_decode``/``decode_step`` (see module docstring).

    Thread contract: ``submit`` may be called from any thread (bounded
    waiting deque); ``step``/``warm`` must run on ONE thread (the engine's
    ``serving-generate`` worker)."""

    MAX_WAITING = 1024

    def __init__(self, model, gen: GenerationParams):
        inner = getattr(model, "_model", None)
        if inner is None or not hasattr(inner, "init_decode") \
                or not hasattr(inner, "decode_step"):
            raise ValueError(
                "generation needs a model whose topology implements "
                "init_decode/decode_step (models/seq2seq.Seq2seq, "
                "models/textmodels.TransformerLM)")
        self.model = model
        self.inner = inner
        self.gen = gen
        import inspect
        sig = inspect.signature(inner.init_decode)
        # cache models (fixed-length KV caches) take cache_len and their
        # prefill yields first-token logits; bare-state models (LSTM
        # stacks) take neither and start from gen.start_id
        self._cache_model = "cache_len" in sig.parameters
        self._vocab = int(getattr(inner, "vocab_size", 0) or 0)
        model_cap = int(getattr(inner, "max_len", 0) or 0)
        # a cache lane must fit under the model's max_len AND hold at
        # least the smallest prefill bucket (prefill allocates the cache
        # at lane capacity, so cache_len >= prompt bucket must hold)
        self._lanes = [
            _Lane(b, gen.max_active_slots) for b in gen.bucket_lens
            if not (self._cache_model
                    and ((model_cap and b > model_cap)
                         or b < gen.prefill_buckets[0]))]
        if not self._lanes:
            raise ValueError(
                f"no usable decode lane: bucket_lens={gen.bucket_lens} "
                f"all exceed the model's max_len={model_cap} or fall "
                f"below the smallest prefill bucket "
                f"{gen.prefill_buckets[0]}")
        if len(self._lanes) < len(gen.bucket_lens):
            logger.warning(
                "generate: dropped %d unusable decode lane(s) from "
                "bucket_lens=%s (model max_len=%s, smallest prefill "
                "bucket %d)", len(gen.bucket_lens) - len(self._lanes),
                gen.bucket_lens, model_cap or "n/a",
                gen.prefill_buckets[0])
        self._waiting: deque = deque()
        self._waiting_lock = threading.Lock()
        # per-boundary decode accounting (PR 13 tracing): after each
        # step(), (rid, trace_id, tokens_emitted_this_boundary) for every
        # slot that ran a decode step — the engine turns these into the
        # per-boundary decode spans TTFT decomposition needs
        self.last_boundary: List[Tuple] = []
        self.last_admitted = 0       # admissions at the last boundary
        # compiled programs: ("prefill", pb, lane_bucket) |
        # ("decode_step", lane_bucket) | ("insert", lane_bucket)
        self._programs: Dict[tuple, object] = {}
        # per-program execution counts (PR 15 resource accounting):
        # scheduler-thread-only, keyed by the manifest-style program name
        self._exec_counts: Dict[str, int] = {}
        self.compiles = 0
        self.decode_steps = 0
        self.generated_tokens = 0
        self.admitted = 0
        self.finished = 0
        self.quarantined = 0
        self.shed = 0
        # COMPILE_STATS listeners: steady-state zero-compile evidence
        from analytics_zoo_tpu.inference import aot
        aot.install_compile_listeners()
        # lane buffers allocated EAGERLY: the warm-up thread and the
        # generate worker both touch lane.state, and lazy allocation would
        # let one overwrite the other's freshly-inserted request state.
        # (Program compiles stay lock-free — a rare duplicate compile is
        # benign, and serializing them would queue a live request behind
        # the whole warm-up set.)
        for lane in self._lanes:
            self._ensure_lane_state(lane)

    # -- program construction (compile-once) ----------------------------------
    def _params(self):
        return self.model._params

    def _jit_key_fns(self, lane_bucket: int):
        import jax
        inner = self.inner

        if self._cache_model:
            def prefill(p, prompt, lengths):
                return inner.init_decode(p, prompt, lengths,
                                         cache_len=lane_bucket)
        else:
            def prefill(p, prompt, lengths):
                return inner.init_decode(p, prompt, lengths)

        K = self.gen.decode_quantum

        def step(p, state, tokens):
            # K decode steps under one lax.scan: one dispatch + one host
            # sync per K tokens.  No in-program EOS logic — the host sees
            # all K tokens per slot and discards everything past a row's
            # EOS/budget; a freed slot's state is fully overwritten by the
            # next insert, so post-finish garbage never leaks.
            def body(carry, _):
                st, tok = carry
                logits, st2 = inner.decode_step(p, st, tok)
                nxt = jax.numpy.argmax(logits, axis=-1).astype("int32")
                return (st2, nxt), nxt

            (st, _), toks = jax.lax.scan(body, (state, tokens), None,
                                         length=K)
            return toks, st            # toks: (K, max_active)

        def insert(state, sub, row, slot):
            # one admitted request: copy `sub` row `row` (an admission
            # batch member) into lane slot `slot`
            return jax.tree.map(lambda L, s: L.at[slot].set(s[row]),
                                state, sub)

        return (jax.jit(prefill), jax.jit(step), jax.jit(insert))

    def _lane_fns(self, lane: _Lane):
        key = ("fns", lane.bucket)
        fns = self._programs.get(key)
        if fns is None:
            fns = self._jit_key_fns(lane.bucket)
            self._programs[key] = fns
        return fns

    def _compiled(self, key: tuple, fn, *args):
        """AOT-compiled executable for one fixed-shape program, compiled
        exactly once; ``warm()`` walks the same path, so a warmed program
        is the very executable the hot path runs."""
        exe = self._programs.get(key)
        if exe is None:
            exe = fn.lower(*args).compile()
            self._programs[key] = exe
            self.compiles += 1
        return exe

    @staticmethod
    def _program_name(key: tuple) -> str:
        """Manifest-style label for one compiled scheduler program
        (PR 15 per-program exec accounting)."""
        if key[0] == "prefill":
            return f"prefill:b{key[1]}xp{key[2]}@{key[3]}"
        if key[0] == "insert":
            return f"insert:b{key[1]}@{key[2]}"
        if key[0] == "decode_step":
            return f"decode_step@{key[1]}"
        return ":".join(str(k) for k in key)

    def _count_exec(self, key: tuple) -> None:
        # scheduler-thread-only (step/admit run on one thread)
        label = self._program_name(key)
        self._exec_counts[label] = self._exec_counts.get(label, 0) + 1

    def _commit_state(self, state):
        """Commit a lane state buffer over the serving mesh (PR 6): slot
        axis over ``data`` when it divides, replicated otherwise.
        Single-chip models pass through."""
        mesh = getattr(self.model, "_mesh", None)
        if mesh is None:
            return state
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        dd = int(mesh.shape.get("data", 1))
        A = self.gen.max_active_slots
        shard_rows = dd > 1 and A % dd == 0

        def place(a):
            spec = P("data", *([None] * (a.ndim - 1))) \
                if (shard_rows and a.ndim >= 1) else P()
            return jax.device_put(a, NamedSharding(mesh, spec))

        return jax.tree.map(place, state)

    def _ensure_lane_state(self, lane: _Lane):
        if lane.state is not None:
            return
        import jax
        pb = self.gen.prefill_buckets[0]
        prefill, _, _ = self._lane_fns(lane)
        A = lane.max_active
        shapes = jax.eval_shape(
            prefill, self._params(),
            jax.ShapeDtypeStruct((A, pb), np.int32),
            jax.ShapeDtypeStruct((A,), np.int32))
        state_shapes = shapes[0] if self._is_pair(shapes) else shapes
        lane.state = self._commit_state(jax.tree.map(
            lambda sd: np.zeros(sd.shape, sd.dtype), state_shapes))
        lane.state = jax.device_put(lane.state) \
            if getattr(self.model, "_mesh", None) is None else lane.state

    @staticmethod
    def _is_pair(res) -> bool:
        """(state, logits) vs bare state: cache models return a 2-tuple
        whose second element is a rank-2 logits array."""
        return (isinstance(res, tuple) and len(res) == 2
                and hasattr(res[1], "shape")
                and getattr(res[1], "ndim", 0) == 2)

    # -- admission ------------------------------------------------------------
    def submit(self, req: GenRequest) -> bool:
        """Queue one request for the next step boundary.  False = waiting
        room full (caller should leave the record staged / backpressure)."""
        with self._waiting_lock:
            if len(self._waiting) >= self.MAX_WAITING:
                return False
            self._waiting.append(req)
            return True

    @property
    def waiting(self) -> int:
        with self._waiting_lock:
            return len(self._waiting)

    @property
    def active(self) -> int:
        return sum(lane.active for lane in self._lanes)

    @property
    def slots_total(self) -> int:
        return sum(lane.max_active for lane in self._lanes)

    def _req_budget(self, req: GenRequest) -> int:
        """Per-request token budget: the deployment cap, lowerable (never
        raisable) by the record's own max_tokens.  The ONE clamp both
        lane selection and the slot budget use — they must agree, or a
        request could land in a lane too small for its budget."""
        budget = self.gen.max_tokens
        if req.max_tokens is not None:
            budget = max(1, min(int(req.max_tokens), budget))
        return budget

    def _budget_for(self, req: GenRequest, lane: _Lane) -> int:
        budget = self._req_budget(req)
        if self._cache_model:
            budget = min(budget, lane.bucket - len(req.prompt))
        return max(1, budget)

    def _pick_lane(self, req: GenRequest) -> Optional[_Lane]:
        """Smallest lane whose capacity holds prompt + budget AND the
        prompt's padded prefill bucket (prefill allocates the cache at
        the lane capacity, so ``cache_len >= prefill bucket`` must hold);
        bare-state models (no length axis) use the first lane."""
        if not self._cache_model:
            return self._lanes[0]
        want = len(req.prompt) + self._req_budget(req)
        pb = self._prefill_bucket(len(req.prompt))
        if pb is not None:
            want = max(want, pb)
        for lane in self._lanes:
            if lane.bucket >= want:
                return lane
        return None

    def _validate(self, req: GenRequest) -> Optional[str]:
        p = np.asarray(req.prompt)
        if p.ndim != 1 or p.size == 0:
            return f"prompt must be a non-empty 1-D token sequence, got " \
                   f"shape {p.shape}"
        if p.size > self.gen.max_prompt_len:
            return f"prompt length {p.size} > max_prompt_len " \
                   f"{self.gen.max_prompt_len}"
        if not np.all(np.isfinite(p)):
            return "prompt contains non-finite token ids"
        ids = p.astype(np.int64)
        if self._vocab and (ids.min() < 0 or ids.max() >= self._vocab):
            return f"token id out of range [0, {self._vocab})"
        return None

    def _prefill_bucket(self, n: int) -> Optional[int]:
        for b in self.gen.prefill_buckets:
            if b >= n:
                return b
        return None

    def _batch_bucket(self, n: int) -> int:
        """Admission-batch bucket: smallest pow-2 >= n, capped at the
        slot-count bucket (the grab loop never claims more than a lane's
        slots anyway)."""
        return min(_pow2_ceil(n), _pow2_ceil(self.gen.max_active_slots))

    def _admit_batch(self, lane: _Lane, pb: int, members, events) -> int:
        """Prefill + insert a same-(lane, prompt-bucket) admission group
        in ONE device call.  ``members``: (req, slot) pairs, slots already
        claimed.  B=1 prefill costs ~the same wall as B=8 (call overhead
        dominates at serving widths), so batching admissions is what keeps
        a churning request mix from spending its steps on prefill calls.
        Padding rows replicate row 0's prompt (any valid prompt works —
        their states are computed and discarded, never inserted).

        A failing batch falls back to singleton admission so a poisoned
        request that slipped past validation quarantines ALONE."""
        import jax
        n = len(members)
        bb = self._batch_bucket(n)
        padded = np.zeros((bb, pb), np.int32)
        lengths = np.ones((bb,), np.int32)
        for j, (req, _) in enumerate(members):
            prompt = np.asarray(req.prompt).astype(np.int32).reshape(-1)
            padded[j, :prompt.size] = prompt
            lengths[j] = prompt.size
        for j in range(n, bb):
            padded[j] = padded[0]
            lengths[j] = lengths[0]
        prefill, _, insert = self._lane_fns(lane)
        try:
            self._ensure_lane_state(lane)
            exe = self._compiled(("prefill", bb, pb, lane.bucket), prefill,
                                 self._params(), padded, lengths)
            res = exe(self._params(), padded, lengths)
            self._count_exec(("prefill", bb, pb, lane.bucket))
            if self._is_pair(res):
                sub, logits0 = res
                toks0 = np.asarray(jax.numpy.argmax(logits0, axis=-1))
            else:
                sub, toks0 = res, None
            ins = self._compiled(("insert", bb, lane.bucket), insert,
                                 lane.state, sub, np.int32(0), np.int32(0))
        except Exception as e:  # noqa: BLE001 — batch-level failure
            if n == 1:
                req, slot = members[0]
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error=f"{type(e).__name__}: {e}", t_read=req.t_read))
                lane.free.append(slot)
                return 0
            # isolate the poison: singleton admissions, per-slot blast
            # radius — neighbours' state buffers were never touched
            return sum(self._admit_batch(lane, pb, [mem], events)
                       for mem in members)
        admitted = 0
        for j, (req, slot) in enumerate(members):
            try:
                lane.state = ins(lane.state, sub, np.int32(j),
                                 np.int32(slot))
                self._count_exec(("insert", bb, lane.bucket))
            except Exception as e:  # noqa: BLE001 — per-row insert failure
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error=f"{type(e).__name__}: {e}", t_read=req.t_read))
                lane.free.append(slot)
                continue
            info = _Slot(req, budget=self._budget_for(req, lane))
            lane.slots[slot] = info
            self.admitted += 1
            admitted += 1
            if toks0 is not None:
                # cache models emit their first token AT prefill: TTFT
                # stops here, and the token feeds the first decode step
                info.t_first = time.monotonic()
                events.append(GenEvent(
                    "first_token", req.rid, trace_id=req.trace_id,
                    ttft_s=info.t_first - req.t_submit,
                    t_read=req.t_read))
                lane.tokens[slot] = int(toks0[j])
                self._account_token(lane, slot, info, int(toks0[j]),
                                    events)
            else:
                lane.tokens[slot] = self.gen.start_id
        return admitted

    def _admit(self, events: List[GenEvent]) -> int:
        """Claim free slots for waiting requests and admit them in
        batched prefill groups.  Stops at the first head-of-line request
        whose lane is full (FIFO; retried next boundary)."""
        grabbed: List[tuple] = []        # (req, lane, slot)
        while True:
            with self._waiting_lock:
                req = self._waiting.popleft() if self._waiting else None
            if req is None:
                break
            if self._expired(req.deadline_ns):
                self.shed += 1
                events.append(GenEvent(
                    "shed", req.rid, trace_id=req.trace_id,
                    t_read=req.t_read))
                continue
            err = self._validate(req)
            if err is not None:
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error=f"ValueError: {err}", t_read=req.t_read))
                continue
            lane = self._pick_lane(req)
            if lane is None:
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error="ValueError: no decode lane holds prompt + "
                          f"max_tokens (buckets {self.gen.bucket_lens})",
                    t_read=req.t_read))
                continue
            if not lane.free:
                # every slot of the right lane busy: the request stays at
                # the head for the next boundary (FIFO per lane is close
                # enough across lanes at this queue depth)
                with self._waiting_lock:
                    self._waiting.appendleft(req)
                break
            grabbed.append((req, lane, lane.free.popleft()))
        if not grabbed:
            return 0
        groups: Dict[tuple, list] = {}
        for req, lane, slot in grabbed:
            prompt_len = int(np.asarray(req.prompt).reshape(-1).size)
            pb = self._prefill_bucket(prompt_len)
            if pb is None:
                # defensive: __post_init__ extends the ladder to cover
                # max_prompt_len, so this is unreachable from config —
                # but an uncovered prompt must quarantine, not crash the
                # worker with its slot claimed
                self.quarantined += 1
                events.append(GenEvent(
                    "quarantine", req.rid, trace_id=req.trace_id,
                    error=f"ValueError: no prefill bucket holds prompt "
                          f"length {prompt_len} (buckets "
                          f"{self.gen.prefill_buckets})",
                    t_read=req.t_read))
                lane.free.append(slot)
                continue
            groups.setdefault((lane.bucket, pb), (lane, pb, []))[2] \
                .append((req, slot))
        return sum(self._admit_batch(lane, pb, members, events)
                   for lane, pb, members in groups.values())

    # -- step boundary --------------------------------------------------------
    @staticmethod
    def _expired(deadline_ns) -> bool:
        if deadline_ns is None:
            return False
        try:
            return time.time_ns() > int(deadline_ns)
        except (TypeError, ValueError, OverflowError):
            return False      # gateway/engine validated upstream

    def _free(self, lane: _Lane, slot: int) -> None:
        lane.slots[slot] = None
        lane.free.append(slot)

    def _finish(self, lane: _Lane, slot: int, info: _Slot, reason: str,
                events: List[GenEvent]) -> None:
        self.finished += 1
        now = time.monotonic()
        events.append(GenEvent(
            "finish", info.req.rid, trace_id=info.req.trace_id,
            tokens=list(info.generated), finish_reason=reason,
            ttft_s=(info.t_first - info.req.t_submit
                    if info.t_first is not None else None),
            t_read=info.req.t_read, wall_s=now - info.req.t_submit))
        self._free(lane, slot)

    def _account_token(self, lane: _Lane, slot: int, info: _Slot,
                       tok: int, events: List[GenEvent]) -> None:
        """Fold one emitted token into the slot: EOS / budget finish the
        request immediately (slot freed THIS boundary), stream_interval
        flushes partials."""
        eos = self.gen.eos_id
        if eos is not None and tok == eos:
            self._finish(lane, slot, info, "eos", events)
            return
        info.generated.append(int(tok))
        self.generated_tokens += 1
        if len(info.generated) >= info.budget:
            self._finish(lane, slot, info, "length", events)
            return
        si = self.gen.stream_interval
        if si and len(info.generated) - info.last_stream >= si:
            info.last_stream = len(info.generated)
            events.append(GenEvent(
                "partial", info.req.rid, trace_id=info.req.trace_id,
                tokens=list(info.generated), t_read=info.req.t_read))

    def _shed_active(self, events: List[GenEvent]) -> None:
        for lane in self._lanes:
            for slot, info in enumerate(lane.slots):
                if info is None or not self._expired(info.req.deadline_ns):
                    continue
                self.shed += 1
                events.append(GenEvent(
                    "shed", info.req.rid, trace_id=info.req.trace_id,
                    tokens=list(info.generated), t_read=info.req.t_read))
                self._free(lane, slot)

    def step(self) -> List[GenEvent]:
        """One decode-step boundary: shed expired, admit into free slots,
        run one token step per non-empty lane, fold the emitted tokens.
        Returns the events the engine must act on; an idle scheduler
        returns [] without touching the device."""
        events: List[GenEvent] = []
        self.last_boundary = []
        self._shed_active(events)
        self.last_admitted = self._admit(events)
        for lane in self._lanes:
            if lane.active == 0:
                continue
            _, step, _ = self._lane_fns(lane)
            tokens = lane.tokens
            exe = self._compiled(("decode_step", lane.bucket), step,
                                 self._params(), lane.state, tokens)
            block, lane.state = exe(self._params(), lane.state, tokens)
            self._count_exec(("decode_step", lane.bucket))
            block = np.asarray(block)          # (decode_quantum, A)
            self.decode_steps += int(block.shape[0])   # token-level steps
            now = time.monotonic()
            for slot, info in enumerate(lane.slots):
                if info is None:
                    continue
                if info.t_first is None:
                    info.t_first = now
                    events.append(GenEvent(
                        "first_token", info.req.rid,
                        trace_id=info.req.trace_id,
                        ttft_s=info.t_first - info.req.t_submit,
                        t_read=info.req.t_read))
                n0 = len(info.generated)
                for k in range(block.shape[0]):
                    self._account_token(lane, slot, info,
                                        int(block[k, slot]), events)
                    if lane.slots[slot] is not info:
                        break      # finished mid-quantum: discard the rest
                # boundary accounting for the per-boundary decode spans
                # (valid whether the request finished this boundary or
                # not — `info` outlives the slot free)
                self.last_boundary.append(
                    (info.req.rid, info.req.trace_id,
                     len(info.generated) - n0))
            # copy: the device block is read-only, and the next boundary's
            # admission writes freshly-claimed slots into this row
            lane.tokens = np.array(block[-1])
        return events

    @property
    def idle(self) -> bool:
        return self.active == 0 and self.waiting == 0

    # -- warm-up (PR 11 integration) ------------------------------------------
    def warmup_manifest(self):
        """The (prefill-bucket x decode-step) program set for this
        deployment — delegated to ``aot.generation_manifest`` so the
        serving warm-up and ``manager warmup`` derive the same set."""
        from analytics_zoo_tpu.inference import aot
        return aot.generation_manifest(
            self.gen.prefill_buckets,
            [lane.bucket for lane in self._lanes],
            prefill_batches=_pow2_ladder(1, self.gen.max_active_slots),
            cache_model=self._cache_model)

    def warm(self, manifest=None, progress: Optional[Callable] = None,
             stop: Optional[Callable[[], bool]] = None) -> Dict:
        """Compile every scheduler program ahead of traffic.  Same stats
        document shape as ``aot.warm_up`` so the engine's warm-up thread
        and ``/readyz`` progress machinery drive either."""
        from analytics_zoo_tpu.inference import aot
        if manifest is None:
            manifest = self.warmup_manifest()
        before = aot.COMPILE_STATS.snapshot()
        t0 = time.monotonic()
        compiled = skipped = failed = 0
        stopped = False
        lanes = {lane.bucket: lane for lane in self._lanes}
        for i, entry in enumerate(manifest):
            if stop is not None and stop():
                stopped = True
                break
            try:
                fresh = self._warm_entry(entry, lanes)
                compiled += 1 if fresh else 0
                skipped += 0 if fresh else 1
            except Exception as e:  # noqa: BLE001 — one bad entry must not
                failed += 1         # strand the set; the live path compiles
                logger.warning("generate: warm-up entry %s failed (%s: %s)",
                               entry, type(e).__name__, e)
            if progress is not None:
                progress(i + 1, len(manifest), entry)
        after = aot.COMPILE_STATS.snapshot()
        return {"programs": len(manifest), "compiled": compiled,
                "skipped": skipped, "failed": failed, "stopped": stopped,
                "seconds": round(time.monotonic() - t0, 3),
                "compile_stats": {k: round(after[k] - before[k], 3)
                                  for k in after}}

    def _warm_entry(self, entry, lanes: Dict[int, "_Lane"]) -> bool:
        import jax
        lane = lanes.get(entry.lane_bucket)
        if lane is None:
            raise ValueError(f"no lane with bucket {entry.lane_bucket}")
        self._ensure_lane_state(lane)
        prefill, step, insert = self._lane_fns(lane)
        if entry.kind == "prefill":
            pb = int(entry.prefill_bucket)
            bb = int(entry.prefill_batch or 1)
            key = ("prefill", bb, pb, lane.bucket)
            fresh = key not in self._programs
            dummy = np.zeros((bb, pb), np.int32)
            self._compiled(key, prefill, self._params(), dummy,
                           np.ones((bb,), np.int32))
            return fresh
        if entry.kind == "decode_step":
            key = ("decode_step", lane.bucket)
            fresh = key not in self._programs
            self._compiled(key, step, self._params(), lane.state,
                           lane.tokens)
            return fresh
        if entry.kind == "insert":
            # insert needs a prefilled sub-state: derive it abstractly so
            # warming never runs a real prefill
            bb = int(entry.prefill_batch or 1)
            key = ("insert", bb, lane.bucket)
            fresh = key not in self._programs
            pb = self.gen.prefill_buckets[0]
            shapes = jax.eval_shape(
                prefill, self._params(),
                jax.ShapeDtypeStruct((bb, pb), np.int32),
                jax.ShapeDtypeStruct((bb,), np.int32))
            sub_shapes = shapes[0] if self._is_pair(shapes) else shapes
            sub = jax.tree.map(lambda sd: np.zeros(sd.shape, sd.dtype),
                               sub_shapes)
            self._compiled(key, insert, lane.state, sub, np.int32(0),
                           np.int32(0))
            return fresh
        raise ValueError(f"unknown warm-up entry kind {entry.kind!r}")

    # -- observability --------------------------------------------------------
    def state_bytes(self) -> int:
        """Bytes pinned by the committed lane state buffers — the
        ``kv_state`` component of the resource ledger (PR 15).  Derived
        from the leaf shapes/dtypes of each lane's fixed
        ``(max_active, bucket)`` pytree, so the number is exact for the
        bucket geometry in force regardless of where jax placed it."""
        import jax
        total = 0
        for lane in self._lanes:
            if lane.state is None:
                continue
            for leaf in jax.tree_util.tree_leaves(lane.state):
                try:
                    total += int(np.prod(leaf.shape)) \
                        * int(np.dtype(leaf.dtype).itemsize)
                except (TypeError, ValueError):
                    continue
            total += int(lane.tokens.nbytes)
        return total

    def program_stats(self) -> Dict:
        """Compiled scheduler programs + per-program execution counts
        (PR 15): the generation half of the per-program exec accounting,
        keyed like the ``aot.generation_manifest`` entries
        (``prefill:b<batch>xp<bucket>@<lane>`` etc.)."""
        progs = {k: v for k, v in self._programs.items()
                 if k and k[0] != "fns"}
        return {"count": len(progs),
                "programs": dict(self._exec_counts)}

    def stats(self) -> Dict:
        return {"slots_total": self.slots_total,
                "active_slots": self.active,
                "waiting": self.waiting,
                "decode_steps": self.decode_steps,
                "generated_tokens": self.generated_tokens,
                "admitted": self.admitted,
                "finished": self.finished,
                "quarantined": self.quarantined,
                "shed": self.shed,
                "compiles": self.compiles,
                "lanes": [{"bucket": lane.bucket,
                           "max_active": lane.max_active,
                           "active": lane.active}
                          for lane in self._lanes]}
