"""Cluster Serving lifecycle manager + config loader.

Reference parity: the `scripts/cluster-serving/` lifecycle scripts
(cluster-serving-init/start/stop/restart/shutdown), `ClusterServingHelper`
(serving/utils/ClusterServingHelper.scala:1-448 — config.yaml parsing with
model-type autodetect) and `ClusterServingManager.listenTermination`
(ClusterServingManager.scala:1-55).

config.yaml surface (scripts/cluster-serving/config.yaml template):

    model:
      path: /path/to/model            # autodetected: .npz zoo weights with
                                      # sibling topology.py, SavedModel dir,
                                      # .onnx, TorchScript .pt
      type: onnx                      # optional override
    data:
      src: redis                      # redis | file:<dir> (cross-process)
      redis_host: localhost
      redis_port: 6379
      stream: image_stream
    params:
      batch_size: 4
      top_n: 5
      filter_threshold: null
      pipeline_depth: 2

CLI (used by scripts/cluster-serving/*.sh):
    python -m analytics_zoo_tpu.serving.manager start  [-c config.yaml]
    python -m analytics_zoo_tpu.serving.manager stop|status|restart
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time
from typing import Optional

from analytics_zoo_tpu.inference.inference_model import InferenceModel
from analytics_zoo_tpu.serving.engine import ClusterServing, ServingParams

PIDFILE = "cluster-serving.pid"


def load_config(path: str) -> dict:
    try:
        import yaml
        with open(path) as f:
            return yaml.safe_load(f) or {}
    except ImportError:
        # minimal fallback parser for the flat 2-level template above
        cfg: dict = {}
        section = None
        with open(path) as f:
            for line in f:
                line = line.split("#", 1)[0].rstrip()
                if not line.strip():
                    continue
                if not line.startswith(" "):
                    section = line.strip().rstrip(":")
                    cfg[section] = {}
                else:
                    k, _, v = line.strip().partition(":")
                    v = v.strip()
                    if v in ("null", ""):
                        val = None
                    else:
                        try:
                            val = int(v)
                        except ValueError:
                            try:
                                val = float(v)
                            except ValueError:
                                val = v
                    cfg[section][k.strip()] = val
        return cfg


def detect_model_type(path: str) -> str:
    """ClusterServingHelper's model-type autodetect analog."""
    if os.path.isdir(path):
        if os.path.exists(os.path.join(path, "saved_model.pb")):
            return "tensorflow"
        raise ValueError(f"cannot autodetect model type for dir {path}")
    ext = os.path.splitext(path)[1].lower()
    if ext == ".onnx":
        return "onnx"
    if ext in (".pt", ".pth", ".ts"):
        return "pytorch"
    if ext == ".npz":
        return "zoo"
    raise ValueError(f"cannot autodetect model type for {path}")


def load_model(cfg: dict) -> InferenceModel:
    mcfg = cfg.get("model", {})
    path = mcfg.get("path")
    if not path:
        raise ValueError("config.yaml: model.path is required")
    mtype = mcfg.get("type") or detect_model_type(path)
    im = InferenceModel()
    if mtype == "tensorflow":
        return im.do_load_tensorflow(path)
    if mtype == "onnx":
        return im.do_load_onnx(path)
    if mtype == "pytorch":
        return im.do_load_pytorch(path)
    if mtype == "zoo":
        topo = mcfg.get("topology")
        if not topo:
            raise ValueError("zoo .npz weights need model.topology "
                             "(python file defining build_model())")
        scope: dict = {}
        with open(topo) as f:
            exec(compile(f.read(), topo, "exec"), scope)
        return im.do_load(scope["build_model"], path)
    raise ValueError(f"unknown model type {mtype!r}")


def build_queue(cfg: dict):
    dcfg = cfg.get("data", {})
    src = str(dcfg.get("src", "redis"))
    if src.startswith("file:"):
        from analytics_zoo_tpu.serving.queues import FileQueue
        return FileQueue(src.split(":", 1)[1])
    if src == "inproc":
        from analytics_zoo_tpu.serving.queues import InProcQueue
        return InProcQueue()
    from analytics_zoo_tpu.serving.queues import RedisQueue
    return RedisQueue(host=dcfg.get("redis_host", "localhost"),
                      port=int(dcfg.get("redis_port", 6379)),
                      stream=dcfg.get("stream", "image_stream"))


def serving_params(cfg: dict) -> ServingParams:
    p = cfg.get("params", {})
    return ServingParams(
        batch_size=int(p.get("batch_size", 4)),
        top_n=int(p.get("top_n", 5)),
        filter_threshold=p.get("filter_threshold"),
        pipeline_depth=int(p.get("pipeline_depth", 2)),
        stream_max_len=int(p.get("stream_max_len", 100000)))


def serve_from_config(config_path: str,
                      tensorboard_dir: Optional[str] = None) -> ClusterServing:
    cfg = load_config(config_path)
    serving = ClusterServing(load_model(cfg), build_queue(cfg),
                             params=serving_params(cfg),
                             tensorboard_dir=tensorboard_dir)
    return serving


def _run_foreground(config_path: str, pidfile: str):
    with open(pidfile, "w") as f:
        f.write(str(os.getpid()))
    serving = serve_from_config(config_path)

    def _terminate(signum, frame):
        # ClusterServingManager.listenTermination analog: drain + exit
        serving.shutdown()
        try:
            os.unlink(pidfile)
        except OSError:
            pass
        sys.exit(0)

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)
    serving.start()
    while True:
        time.sleep(1)


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(prog="cluster-serving")
    ap.add_argument("action",
                    choices=["start", "stop", "status", "restart"])
    ap.add_argument("-c", "--config", default="config.yaml")
    ap.add_argument("--pidfile", default=PIDFILE)
    ap.add_argument("--foreground", action="store_true")
    args = ap.parse_args(argv)

    def read_pid():
        try:
            with open(args.pidfile) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def alive(pid):
        try:
            os.kill(pid, 0)
            return True
        except OSError:
            return False

    if args.action == "status":
        pid = read_pid()
        up = pid is not None and alive(pid)
        print(json.dumps({"running": up, "pid": pid if up else None}))
        return 0
    if args.action in ("stop", "restart"):
        pid = read_pid()
        if pid is not None and alive(pid):
            os.kill(pid, signal.SIGTERM)
            for _ in range(50):
                if not alive(pid):
                    break
                time.sleep(0.1)
        if args.action == "stop":
            print(json.dumps({"stopped": True}))
            return 0
        if pid is not None and alive(pid):
            print(json.dumps({"error": f"pid {pid} did not terminate"}),
                  file=sys.stderr)
            return 1
    # start / restart
    pid = read_pid()
    if pid is not None and alive(pid):
        print(json.dumps({"error": f"already running (pid {pid})"}),
              file=sys.stderr)
        return 1
    if args.foreground:
        _run_foreground(args.config, args.pidfile)
        return 0
    pid = os.fork()
    if pid == 0:                           # child: detach and serve
        os.setsid()
        _run_foreground(args.config, args.pidfile)
        return 0
    print(json.dumps({"started": True, "pid": pid}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
