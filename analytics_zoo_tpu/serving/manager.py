"""Cluster Serving lifecycle manager + config loader.

Reference parity: the `scripts/cluster-serving/` lifecycle scripts
(cluster-serving-init/start/stop/restart/shutdown), `ClusterServingHelper`
(serving/utils/ClusterServingHelper.scala:1-448 — config.yaml parsing with
model-type autodetect) and `ClusterServingManager.listenTermination`
(ClusterServingManager.scala:1-55).

config.yaml surface (scripts/cluster-serving/config.yaml template):

    model:
      path: /path/to/model            # autodetected: .npz zoo weights with
                                      # sibling topology.py, SavedModel dir,
                                      # .onnx, TorchScript .pt
      type: onnx                      # optional override
    data:
      src: redis                      # redis | file:<dir> (cross-process)
      redis_host: localhost
      redis_port: 6379
      stream: image_stream
      max_depth: null                 # admission cap: xadd raises QueueFull
    params:
      batch_size: 4
      top_n: 5
      filter_threshold: null
      pipeline_depth: 2
      max_worker_restarts: 5            # resilience (PR 1)
      worker_backoff_s: 0.05
      breaker_threshold: 5
      breaker_cooldown_s: 0.5
      http_port: null                   # availability (PR 2): /healthz,
      http_host: 127.0.0.1              # /readyz, /metrics probe endpoint
      gateway: true                     # ingestion gateway (PR 7): serve
                                        # POST /v1/enqueue + GET
                                        # /v1/result/<uri> on the probe
                                        # port (binary frame or JSON,
                                        # 429/503 at the edge).  Under
                                        # --replicas the gateway rides each
                                        # replica (port http_port + i), so
                                        # ingest fails over with the
                                        # supervisor.  false = probe-only
                                        # port
      drain_s: null                     # graceful-drain budget on SIGTERM
      ready_queue_depth: null           # /readyz depth threshold
      max_batch: null                   # throughput (PR 3): adaptive batcher
                                        # ceiling (null = batch_size)
      max_wait_ms: 5                    # coalescing budget for partial batches
      preprocess_workers: 1             # decode fan-out (>1 = thread pool)
      inflight_batches: 2               # async device pipeline depth
      trim_interval_s: 5                # amortized stream-trim period
      lease_s: 30                       # replicas (PR 5): claimed-record
                                        # lease before another replica may
                                        # reclaim (> worst-case record time)
      reclaim_interval_s: null          # reclaim sweep period (null=lease/2)
      max_deliveries: 5                 # poison-pill parking (PR 10): a
                                        # record delivered more than this
                                        # many times is parked to the
                                        # dead-letter queue
                                        # (max-deliveries-exceeded) instead
                                        # of looping through reclaim; <= 0
                                        # disables
      warmup: false                     # zero cold start (PR 11): true =
                                        # AOT-compile every (bucket,
                                        # scales-variant) program at boot
                                        # (input spec inferred from the
                                        # topology), or a spec dict
                                        # {shape: [d0, ...], dtype: <f4,
                                        # scales: auto|both|off,
                                        # max_batch: N}.  /readyz reports
                                        # `warming (k/n programs)` until
                                        # done; `start --replicas` runs
                                        # one throwaway pre-warm pass
                                        # first so replicas boot from the
                                        # compile cache
      compile_cache_dir: null           # persistent XLA compilation cache
                                        # shared by every replica spawn:
                                        # null = <pidfile>.xla_cache
                                        # (created by the manager), a
                                        # path pins it, "off" disables
      trace_sample: 1.0                 # distributed tracing (PR 13):
                                        # head-sampling rate in [0, 1] —
                                        # the keep/drop verdict is a pure
                                        # function of the trace_id, so
                                        # LB/gateway/replicas agree
                                        # without coordination.  Error
                                        # spans always record.  0
                                        # disables span volume entirely
                                        # (metrics stay on).
      quantize: null                    # fused-dequant quantized predict
                                        # (PR 14): null/off = float serve,
                                        # int8 | int4, or a dict
                                        # {bits: 8|4, group_size: 64,
                                        # percentile: 99.9, calib:
                                        # /path/batch.npy}.  `manager
                                        # warmup` quantizes BEFORE
                                        # exporting the weight store, so
                                        # replica forks serve quantized
                                        # from the mmap'd store with zero
                                        # steady-state compiles.  int8
                                        # needs `calib` (activation
                                        # scales); int4 is weight-only
      flight_recorder: true             # incident flight recorder
                                        # (PR 15): typed events (state
                                        # transitions, retunes, reclaims,
                                        # quarantines, warm-up phases,
                                        # scheduler boundaries) into a
                                        # bounded ring, drained to
                                        # <pidfile>.events.jsonl; false =
                                        # no-op hop
      recorder_ring: 4096               # ring size (events kept between
                                        # the manager's 1 s drains)
      profiling: true                   # POST /debug/profile?seconds=N
                                        # on the replica PROBE port (the
                                        # LB never proxies /debug); false
                                        # removes the route
      serving_slo: null                 # SLO attribution (PR 13):
                                        # {latency_ms: 500, window_s: 60,
                                        # target: 0.99} judges every
                                        # completed record, attributes
                                        # each violation to its dominant
                                        # stage
                                        # (serving_slo_violations_total)
                                        # and drives the windowed
                                        # serving_slo_burn_rate gauge
    autoscaler:                         # closed-loop autoscaling (PR 10),
      slo_p99_ms: 500                   # used with `start --replicas N
      min_replicas: 1                   # --autoscale`; every
      max_replicas: 8                   # AutoscalerParams field is accepted
      dwell_up_s: 2                     # (serving/autoscaler.py)
      dwell_down_s: 10
      scale_down_cooldown_s: 30
      max_step: 2
      sharding: off                     # multi-chip serving (PR 6): off |
                                        # auto (batch-shard small models,
                                        # tensor-shard large) | batch | tensor
      mesh_shape: null                  # null = all devices, N = first N
                                        # chips, [dd, mm] = hybrid data x
                                        # model mesh layout

CLI (used by scripts/cluster-serving/*.sh):
    python -m analytics_zoo_tpu.serving.manager start  [-c config.yaml]
        [--replicas N]                 # N serving replica processes over the
        # SHARED queue (file/redis), supervised: a crashed replica is
        # respawned, its orphaned in-flight records reclaimed by survivors.
        # Replica i gets pidfile <pidfile>.r<i> (+ its own health snapshot)
        # and params.http_port + i when a probe port is configured.
        [--autoscale]                  # PR 10: run the closed-loop
        # autoscaler in the supervisor — fleet signals from the per-replica
        # health docs, topology through the scale file (same path as
        # `manager scale N`), fast knob nudges through <pidfile>.knobs.json
        # (each replica polls + ClusterServing.retune()s), controller
        # metrics snapshotted to <pidfile>.autoscaler.json.  Tuned by the
        # config's `autoscaler:` section.
        [--lb-port P]                  # PR 10: single-port load-balancing
        # front door (serving/lb.py) in the supervisor: proxies
        # /v1/enqueue + /v1/result across the live replica gateways with
        # least-inflight pick + /readyz health-out, tracking membership as
        # the fleet resizes — clients never see a scale event.
    python -m analytics_zoo_tpu.serving.manager scale N
        # resize a running --replicas supervisor to N replicas (scale-up
        # spawns, scale-down SIGTERMs the highest-numbered replicas, which
        # drain gracefully per params.drain_s)
    python -m analytics_zoo_tpu.serving.manager stop|status|restart
    python -m analytics_zoo_tpu.serving.manager health   # worker/breaker/
        # dead-letter state from the daemon's <pidfile>.health.json snapshot
    python -m analytics_zoo_tpu.serving.manager replay [--filter SUBSTR]
        # re-enqueue quarantined records after a fix (dead-letter replay)
    python -m analytics_zoo_tpu.serving.manager metrics [--prom]
        # live metrics snapshot: GET the daemon's /metrics endpoint when
        # params.http_port is configured (--prom asks for the Prometheus
        # text exposition), else derive the same JSON document from the
        # health.json snapshot
    python -m analytics_zoo_tpu.serving.manager warmup [-c config.yaml]
        # zero cold start (PR 11): one throwaway pass that persists the
        # deployment's warm state next to the pidfile — the mmap weight
        # store (<pidfile>.weights, np.load(mmap_mode="r") at every
        # replica boot, page cache shared host-wide) and the persistent
        # XLA compilation cache (<pidfile>.xla_cache) covering the whole
        # (bucket x scales-variant) program set.  `start --replicas` runs
        # this implicitly when params.warmup is set (skip: --no-prewarm);
        # every replica spawned after it — including autoscaler
        # scale-ups — reaches /readyz in seconds with ZERO XLA compiles.
    python -m analytics_zoo_tpu.serving.manager metrics --all-replicas
        [--prom]
        # PR 10: ONE fleet-wide snapshot summed across the per-replica
        # registries (HTTP scrape per replica, health.json fallback) — the
        # same aggregation the autoscaler consumes (serving/fleet.py).
        # --prom merges the per-replica text expositions (counters and
        # histogram series sum; shared-queue gauges take the max) and
        # appends the controller's own exposition when the autoscaler is
        # running, plus (PR 13) the LB front door's own series from
        # <pidfile>.lb.json.
    python -m analytics_zoo_tpu.serving.manager incident
        [--list | --show [bundle] [--last N]]
        # PR 15 incident forensics.  Bare `incident` snapshots a
        # self-contained bundle NOW (works live or post-mortem) into
        # <pidfile>.incidents/<ts>/: every process's flight-recorder
        # event spool + trace spools + health snapshots + autoscaler
        # decision log + LB telemetry + knobs/scale files.  The
        # supervisor auto-captures on replica crash and on SLO-burn
        # threshold (config `incident:` section).  --list enumerates
        # bundles; --show renders one merged cross-process timeline
        # (recorder events + trace spans, clock-normalized) —
        # tools/incident_view.py renders the same document as text.
    python -m analytics_zoo_tpu.serving.manager profile [replica]
        [--seconds S]
        # PR 15 on-demand device profiling: POST /debug/profile on the
        # replica's probe port; a jax.profiler trace lands under
        # <pidfile>.profiles/<ts>/ (open with TensorBoard/Perfetto).
    python -m analytics_zoo_tpu.serving.manager trace <trace_id>
    python -m analytics_zoo_tpu.serving.manager trace --slowest N
    python -m analytics_zoo_tpu.serving.manager trace --chrome fleet.json
        # PR 13: fleet-wide trace reconstruction.  Every process spools
        # its drained spans next to its health snapshot
        # (<pidfile>.rN.spans.jsonl per replica, <pidfile>.lb.spans.jsonl
        # for the front door); `trace <id>` merges them — monotonic clocks
        # normalized per process — and prints one request's cross-process
        # timeline (lb -> gateway -> queue-wait -> preprocess -> predict
        # -> write -> result-poll, parented spans, untracked gaps,
        # errors).  --slowest ranks traces by fleet e2e; --chrome exports
        # the merged timeline with one Perfetto track per process.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time
from typing import Optional

from analytics_zoo_tpu.inference.inference_model import InferenceModel
from analytics_zoo_tpu.serving.engine import ClusterServing, ServingParams

PIDFILE = "cluster-serving.pid"


def load_config(path: str) -> dict:
    try:
        import yaml
        with open(path) as f:
            return yaml.safe_load(f) or {}
    except ImportError:
        # minimal fallback parser for the flat 2-level template above
        cfg: dict = {}
        section = None
        with open(path) as f:
            for line in f:
                line = line.split("#", 1)[0].rstrip()
                if not line.strip():
                    continue
                if not line.startswith(" "):
                    section = line.strip().rstrip(":")
                    cfg[section] = {}
                else:
                    k, _, v = line.strip().partition(":")
                    v = v.strip()
                    if v in ("null", ""):
                        val = None
                    else:
                        try:
                            val = int(v)
                        except ValueError:
                            try:
                                val = float(v)
                            except ValueError:
                                val = v
                    cfg[section][k.strip()] = val
        return cfg


def detect_model_type(path: str) -> str:
    """ClusterServingHelper's model-type autodetect analog."""
    if os.path.isdir(path):
        if os.path.exists(os.path.join(path, "saved_model.pb")):
            return "tensorflow"
        raise ValueError(f"cannot autodetect model type for dir {path}")
    ext = os.path.splitext(path)[1].lower()
    if ext == ".onnx":
        return "onnx"
    if ext in (".pt", ".pth", ".ts"):
        return "pytorch"
    if ext == ".npz":
        return "zoo"
    raise ValueError(f"cannot autodetect model type for {path}")


def load_model(cfg: dict,
               weight_store: Optional[str] = None) -> InferenceModel:
    """Build the deployment's InferenceModel.  ``weight_store`` (PR 11):
    when the per-deployment mmap store exists (``manager warmup`` exports
    it next to the pidfile), zoo weights restore from it —
    ``np.load(mmap_mode="r")`` per leaf, so N replicas on one host share
    the page cache instead of each inflating its own `.npz` copy."""
    mcfg = cfg.get("model", {})
    path = mcfg.get("path")
    if not path:
        raise ValueError("config.yaml: model.path is required")
    mtype = mcfg.get("type") or detect_model_type(path)
    im = InferenceModel()
    if mtype == "tensorflow":
        return im.do_load_tensorflow(path)
    if mtype == "onnx":
        return im.do_load_onnx(path)
    if mtype == "pytorch":
        return im.do_load_pytorch(path)
    if mtype == "zoo":
        topo = mcfg.get("topology")
        if not topo:
            raise ValueError("zoo .npz weights need model.topology "
                             "(python file defining build_model())")
        scope: dict = {}
        with open(topo) as f:
            exec(compile(f.read(), topo, "exec"), scope)
        if weight_store:
            from analytics_zoo_tpu.inference import weightstore
            if weightstore.is_store(weight_store):
                return im.do_load(scope["build_model"], weight_store)
        return im.do_load(scope["build_model"], path)
    raise ValueError(f"unknown model type {mtype!r}")


def build_queue(cfg: dict):
    dcfg = cfg.get("data", {})
    src = str(dcfg.get("src", "redis"))
    max_depth = dcfg.get("max_depth")
    if max_depth is not None:
        max_depth = int(max_depth)
    if src.startswith("file:"):
        from analytics_zoo_tpu.serving.queues import FileQueue
        return FileQueue(src.split(":", 1)[1], max_depth=max_depth)
    if src == "inproc":
        from analytics_zoo_tpu.serving.queues import InProcQueue
        return InProcQueue(max_depth=max_depth)
    from analytics_zoo_tpu.serving.queues import RedisQueue
    return RedisQueue(host=dcfg.get("redis_host", "localhost"),
                      port=int(dcfg.get("redis_port", 6379)),
                      stream=dcfg.get("stream", "image_stream"),
                      max_depth=max_depth)


def serving_params(cfg: dict) -> ServingParams:
    # single shared parser (incl. the PR 1 resilience knobs)
    return ServingParams.from_dict(cfg.get("params", {}))


def serve_from_config(config_path: str,
                      tensorboard_dir: Optional[str] = None,
                      replica_id: Optional[str] = None,
                      http_port_offset: int = 0,
                      cache_dir: Optional[str] = None,
                      weight_store: Optional[str] = None,
                      model_version: Optional[str] = None) -> ClusterServing:
    cfg = load_config(config_path)
    params = serving_params(cfg)
    if replica_id is not None:
        # supervisor-assigned identity (PR 5) wins over the config default
        # so every replica of one deployment is distinguishable
        params.replica_id = replica_id
    if model_version is not None:
        # rollout version identity (PR 16): the supervisor's spawn spec
        # pins the registry version this replica serves; it rides the
        # health doc, /healthz and every result payload
        params.model_version = str(model_version)
    if params.http_port and http_port_offset:
        # replicas cannot share one probe port: replica i listens on
        # http_port + i (documented in the module docstring)
        params.http_port += http_port_offset
    if cache_dir and not params.compile_cache_dir:
        # the manager's per-deployment cache dir (PR 11); the engine
        # enables it at start(), before any program compiles
        params.compile_cache_dir = cache_dir
    serving = ClusterServing(load_model(cfg, weight_store=weight_store),
                             build_queue(cfg),
                             params=params,
                             tensorboard_dir=tensorboard_dir)
    return serving


def _health_path(pidfile: str) -> str:
    return pidfile + ".health.json"


def _replica_pidfile(pidfile: str, index: int) -> str:
    return f"{pidfile}.r{index}"


def _scale_path(pidfile: str) -> str:
    """Desired replica count, written by `manager scale N` and polled by
    the supervisor — a file, not a signal, so the target survives a
    supervisor restart and is inspectable."""
    return pidfile + ".replicas"


def _knobs_path(pidfile: str) -> str:
    """Fast-tier knob targets (PR 10): written by the supervisor's
    autoscaler, polled by every replica (same file-not-signal rationale as
    the scale file)."""
    return pidfile + ".knobs.json"


def _autoscaler_path(pidfile: str) -> str:
    return pidfile + ".autoscaler.json"


def _cache_dir(pidfile: str) -> str:
    """Per-deployment persistent XLA compilation cache (PR 11), created
    by the manager and shared read/write across every replica spawn of
    this deployment — the second replica of a topology never compiles."""
    return pidfile + ".xla_cache"


def _profiles_dir(pidfile: str) -> str:
    """On-demand jax.profiler traces (PR 15): `manager profile <replica>`
    lands one timestamped trace dir per run in here."""
    return pidfile + ".profiles"


def _weights_dir(pidfile: str) -> str:
    """Per-deployment mmap'd weight store (PR 11): `manager warmup`
    persists the params once, every replica boot maps the same pages."""
    return pidfile + ".weights"


def _registry_dir(pidfile: str) -> str:
    """Versioned model registry (PR 16): `manager publish <version>`
    snapshots immutable version dirs under here; `manager rollout` moves
    the fleet between them one replica at a time."""
    return pidfile + ".registry"


def _version_store(pidfile: str, version: str,
                   model_name: str = "default") -> str:
    """The weight store a replica assigned to ``version`` must load —
    verified FIRST: a truncated/corrupt version must fail the spawn
    loudly (the supervisor's crash accounting then rolls back), never
    serve garbage weights."""
    from analytics_zoo_tpu.serving import registry as _registry
    problems = _registry.verify(_registry_dir(pidfile), version,
                                model=model_name)
    if problems:
        raise _registry.RegistryError(
            f"version {version!r} failed integrity verification: "
            + "; ".join(problems[:3]))
    return _registry.store_path(_registry_dir(pidfile), version,
                                model=model_name)


def _model_name(cfg: dict) -> str:
    name = (cfg.get("model") or {}).get("name")
    return str(name) if name else "default"


def _jsonable(v):
    """Best-effort JSON projection for registry metadata (warm-up
    manifest entries carry dtypes/tuples json.dump chokes on)."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


def _resolve_cache_dir(params: ServingParams, pidfile: str):
    """`params.compile_cache_dir`: an explicit path wins, "off" disables,
    unset defaults to the per-deployment dir next to the pidfile."""
    if params.compile_cache_dir == "off":
        return None
    return params.compile_cache_dir or _cache_dir(pidfile)


def _write_health(serving, path: str) -> None:
    """Atomic health snapshot (ClusterServing.health()) next to the pidfile —
    the `status`/`health` CLI actions read it from outside the daemon."""
    tmp = path + ".tmp"
    try:
        snapshot = dict(serving.health(), ts=time.time())
        with open(tmp, "w") as f:
            json.dump(snapshot, f)
        os.replace(tmp, path)
    except OSError:
        pass


def _lb_path(pidfile: str) -> str:
    """LB telemetry snapshot (PR 13): the supervisor persists the front
    door's registry snapshot + Prometheus exposition here each pass, so
    ``manager metrics --all-replicas`` can include the LB's own series
    (lb_requests_total / lb_retries_total were otherwise invisible to the
    fleet doc)."""
    return pidfile + ".lb.json"


def _drain_spans(serving, pidfile: str) -> None:
    """Span spool hop (PR 13): drain this replica's tracer ring into the
    per-replica spool next to the health snapshot.  Best-effort — a full
    disk must not kill the serving loop."""
    try:
        from analytics_zoo_tpu.serving import tracecollect
        spans = serving.tracer.drain_spans()
        if spans:
            tracecollect.append_spans(tracecollect.spool_path(pidfile),
                                      spans, source=serving.replica_id)
    except Exception:  # noqa: BLE001 — tracing is never load-bearing
        pass


def _drain_events(pidfile: str, source=None) -> None:
    """Flight-recorder spool hop (PR 15): drain this PROCESS's event ring
    into ``<pidfile>.events.jsonl`` — same rotation/clock contract as the
    span spools, so `manager incident`/`trace` merge both onto one
    timeline.  Runs in replicas (engine/gateway/compile events) AND the
    supervisor (autoscaler/LB/lifecycle events)."""
    try:
        from analytics_zoo_tpu.common.observability import get_recorder
        from analytics_zoo_tpu.serving import tracecollect
        events = get_recorder().drain_events()
        if events:
            tracecollect.append_events(tracecollect.events_path(pidfile),
                                       events, source=source)
    except Exception:  # noqa: BLE001 — forensics is never load-bearing
        pass


def _drain_usage(serving, pidfile: str) -> None:
    """Usage journal hop (PR 19): drain this replica's per-interval
    usage deltas into ``<pidfile>.usage.jsonl`` — same rotation/clock
    contract as the span/event spools, rolled up by `manager usage`.
    Best-effort: metering must never be load-bearing."""
    try:
        from analytics_zoo_tpu.serving import tracecollect
        records = serving.drain_usage()
        if records:
            tracecollect.append_usage(tracecollect.usage_path(pidfile),
                                      records, source=serving.replica_id)
    except Exception:  # noqa: BLE001 — metering is never load-bearing
        pass


def _run_foreground(config_path: str, pidfile: str,
                    replica_id: Optional[str] = None,
                    http_port_offset: int = 0,
                    knobs_path: Optional[str] = None,
                    base_pidfile: Optional[str] = None,
                    model_version: Optional[str] = None):
    with open(pidfile, "w") as f:
        f.write(str(os.getpid()))
    # zero cold start (PR 11): every replica of one deployment shares the
    # BASE pidfile's compile cache + weight store (replica pidfiles are
    # `<base>.rN`); the cache dir must be live before the model loads so
    # no compile escapes it
    base = base_pidfile or pidfile
    cfg0 = load_config(config_path)
    params0 = serving_params(cfg0)
    cache_dir = _resolve_cache_dir(params0, base)
    if cache_dir:
        from analytics_zoo_tpu.inference import aot
        aot.enable_persistent_cache(cache_dir)
    # rollout (PR 16): a version-assigned replica loads the REGISTRY's
    # immutable snapshot for that version, integrity-verified first — a
    # corrupt version fails the spawn loudly instead of serving garbage
    weight_store = (_version_store(base, model_version, _model_name(cfg0))
                    if model_version else _weights_dir(base))
    serving = serve_from_config(config_path, replica_id=replica_id,
                                http_port_offset=http_port_offset,
                                cache_dir=cache_dir,
                                weight_store=weight_store,
                                model_version=model_version)
    # on-demand profiling (PR 15): traces land next to the deployment's
    # other artifacts, shared across the replicas of one base pidfile
    serving.profile_dir = _profiles_dir(base)
    # generation continuity (PR 20): checkpoints spool next to THIS
    # replica's pidfile (per-replica ownership, like span/event spools) —
    # the engine writes it directly at step boundaries, because the
    # manager's 1 s drain cadence is far too slow for crash durability
    from analytics_zoo_tpu.serving import tracecollect as _tc
    serving.snapshot_path = _tc.gensnap_path(pidfile)
    health_path = _health_path(pidfile)
    if knobs_path is None:
        knobs_path = _knobs_path(pidfile)
    knobs_seen = 0

    def _terminate(signum, frame):
        # ClusterServingManager.listenTermination analog: graceful drain
        # (admission closed, /readyz flips to draining, in-flight results
        # flushed within params.drain_s) + exit.  Spans recorded during
        # the drain (final writes, sheds) flush to the spool last — the
        # spool survives the process for post-mortem `manager trace`.
        serving.shutdown(drain_s=serving.params.drain_s)
        _drain_spans(serving, pidfile)
        _drain_events(pidfile, source=serving.replica_id)
        # the journal survives `manager stop`: the final interval's usage
        # (results flushed during the drain) must not be lost to billing
        _drain_usage(serving, pidfile)
        for p in (pidfile, health_path):
            try:
                os.unlink(p)
            except OSError:
                pass
        sys.exit(0)

    def _retire(signum, frame):
        # scale-down decommission (PR 10): flush in-flight work and exit
        # WITHOUT closing the shared queue's admission — one retiring
        # replica must not cut off ingest for the survivors.  (This was a
        # live bug in the PR 5 scale path: `manager scale N-1` SIGTERMed a
        # replica, whose drain closed admission on the shared backend and
        # left the whole fleet rejecting enqueues.)
        serving.shutdown(drain_s=serving.params.drain_s,
                         close_admission=False)
        _drain_spans(serving, pidfile)
        _drain_events(pidfile, source=serving.replica_id)
        _drain_usage(serving, pidfile)
        for p in (pidfile, health_path):
            try:
                os.unlink(p)
            except OSError:
                pass
        sys.exit(0)

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)
    if hasattr(signal, "SIGUSR1"):
        signal.signal(signal.SIGUSR1, _retire)
    serving.start()
    while True:
        _write_health(serving, health_path)
        # fleet tracing (PR 13): the replica's export hop — drained spans
        # land in <pidfile>.spans.jsonl, merged fleet-wide by
        # `manager trace` / tools/trace_view.py
        _drain_spans(serving, pidfile)
        # flight recorder (PR 15): same hop for the event ring
        _drain_events(pidfile, source=serving.replica_id)
        # usage metering (PR 19): same hop for the usage journal
        _drain_usage(serving, pidfile)
        # live knob nudges (PR 10 autoscaler fast tier): the supervisor's
        # autoscaler writes <base pidfile>.knobs.json; every replica polls
        # it once a second and applies via retune() — validated, and taken
        # up at the engine's next batch boundary
        try:
            st = os.stat(knobs_path)
            if st.st_mtime_ns != knobs_seen:
                knobs_seen = st.st_mtime_ns
                with open(knobs_path) as f:
                    knobs = json.load(f)
                if isinstance(knobs, dict):
                    serving.retune(**{
                        k: knobs[k] for k in
                        ("max_batch", "max_wait_ms",
                         "preprocess_workers", "inflight_batches")
                        if k in knobs})
        except (OSError, ValueError, TypeError):
            pass                           # no/garbled knobs file: keep as-is
        time.sleep(1)


def _prewarm(config_path: str, pidfile: str,
             timeout_s: float = 900.0,
             version: Optional[str] = None) -> Optional[dict]:
    """One throwaway warm-up pass BEFORE any replica forks (PR 11): a
    subprocess (never a fork — the supervisor must stay jax-free so its
    children fork clean) runs `manager warmup`, which exports the mmap
    weight store and populates the per-deployment XLA compilation cache.
    Every replica spawned afterwards — including every future autoscaler
    scale-up — loads executables from disk instead of compiling.  Failure
    is logged, not fatal: replicas fall back to compiling for themselves.

    With ``version`` (PR 16 rollout), the pass loads the REGISTRY
    snapshot for that version instead of re-exporting — run before the
    canary takes traffic, so every replaced replica boots with zero
    steady-state compiles."""
    import subprocess
    cmd = [sys.executable, "-m", "analytics_zoo_tpu.serving.manager",
           "warmup", "-c", config_path, "--pidfile", pidfile]
    if version:
        cmd += ["--version", version]
    try:
        out = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s)
        doc = None
        for line in (out.stdout or "").splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    doc = json.loads(line)
                except ValueError:
                    pass
        if out.returncode != 0:
            print(json.dumps({"event": "prewarm failed",
                              "rc": out.returncode,
                              "stderr": (out.stderr or "")[-500:]}),
                  file=sys.stderr, flush=True)
            return None
        print(json.dumps({"event": "prewarm done", "warmup": doc}),
              file=sys.stderr, flush=True)
        return doc
    except Exception as e:  # noqa: BLE001 — prewarm is best-effort
        print(json.dumps({"event": "prewarm failed",
                          "error": f"{type(e).__name__}: {e}"}),
              file=sys.stderr, flush=True)
        return None


def _run_supervisor(config_path: str, pidfile: str, replicas: int,
                    autoscale: bool = False,
                    lb_port: Optional[int] = None,
                    prewarm: bool = True):
    """Replica supervisor (PR 5 tentpole): fork one serving process per
    replica over the SHARED queue, monitor them, respawn crashed ones (a
    SIGKILLed replica's orphaned records are reclaimed by the survivors
    while the respawn happens), and track the desired count in
    `<pidfile>.replicas` so `manager scale N` can resize a live deployment.
    SIGTERM forwards to every replica (each drains per params.drain_s) and
    then exits.

    PR 10: with ``autoscale`` the closed-loop controller runs here too —
    fleet signals from the per-replica health docs, topology through the
    SAME scale file `manager scale N` writes (the supervisor poll loop is
    the actuator either way), knob nudges through `<pidfile>.knobs.json`,
    controller metrics snapshotted to `<pidfile>.autoscaler.json` each
    pass.  With ``lb_port`` the single-port load-balancing front door
    (serving/lb.py) serves next to the supervisor, tracking membership as
    the fleet resizes."""
    with open(pidfile, "w") as f:
        f.write(str(os.getpid()))
    scale_path = _scale_path(pidfile)
    with open(scale_path, "w") as f:
        f.write(str(replicas))
    children: dict = {}                    # index -> pid
    last_spawn: dict = {}                  # index -> monotonic ts (backoff)
    stopping: set = set()                  # indices already SIGTERMed

    cfg = load_config(config_path)
    params = serving_params(cfg)
    # incident auto-capture (PR 15): config `incident:` section —
    # `burn_threshold` snapshots a bundle when any replica's SLO burn
    # rate crosses it, `on_crash` (default on) when a replica dies and
    # is respawned, `cooldown_s` bounds capture frequency, `max_bundles`
    # bounds disk.  Capture is supervisor-side file copying of drained
    # spools: the serving hot path never blocks.
    icfg = cfg.get("incident") if isinstance(cfg.get("incident"), dict) \
        else {}
    inc_burn = icfg.get("burn_threshold")
    inc_burn = None if inc_burn is None else float(inc_burn)
    inc_on_crash = bool(icfg.get("on_crash", True))
    inc_cooldown = float(icfg.get("cooldown_s", 60.0))
    inc_max = int(icfg.get("max_bundles", 20))
    inc_last = {"t": -1e9}
    from analytics_zoo_tpu.common.observability import get_recorder
    recorder = get_recorder()

    def _capture_incident(reason: str, meta=None, force=False):
        from analytics_zoo_tpu.serving import incident as _incident
        now = time.monotonic()
        if not force and now - inc_last["t"] < inc_cooldown:
            return None
        inc_last["t"] = now
        # the bundle meta may itself carry a "reason" (the rollback
        # verdict) — the event's positional `reason` wins, drop the
        # duplicate instead of TypeError-ing the capture away
        extra = {k: v for k, v in (meta or {}).items() if k != "reason"}
        recorder.record("incident", reason=reason, **extra)
        # flush the supervisor's own ring first so the bundle carries the
        # trigger event itself (replica spools were drained by their own
        # 1 s loops — capture reads files, never the hot path)
        _drain_events(pidfile, source="supervisor")
        bundle = _incident.capture(pidfile, reason, meta=meta,
                                   max_bundles=inc_max)
        if bundle:
            print(json.dumps({"event": "incident captured",
                              "reason": reason, "bundle": bundle}),
                  file=sys.stderr, flush=True)
        return bundle

    # zero-drop rollout (PR 16): versioned-registry state.  The rollout
    # STATE file persists the per-replica version assignments — the
    # respawn pin (satellite bugfix: a replica that crashes mid-rollout
    # respawns at its ASSIGNED version, incumbent or canary, never
    # blindly at `latest`) — and survives a supervisor restart.
    from analytics_zoo_tpu.serving import registry as _registry
    from analytics_zoo_tpu.serving import rollout as _rollout
    rparams = _rollout.RolloutParams.from_dict(cfg.get("rollout"))
    model_name = _model_name(cfg)
    reg_dir = _registry_dir(pidfile)
    rst = _rollout.load_state(pidfile)
    assigned: dict = rst.get("assignments") or {}
    if rst.get("base") is None:
        # fresh deployment: serve the registry's latest when one is
        # published; an unversioned deployment (no registry) keeps the
        # plain config/weight-store path exactly as before PR 16
        rst["base"] = _registry.latest(reg_dir, model_name)
    rolling: set = set()        # indices being intentionally replaced
    rollout_meta = {"canary_crashes": 0, "t_phase": time.monotonic(),
                    "dwell_start": None, "replacing": None}

    def _assigned_version(index: int):
        return assigned.get(index, rst.get("base"))

    def _save_rollout():
        rst["assignments"] = assigned
        _rollout.save_state(pidfile, rst)

    _save_rollout()

    if prewarm and params.warmup and \
            _resolve_cache_dir(params, pidfile):
        # pre-populate the deployment's compile cache + weight store so
        # the replicas about to fork (and every scale-up after them) boot
        # warm.  The fleet takes traffic a few seconds later but each
        # member reaches /readyz in seconds instead of a compile.
        _prewarm(config_path, pidfile, version=rst.get("base"))
    scaler = None
    balancer = None
    if autoscale:
        from analytics_zoo_tpu.serving.autoscaler import (Autoscaler,
                                                          AutoscalerParams,
                                                          ManagerFleet)
        as_params = AutoscalerParams.from_dict(cfg.get("autoscaler") or {})
        fleet = ManagerFleet(pidfile, http_host=params.http_host,
                             http_port=params.http_port,
                             max_replicas=as_params.max_replicas)
        scaler = Autoscaler(fleet, params=as_params).start()
    if lb_port is not None:
        from analytics_zoo_tpu.serving.lb import (LoadBalancer,
                                                  manager_members)
        from analytics_zoo_tpu.serving.tracecollect import spool_path
        balancer = LoadBalancer(
            manager_members(pidfile, http_host=params.http_host,
                            http_port=params.http_port),
            host=params.http_host, port=lb_port,
            trace_sample=params.trace_sample,
            span_spool=spool_path(pidfile + ".lb"),
            retry_budget=cfg.get("retry_budget")).start()

    def _spawn(index: int):
        last_spawn[index] = time.monotonic()
        # rollout (PR 16): the spawn spec pins the replica's ASSIGNED
        # version — during a rollout the canary respawns at the target
        # and every incumbent at the prior, so a crash mid-canary can
        # never silently promote (or demote) a replica
        version = _assigned_version(index)
        recorder.record("replica_spawn", index=index,
                        model_version=version)
        pid = os.fork()
        if pid == 0:
            # child: plain replica process with its own pidfile/health
            # snapshot, default signal disposition restored so the replica
            # installs its own graceful-drain SIGTERM handler
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            signal.signal(signal.SIGINT, signal.SIG_DFL)
            # the fork copies the supervisor's process-wide flight-
            # recorder ring: clear it, or the child's 1 s drain would
            # re-spool the supervisor's undrained events (this very
            # spawn event included) misattributed to the replica
            get_recorder().clear()
            try:
                _run_foreground(config_path, _replica_pidfile(pidfile, index),
                                replica_id=f"replica-{index}",
                                http_port_offset=index,
                                knobs_path=_knobs_path(pidfile),
                                base_pidfile=pidfile,
                                model_version=version)
            finally:
                os._exit(0)
        children[index] = pid

    retire_sig = getattr(signal, "SIGUSR1", signal.SIGTERM)

    def _read_rhealth(index: int):
        try:
            with open(_health_path(_replica_pidfile(pidfile, index))) as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else None
        except (OSError, ValueError):
            return None

    def _replace(index: int, version):
        """Move one replica slot onto ``version``: pin the assignment
        (respawn-safe), then SIGUSR1-retire the old process — it drains
        with shared-queue admission OPEN, its leases cover in-flight
        records, and the reap/spawn passes bring the slot back up at the
        new version.  The LB health-outs the retiring gateway, so the
        swap is client-invisible."""
        assigned[index] = version
        _save_rollout()
        recorder.record("rollout_replace", index=index, version=version)
        pid = children.get(index)
        if pid:
            rolling.add(index)
            try:
                os.kill(pid, retire_sig)
            except OSError:
                pass

    def _begin_rollback(reason: str):
        target, prior = rst.get("target"), rst.get("base")
        recorder.record("rollback", target=target, prior=prior,
                        reason=str(reason)[:200])
        print(json.dumps({"event": "rollout rollback",
                          "from_version": target, "to_version": prior,
                          "reason": reason}), file=sys.stderr, flush=True)
        # the rollback IS the incident: bundle the evidence BEFORE the
        # reverse restart rotates it, stamped with both versions.
        # force=True — a crash capture moments earlier must not suppress
        # the rollback's own forensics behind the cooldown
        _capture_incident(
            f"rollout-rollback {target} -> {prior or 'unversioned'}",
            meta={"from_version": target, "to_version": prior,
                  "reason": str(reason)[:500],
                  "phase": rst.get("phase")},
            force=True)
        rst["phase"] = "rollback"
        rst["reason"] = str(reason)
        rollout_meta["t_phase"] = time.monotonic()
        rollout_meta["replacing"] = None
        rollout_meta["dwell_start"] = None
        _save_rollout()

    def _rollout_tick(desired: int):
        """One pass of the rollout state machine (idle -> canary ->
        rolling -> idle, or -> rollback -> idle), driven off the same
        per-replica health snapshots the incident triggers read."""
        now = time.monotonic()
        phase = rst.get("phase", "idle")
        if phase == "idle":
            req = _rollout.read_request(pidfile)
            if not req or not req.get("target"):
                return
            if float(req.get("ts") or 0) <= float(rst.get("req_ts") or 0):
                return                     # request already processed
            target = str(req["target"])
            rst["req_ts"] = req.get("ts")
            if target == rst.get("base"):
                print(json.dumps({"event": "rollout no-op",
                                  "target": target,
                                  "detail": "fleet already at target"}),
                      file=sys.stderr, flush=True)
                _save_rollout()
                return
            try:
                problems = _registry.verify(reg_dir, target,
                                            model=model_name)
            except Exception as e:  # noqa: BLE001 — registry unreadable
                problems = [f"{type(e).__name__}: {e}"]
            if problems:
                # a truncated/corrupt version is rejected LOUDLY and the
                # previous version keeps serving — no replica is touched
                recorder.record("rollout_rejected", target=target,
                                problems=len(problems))
                rst["last_error"] = {"target": target,
                                     "problems": problems[:5]}
                _save_rollout()
                print(json.dumps({"event": "rollout rejected",
                                  "target": target,
                                  "problems": problems[:5]}),
                      file=sys.stderr, flush=True)
                return
            if rparams.prewarm and params.warmup and \
                    _resolve_cache_dir(params, pidfile):
                # pre-warm the new version's programs into the SHARED
                # XLA cache before any replica is retired: every
                # replaced replica then boots with zero steady-state
                # compiles
                _prewarm(config_path, pidfile, version=target)
            rst.update(phase="canary", target=target, canary_index=0,
                       started=time.time(), reason=None, diverged=None)
            rollout_meta.update(canary_crashes=0, t_phase=now,
                                dwell_start=None, replacing=None)
            recorder.record("rollout_start", target=target,
                            prior=rst.get("base"))
            print(json.dumps({"event": "rollout start", "target": target,
                              "prior": rst.get("base")}),
                  file=sys.stderr, flush=True)
            _replace(0, target)
            return
        target = rst.get("target")
        if phase == "canary":
            idx = int(rst.get("canary_index") or 0)
            doc = _read_rhealth(idx)
            at_target = (doc is not None
                         and doc.get("model_version") == target
                         and idx in children and idx not in rolling)
            incumbents = []
            for i in range(desired):
                if i == idx:
                    continue
                d = _read_rhealth(i)
                if d is not None:
                    incumbents.append(d)
            reason = _rollout.judge(doc if at_target else None, incumbents,
                                    rparams,
                                    rollout_meta["canary_crashes"])
            if reason:
                if rparams.auto_rollback:
                    _begin_rollback(reason)
                    return
                if rst.get("diverged") != reason:
                    # rollback disabled (chaos A/B control arm): record
                    # the divergence verdict, keep rolling — the damage
                    # this causes is the measurement
                    rst["diverged"] = reason
                    recorder.record("rollout_diverged", target=target,
                                    reason=str(reason)[:200])
                    _save_rollout()
            if not at_target or not bool(
                    (doc.get("ready") or {}).get("ready")):
                if now - rollout_meta["t_phase"] > rparams.ready_timeout_s \
                        and rparams.auto_rollback:
                    _begin_rollback(
                        f"canary not ready at {target} within "
                        f"{rparams.ready_timeout_s:g}s")
                return
            if rollout_meta["dwell_start"] is None:
                rollout_meta["dwell_start"] = now
                recorder.record("canary_serving", index=idx,
                                target=target)
                return
            if now - rollout_meta["dwell_start"] >= rparams.canary_dwell_s:
                recorder.record("canary_pass", target=target,
                                dwell_s=round(
                                    now - rollout_meta["dwell_start"], 3))
                print(json.dumps({"event": "canary pass",
                                  "target": target}),
                      file=sys.stderr, flush=True)
                rst["phase"] = "rolling"
                rollout_meta["t_phase"] = now
                rollout_meta["replacing"] = None
                _save_rollout()
            return
        if phase == "rolling":
            r = rollout_meta["replacing"]
            if r is not None:
                doc = _read_rhealth(r)
                up = (doc is not None
                      and doc.get("model_version") == target
                      and bool((doc.get("ready") or {}).get("ready"))
                      and r in children and r not in rolling)
                if up:
                    rollout_meta["replacing"] = None
                    rollout_meta["t_phase"] = now
                elif now - rollout_meta["t_phase"] > \
                        rparams.ready_timeout_s:
                    if rparams.auto_rollback:
                        _begin_rollback(
                            f"replica {r} not ready at {target} within "
                            f"{rparams.ready_timeout_s:g}s")
                    return
                else:
                    return
            pending = [i for i in range(desired)
                       if _assigned_version(i) != target]
            if pending:
                # one at a time: the fleet is never more than one
                # replica short of desired capacity
                nxt = pending[0]
                rollout_meta["replacing"] = nxt
                rollout_meta["t_phase"] = now
                _replace(nxt, target)
                return
            rst["base"] = target
            assigned.clear()
            rst.update(phase="idle", target=None, reason=None)
            recorder.record("promote", version=target)
            print(json.dumps({"event": "promote", "version": target}),
                  file=sys.stderr, flush=True)
            _save_rollout()
            return
        if phase == "rollback":
            prior = rst.get("base")
            r = rollout_meta["replacing"]
            if r is not None:
                doc = _read_rhealth(r)
                home = (doc is not None
                        and doc.get("model_version") == prior
                        and r in children and r not in rolling)
                if home:
                    rollout_meta["replacing"] = None
                    rollout_meta["t_phase"] = now
                elif now - rollout_meta["t_phase"] > \
                        rparams.ready_timeout_s:
                    # never wedge the rollback on one slow slot — its
                    # assignment is already pinned to prior, the respawn
                    # loop keeps trying; move on
                    recorder.record("rollback_replica_timeout", index=r)
                    rollout_meta["replacing"] = None
                    rollout_meta["t_phase"] = now
                else:
                    return
            pending = [i for i in range(desired)
                       if _assigned_version(i) != prior]
            if pending:
                nxt = pending[0]
                rollout_meta["replacing"] = nxt
                rollout_meta["t_phase"] = now
                _replace(nxt, prior)
                return
            tgt = rst.get("target")
            assigned.clear()
            rst["last_rollback"] = {"target": tgt,
                                    "reason": rst.get("reason"),
                                    "finished": time.time()}
            rst.update(phase="idle", target=None)
            recorder.record("rollback_done", target=tgt, prior=prior)
            print(json.dumps({"event": "rollback done", "target": tgt,
                              "prior": prior}),
                  file=sys.stderr, flush=True)
            _save_rollout()
            return

    def _terminate(signum, frame):
        for pid in children.values():
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
        deadline = time.time() + 60        # replicas drain per their config
        for pid in children.values():
            while time.time() < deadline:
                try:
                    if os.waitpid(pid, os.WNOHANG)[0]:
                        break
                except ChildProcessError:
                    break
                time.sleep(0.1)
        if scaler is not None:
            scaler.stop()
        if balancer is not None:
            try:
                balancer.drain_spans_to_spool()
            except Exception:  # noqa: BLE001
                pass
            balancer.stop()
        for index in list(children):
            for p in (_replica_pidfile(pidfile, index),
                      _health_path(_replica_pidfile(pidfile, index))):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        for p in (pidfile, scale_path, _knobs_path(pidfile),
                  _autoscaler_path(pidfile), _lb_path(pidfile),
                  _rollout.request_path(pidfile)):
            # the rollout STATE file deliberately survives: it pins the
            # per-replica version assignments across a supervisor restart
            try:
                os.unlink(p)
            except OSError:
                pass
        # span spools deliberately survive shutdown: `manager trace` is a
        # post-mortem tool as much as a live one
        sys.exit(0)

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)
    while True:
        try:
            with open(scale_path) as f:
                desired = max(0, int(f.read().strip()))
        except (OSError, ValueError):
            desired = replicas
        # reap exits (crash -> respawn below; scale-down exit -> forget)
        for index, pid in list(children.items()):
            try:
                done, _ = os.waitpid(pid, os.WNOHANG)
            except ChildProcessError:
                done = pid
            if done:
                children.pop(index)
                was_retiring = index in stopping
                was_rolling = index in rolling
                stopping.discard(index)
                rolling.discard(index)
                if index < desired:
                    print(json.dumps({"replica": index, "pid": pid,
                                      "event": "exited; respawning",
                                      "rolling": was_rolling}),
                          file=sys.stderr, flush=True)
                    recorder.record("replica_exit", index=index, pid=pid,
                                    respawning=True, rolling=was_rolling)
                    if was_rolling:
                        # rollout (PR 16): an INTENTIONAL replace — the
                        # old process finished its retire-drain; the
                        # respawn below brings the slot up at its newly
                        # assigned version.  Not a crash, no incident.
                        pass
                    else:
                        if rst.get("phase") != "idle" and \
                                _assigned_version(index) == rst.get(
                                    "target"):
                            # a replica already moved to the rollout
                            # target died unexpectedly: crash evidence
                            # for the canary judge
                            rollout_meta["canary_crashes"] += 1
                        if inc_on_crash:
                            # PR 15: an unexpected replica death IS the
                            # incident — bundle every process's recent
                            # events/spans/health before evidence rotates
                            _capture_incident(
                                f"replica-{index}-crash",
                                meta={"replica": index, "pid": pid})
                else:
                    recorder.record("replica_exit", index=index, pid=pid,
                                    respawning=False,
                                    retired=was_retiring)
        # scale down: highest-numbered replicas RETIRE (SIGUSR1: drain
        # their in-flight work, shared admission stays open for the
        # survivors) and exit; signalled once — a repeat would re-enter
        # the replica's drain handler
        retire_sig = getattr(signal, "SIGUSR1", signal.SIGTERM)
        for index in sorted(children, reverse=True):
            if index >= desired and index not in stopping:
                stopping.add(index)
                recorder.record("replica_retire", index=index)
                try:
                    os.kill(children[index], retire_sig)
                except OSError:
                    pass
        # spawn missing replicas, rate-limited to one respawn per second
        # per slot so a crash-looping config cannot fork-bomb the host
        now = time.monotonic()
        for index in range(desired):
            if index not in children and \
                    now - last_spawn.get(index, -1e9) >= 1.0:
                _spawn(index)
        # zero-drop rollout (PR 16): drive the canary / rolling-replace /
        # rollback state machine off the same per-replica health
        # snapshots the incident triggers read.  Never load-bearing for
        # the fleet's liveness: a tick error logs and retries next pass.
        try:
            _rollout_tick(desired)
        except Exception as e:  # noqa: BLE001
            print(json.dumps({"event": "rollout tick error",
                              "error": f"{type(e).__name__}: {e}"}),
                  file=sys.stderr, flush=True)
        # SLO-burn incident trigger (PR 15): the replicas' health
        # snapshots already land next to the pidfile every second —
        # cheap file reads, throttled by the capture cooldown itself
        if inc_burn is not None:
            worst = None
            for index in range(desired):
                try:
                    with open(_health_path(
                            _replica_pidfile(pidfile, index))) as f:
                        doc = json.load(f)
                    br = (doc.get("slo") or {}).get("burn_rate")
                    if isinstance(br, (int, float)):
                        worst = br if worst is None else max(worst, br)
                except (OSError, ValueError):
                    continue
            if worst is not None and worst >= inc_burn:
                _capture_incident(
                    f"slo-burn {worst:.2f} >= threshold {inc_burn:.2f}",
                    meta={"burn_rate": round(float(worst), 4),
                          "threshold": inc_burn})
        # the supervisor's own events (spawns, retires, autoscaler
        # decisions, LB member flips) spool next to the replicas'
        _drain_events(pidfile, source="supervisor")
        if scaler is not None:
            # controller observability through `manager metrics`: persist
            # the decision counters / target gauges / decision log next to
            # the pidfile (atomic, same pattern as the health snapshots)
            try:
                snap_path = _autoscaler_path(pidfile)
                with open(snap_path + ".tmp", "w") as f:
                    json.dump(scaler.snapshot(), f)
                os.replace(snap_path + ".tmp", snap_path)
            except OSError:
                pass
        if balancer is not None:
            # PR 13: the front door's half of fleet observability — its
            # root spans to the LB spool, its registry (lb_requests_total
            # / lb_retries_total / member gauges + exposition) to
            # <pidfile>.lb.json so `manager metrics --all-replicas`
            # includes the LB instead of leaving it invisible
            try:
                balancer.drain_spans_to_spool()
            except Exception:  # noqa: BLE001 — never load-bearing
                pass
            try:
                lb_path = _lb_path(pidfile)
                with open(lb_path + ".tmp", "w") as f:
                    json.dump({"url": balancer.url, "ts": time.time(),
                               "snapshot": balancer.registry.snapshot(),
                               "prom": balancer.registry.to_prometheus()},
                              f)
                os.replace(lb_path + ".tmp", lb_path)
            except (OSError, TypeError, ValueError):
                pass
        time.sleep(0.5)


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(prog="cluster-serving")
    ap.add_argument("action",
                    choices=["start", "stop", "status", "restart", "health",
                             "replay", "metrics", "scale", "warmup",
                             "trace", "incident", "profile", "publish",
                             "versions", "rollout", "usage"])
    ap.add_argument("value", nargs="?", default=None,
                    help="scale: target replica count; trace: the "
                         "trace_id to reconstruct; incident --show: the "
                         "bundle name (default latest); profile: the "
                         "replica index (default 0); publish/rollout: "
                         "the version name")
    ap.add_argument("-c", "--config", default="config.yaml")
    ap.add_argument("--pidfile", default=PIDFILE)
    ap.add_argument("--foreground", action="store_true")
    ap.add_argument("--replicas", type=int, default=None, metavar="N",
                    help="start: run N supervised serving replicas over the "
                         "shared queue (crashed replicas respawn; their "
                         "in-flight records are reclaimed by survivors)")
    ap.add_argument("--autoscale", action="store_true",
                    help="start --replicas: run the closed-loop autoscaler "
                         "in the supervisor (config `autoscaler:` section "
                         "tunes it); topology via the scale file, knob "
                         "nudges via <pidfile>.knobs.json")
    ap.add_argument("--lb-port", type=int, default=None, metavar="P",
                    help="start --replicas: serve the single-port "
                         "load-balancing front door on P (proxies "
                         "/v1/enqueue + /v1/result across the live replica "
                         "gateways)")
    ap.add_argument("--all-replicas", action="store_true",
                    help="metrics: one fleet-wide snapshot summed across "
                         "the per-replica registries (HTTP scrape with "
                         "health.json fallback); with --prom, the merged "
                         "text exposition")
    ap.add_argument("--filter", default=None, metavar="SUBSTR",
                    help="replay only dead letters whose uri or error "
                         "contains SUBSTR")
    ap.add_argument("--prom", action="store_true",
                    help="metrics: print the Prometheus text exposition "
                         "(requires params.http_port on the daemon)")
    ap.add_argument("--no-prewarm", action="store_true",
                    help="start --replicas: skip the supervisor's "
                         "throwaway warm-up pass (replicas then compile "
                         "for themselves on first boot)")
    ap.add_argument("--slowest", type=int, default=None, metavar="N",
                    help="trace: rank the N slowest traces fleet-wide "
                         "instead of reconstructing one")
    ap.add_argument("--chrome", default=None, metavar="PATH",
                    help="trace: export the merged fleet timeline as "
                         "Chrome trace-event JSON (one track per "
                         "process) for Perfetto")
    ap.add_argument("--list", action="store_true", dest="list_",
                    help="incident: list captured bundles")
    ap.add_argument("--show", action="store_true",
                    help="incident: render a bundle's merged "
                         "cross-process timeline (recorder events + "
                         "trace spans); pass the bundle name as the "
                         "positional value, default latest")
    ap.add_argument("--last", type=int, default=200, metavar="N",
                    help="incident --show: timeline entries to render "
                         "(default 200)")
    ap.add_argument("--seconds", type=float, default=5.0, metavar="S",
                    help="profile: trace duration (default 5s)")
    ap.add_argument("--version", default=None, metavar="V",
                    help="warmup: warm the registry snapshot for version "
                         "V (no re-export) — the rollout's pre-warm pass "
                         "runs this so replaced replicas boot with zero "
                         "compiles")
    ap.add_argument("--since", type=float, default=None, metavar="EPOCH",
                    help="usage: only count journal deltas drained after "
                         "this wall time (epoch seconds)")
    ap.add_argument("--by", default="tenant", choices=["tenant", "model"],
                    help="usage: rollup dimension (default tenant)")
    ap.add_argument("--json", action="store_true", dest="json_",
                    help="usage: print the rollup as JSON")
    args = ap.parse_args(argv)

    def read_pid():
        try:
            with open(args.pidfile) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def alive(pid):
        try:
            os.kill(pid, 0)
            return True
        except OSError:
            return False

    def read_health():
        try:
            with open(_health_path(args.pidfile)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    if args.action == "warmup":
        # zero cold start (PR 11): one throwaway pass that persists the
        # deployment's warm state — the mmap weight store and the
        # persistent XLA compilation cache, both next to the pidfile — so
        # every replica spawned after it boots warm.  Run standalone at
        # deploy time, or implicitly by `start --replicas` (the
        # supervisor's pre-warm subprocess IS this action).
        from analytics_zoo_tpu.inference import aot, weightstore
        cfg = load_config(args.config)
        params = serving_params(cfg)
        cache_dir = _resolve_cache_dir(params, args.pidfile)
        if cache_dir:
            aot.enable_persistent_cache(cache_dir)
        if args.version:
            # rollout pre-warm (PR 16): warm the REGISTRY snapshot for
            # this version into the shared compile cache — verified
            # first, never re-exported (published versions are immutable)
            from analytics_zoo_tpu.serving import registry as _registry
            try:
                ver = _registry.resolve(_registry_dir(args.pidfile),
                                        args.version,
                                        model=_model_name(cfg))
                store = _version_store(args.pidfile, ver,
                                       _model_name(cfg))
            except _registry.RegistryError as e:
                print(json.dumps({"error": str(e)}), file=sys.stderr)
                return 1
            im = load_model(cfg, weight_store=store)
            if params.sharding != "off":
                im.shard(mesh=params.mesh_shape, sharding=params.sharding)
            stats = aot.warm_up(im, aot.resolve_manifest(
                im, params.warmup if params.warmup else True))
            print(json.dumps({"cache_dir": cache_dir,
                              "weight_store": store, "version": ver,
                              "load_seconds": im.load_seconds,
                              "load_mmap": im.load_mmap, **stats}))
            return 0 if stats["failed"] == 0 else 1
        store = _weights_dir(args.pidfile)
        im = load_model(cfg, weight_store=store)
        if params.quantize:
            # quantize BEFORE the export + warm-up (PR 14): the store this
            # pass persists holds the packed int4 / int8 + scale leaves,
            # and the programs it compiles are the quantized graph — a
            # replica fork then mmaps quantized weights and hits the warm
            # cache, compiling nothing.  A store already quantized (a
            # prior warmup pass) restores as-is and is skipped here.
            from analytics_zoo_tpu.serving.engine import apply_quantize
            apply_quantize(im, params.quantize)
        exported = False
        if getattr(im, "_params", None):
            try:
                man = weightstore.save_store(
                    store, {"params": im._params,
                            "state": im._state or {}})
                exported = not man.get("skipped", False)
            except Exception as e:  # noqa: BLE001 — store is an optim,
                # not a correctness requirement
                print(json.dumps({"warning": f"weight store export "
                                             f"failed ({type(e).__name__}"
                                             f": {e})"}), file=sys.stderr)
                store = None
        else:
            store = None
        if params.sharding != "off":
            # warm the DEPLOYED placement: the replicas shard at
            # construction, so an unsharded warm-up would compile the
            # wrong programs
            im.shard(mesh=params.mesh_shape, sharding=params.sharding)
        stats = aot.warm_up(im, aot.resolve_manifest(
            im, params.warmup if params.warmup else True))
        from analytics_zoo_tpu.inference.quantize import quantized_bits
        print(json.dumps({"cache_dir": cache_dir, "weight_store": store,
                          "store_exported": exported,
                          "load_seconds": im.load_seconds,
                          "load_mmap": im.load_mmap,
                          "quantized_bits": quantized_bits(
                              getattr(im, "_params", None) or {}),
                          **stats}))
        return 0 if stats["failed"] == 0 else 1
    if args.action == "publish":
        # versioned model registry (PR 16): build the deployment's model
        # per the CONFIG (never the shared weight store — a stale store
        # would republish the previous version's weights under a new
        # name), quantize like `manager warmup` would, export a staging
        # weight store, and snapshot it as one immutable version.
        if not args.value:
            print(json.dumps({"error": "publish needs a version name: "
                                       "manager publish <version>"}),
                  file=sys.stderr)
            return 1
        import shutil
        import tempfile
        from analytics_zoo_tpu.inference import aot, weightstore
        from analytics_zoo_tpu.serving import registry as _registry
        cfg = load_config(args.config)
        params = serving_params(cfg)
        model_name = _model_name(cfg)
        reg = _registry_dir(args.pidfile)
        im = load_model(cfg)
        if params.quantize:
            from analytics_zoo_tpu.serving.engine import apply_quantize
            apply_quantize(im, params.quantize)
        if not getattr(im, "_params", None):
            print(json.dumps({"error": "publish needs a model with "
                                       "restorable params (zoo "
                                       "topology)"}), file=sys.stderr)
            return 1
        os.makedirs(reg, exist_ok=True)
        staging = tempfile.mkdtemp(prefix=".staging-", dir=reg)
        try:
            sdir = os.path.join(staging, "weights")
            weightstore.save_store(sdir, {"params": im._params,
                                          "state": im._state or {}})
            try:
                # the warm-up manifest rides the version doc, so ops can
                # see WHAT program set a version pre-warms without
                # loading it
                entries = aot.resolve_manifest(
                    im, params.warmup if params.warmup else True)
                wdoc = [_jsonable(vars(e)) for e in entries]
            except Exception:  # noqa: BLE001 — metadata, never fatal
                wdoc = None
            try:
                doc = _registry.publish(
                    reg, args.value, sdir, model=model_name,
                    quantize=_jsonable(params.quantize),
                    warmup=wdoc,
                    meta={"config": os.path.abspath(args.config)})
            except _registry.RegistryError as e:
                print(json.dumps({"error": str(e)}), file=sys.stderr)
                return 1
        finally:
            shutil.rmtree(staging, ignore_errors=True)
        print(json.dumps({"published": doc["version"],
                          "model": model_name,
                          "fingerprint": doc["fingerprint"],
                          "registry": reg,
                          "latest": _registry.latest(reg, model_name)}))
        return 0
    if args.action == "versions":
        # registry inventory: every published version, latest marked
        from analytics_zoo_tpu.serving import registry as _registry
        try:
            model_name = _model_name(load_config(args.config))
        except OSError:
            model_name = "default"
        reg = _registry_dir(args.pidfile)
        vs = _registry.versions(reg, model_name)
        print(json.dumps({
            "registry": reg, "model": model_name,
            "latest": _registry.latest(reg, model_name),
            "versions": [{k: v.get(k) for k in
                          ("version", "fingerprint", "created",
                           "quantize", "latest")} for v in vs]}))
        return 0
    if args.action == "rollout":
        # zero-drop rollout (PR 16): verify the target version, then hand
        # the supervisor a request file (same file-not-signal pattern as
        # `manager scale`) — its poll loop runs the canary / rolling
        # replace / auto-rollback state machine
        from analytics_zoo_tpu.serving import registry as _registry
        from analytics_zoo_tpu.serving import rollout as _rollout
        if not args.value:
            print(json.dumps({"error": "rollout needs a version: "
                                       "manager rollout <version>"}),
                  file=sys.stderr)
            return 1
        pid = read_pid()
        if pid is None or not alive(pid):
            print(json.dumps({"error": "serving not running"}),
                  file=sys.stderr)
            return 1
        if not os.path.exists(_scale_path(args.pidfile)):
            print(json.dumps({"error": "not running as a replica "
                                       "supervisor (start with "
                                       "--replicas N)"}), file=sys.stderr)
            return 1
        try:
            model_name = _model_name(load_config(args.config))
        except OSError:
            model_name = "default"
        reg = _registry_dir(args.pidfile)
        try:
            ver = _registry.resolve(reg, args.value, model=model_name)
        except _registry.RegistryError as e:
            print(json.dumps({"error": str(e)}), file=sys.stderr)
            return 1
        problems = _registry.verify(reg, ver, model=model_name)
        if problems:
            # reject a corrupt version at the CLI already — the
            # supervisor re-verifies, but the operator should hear it now
            print(json.dumps({"error": f"version {ver!r} failed "
                                       "integrity verification",
                              "problems": problems[:5]}),
                  file=sys.stderr)
            return 1
        _rollout.write_request(args.pidfile, ver, time.time())
        print(json.dumps({"rollout": ver,
                          "state": _rollout.state_path(args.pidfile)}))
        return 0
    if args.action == "incident":
        # incident forensics (PR 15): capture/list/show self-contained
        # bundles under <pidfile>.incidents/ — works on a live OR dead
        # deployment (post-mortem forensics reads files, not processes)
        from analytics_zoo_tpu.serving import incident as _incident
        if args.list_:
            print(json.dumps({"incidents":
                              _incident.list_incidents(args.pidfile)}))
            return 0
        if args.show:
            bundle = _incident.resolve_bundle(args.pidfile, args.value)
            if bundle is None:
                print(json.dumps({"error": "no incident bundle found "
                                           f"(looked under "
                                           f"{args.pidfile}.incidents)"}),
                      file=sys.stderr)
                return 1
            print(json.dumps(_incident.render(bundle, last=args.last)))
            return 0
        # operator-triggered capture: flush this CLI process's view is
        # moot (replicas spool their own rings every second); just bundle
        bundle = _incident.capture(args.pidfile, "operator",
                                   meta={"via": "manager incident"})
        if bundle is None:
            print(json.dumps({"error": "nothing to capture (no spools/"
                                       "health snapshots next to "
                                       f"{args.pidfile})"}),
                  file=sys.stderr)
            return 1
        print(json.dumps({"captured": True, "bundle": bundle}))
        return 0
    if args.action == "profile":
        # on-demand device profiling (PR 15): POST /debug/profile on the
        # target replica's PROBE port (never via the LB/gateway surface)
        try:
            params = serving_params(load_config(args.config))
        except OSError:
            params = ServingParams()
        if not params.http_port:
            print(json.dumps({"error": "profile needs params.http_port "
                                       "(the replica probe port)"}),
                  file=sys.stderr)
            return 1
        index = 0
        if args.value is not None:
            try:
                index = int(args.value)
            except ValueError:
                print(json.dumps({"error": f"profile: replica index "
                                           f"expected, got "
                                           f"{args.value!r}"}),
                      file=sys.stderr)
                return 1
        import urllib.error
        import urllib.request
        url = (f"http://{params.http_host}:{params.http_port + index}"
               f"/debug/profile?seconds={max(args.seconds, 0.05):g}")
        try:
            req = urllib.request.Request(url, data=b"", method="POST")
            with urllib.request.urlopen(
                    req, timeout=10.0) as resp:
                print(json.dumps(json.loads(resp.read())))
                return 0
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read())
            except (ValueError, OSError):
                body = {"error": f"HTTP {e.code}"}
            print(json.dumps(dict(body, code=e.code)), file=sys.stderr)
            return 1
        except Exception as e:  # noqa: BLE001 — replica down
            print(json.dumps({"error": f"replica {index} probe port "
                                       f"unreachable ({type(e).__name__}"
                                       f": {e})"}), file=sys.stderr)
            return 1
    if args.action == "trace":
        # fleet-wide trace reconstruction (PR 13): merge every span spool
        # of the deployment (per-replica + LB, written next to the health
        # snapshots), normalize each process's monotonic clock onto the
        # wall clock, and either reconstruct ONE request's cross-process
        # timeline, rank the slowest traces, or export the whole timeline
        # as Chrome trace-event JSON.
        from analytics_zoo_tpu.serving import fleet as _fleet
        from analytics_zoo_tpu.serving import tracecollect
        try:
            params = serving_params(load_config(args.config))
        except OSError:
            params = ServingParams()
        count = _fleet.read_scale(args.pidfile)
        docs = _fleet.replica_docs(
            args.pidfile, http_host=params.http_host,
            http_port=params.http_port, count=count) if count else {}
        by_rid = {str(d.get("replica_id") or f"replica-{i}"): d
                  for i, d in docs.items()}
        spans = tracecollect.collect(args.pidfile, health_docs=by_rid)
        if not spans:
            print(json.dumps(
                {"error": "no span spools found (nothing matching "
                          f"{args.pidfile}*.spans.jsonl — is tracing on "
                          "and the deployment running/ran?)"}),
                file=sys.stderr)
            return 1
        if args.chrome:
            tracecollect.export_chrome_trace(spans, args.chrome)
            print(json.dumps({"chrome_trace": args.chrome,
                              "spans": len(spans)}))
            return 0
        if args.slowest is not None:
            print(json.dumps(
                {"slowest": tracecollect.slowest(spans, args.slowest),
                 "spans": len(spans)}))
            return 0
        if not args.value:
            print(json.dumps({"error": "pass a trace_id (or --slowest N "
                                       "/ --chrome PATH)"}),
                  file=sys.stderr)
            return 1
        doc = tracecollect.reconstruct(spans, args.value)
        print(json.dumps(doc))
        return 0 if doc.get("found") else 1
    if args.action == "usage":
        # usage metering rollup (PR 19): load every replica's usage
        # journal (rotated generations included), normalize the drain
        # clocks, and sum the per-interval deltas by tenant or model.
        # Works on a STOPPED deployment — the journal survives `manager
        # stop` precisely so billing can run after the fact.
        from analytics_zoo_tpu.serving import tracecollect
        paths = tracecollect.find_usage_spools(args.pidfile)
        if not paths:
            print(json.dumps(
                {"error": "no usage journals found (nothing matching "
                          f"{args.pidfile}*.usage.jsonl — is metering "
                          "on and the deployment running/ran?)"}),
                file=sys.stderr)
            return 1
        records = tracecollect.load_usage(paths)
        doc = tracecollect.aggregate_usage(records, by=args.by,
                                           since=args.since)
        doc["journals"] = len(paths)
        if args.json_:
            print(json.dumps(doc))
            return 0
        hdr = (f"{args.by:<24} {'records':>10} {'tokens':>10} "
               f"{'device_s':>12} {'bytes':>12} {'sheds':>8}")
        print(hdr)
        print("-" * len(hdr))
        for key, vals in doc["usage"].items():
            print(f"{key:<24} {vals['records']:>10} {vals['tokens']:>10} "
                  f"{vals['device_s']:>12} {vals['bytes']:>12} "
                  f"{vals['sheds']:>8}")
        print(f"({doc['intervals']} journal interval(s) across "
              f"{doc['journals']} journal(s))")
        return 0
    if args.action == "metrics":
        # live metrics snapshot (PR 4).  Preferred source: the daemon's own
        # /metrics endpoint (exactly what a scraper sees, including
        # ?format=prom); fallback: derive the JSON document from the
        # health.json snapshot the daemon writes every second.
        try:
            params = serving_params(load_config(args.config))
        except OSError:
            params = ServingParams()       # no config: snapshot-only path
        if args.all_replicas:
            # fleet-wide aggregation (PR 10): sum the per-replica
            # registries — the same serving/fleet.py path the autoscaler's
            # ManagerFleet collector consumes
            from analytics_zoo_tpu.serving import fleet as _fleet
            count = _fleet.read_scale(args.pidfile)
            if args.prom:
                texts = _fleet.scrape_prometheus(
                    count, http_host=params.http_host,
                    http_port=params.http_port)
                if not texts:
                    print(json.dumps(
                        {"error": "--all-replicas --prom needs reachable "
                                  "replica probe ports (params.http_port "
                                  "+ a running --replicas deployment)"}),
                        file=sys.stderr)
                    return 1
                out = _fleet.merge_prometheus(texts)
                asnap = _fleet.autoscaler_snapshot(args.pidfile)
                if asnap and asnap.get("prom"):
                    out += asnap["prom"]   # controller series ride along
                lbsnap = _fleet.lb_snapshot(args.pidfile)
                if lbsnap and lbsnap.get("prom"):
                    # PR 13 satellite: the front door's own exposition
                    # (lb_requests_total / lb_retries_total / member
                    # gauges) joins the fleet scrape
                    out += lbsnap["prom"]
                print(out, end="")
                return 0
            docs = _fleet.replica_docs(args.pidfile,
                                       http_host=params.http_host,
                                       http_port=params.http_port,
                                       count=count)
            if not docs:
                print(json.dumps(
                    {"error": "no replica health docs (not running as a "
                              "--replicas deployment, or none written "
                              "yet)"}), file=sys.stderr)
                return 1
            doc = _fleet.fleet_metrics(docs,
                                       lb=_fleet.lb_snapshot(args.pidfile))
            asnap = _fleet.autoscaler_snapshot(args.pidfile)
            if asnap:
                doc["autoscaler"] = {
                    "decisions": asnap.get("decisions", [])[-20:],
                    "metrics": asnap.get("metrics", {})}
            print(json.dumps(doc))
            return 0
        if params.http_port:
            import urllib.request
            url = (f"http://{params.http_host}:{params.http_port}/metrics"
                   + ("?format=prom" if args.prom else ""))
            try:
                with urllib.request.urlopen(url, timeout=2.0) as resp:
                    body = resp.read().decode()
                print(body if args.prom else json.dumps(json.loads(body)))
                return 0
            except Exception as e:  # noqa: BLE001 — daemon down/unreachable
                print(json.dumps({"warning": f"probe endpoint {url} "
                                             f"unreachable "
                                             f"({type(e).__name__}: {e}); "
                                             "falling back to the health "
                                             "snapshot"}), file=sys.stderr)
        if args.prom:
            print(json.dumps({"error": "--prom needs a reachable "
                                       "params.http_port probe endpoint"}),
                  file=sys.stderr)
            return 1
        health = read_health()
        if health is None:
            print(json.dumps({"error": "no health snapshot (serving not "
                                       "running, or not yet written)"}),
                  file=sys.stderr)
            return 1
        pid = read_pid()
        doc = ClusterServing.metrics_from_health(health)
        if pid is None or not alive(pid):
            doc["stale"] = True            # snapshot outlived its daemon
        print(json.dumps(doc))
        return 0
    if args.action == "replay":
        # dead-letter replay (ROADMAP open item): re-enqueue quarantined
        # records after a fix — works against the live daemon's backend
        # (file/redis are cross-process), no model load needed
        queue = build_queue(load_config(args.config))
        sub = args.filter
        filt = None if sub is None else (
            lambda e: sub in str(e.get("uri", ""))
            or sub in str(e.get("error", "")))
        out = queue.replay_dead_letters(filter=filt)
        # admission_open=false explains a 0-replayed run: a drained queue
        # rejects re-enqueues until serving starts again (which reopens it)
        print(json.dumps({"replayed": len(out["replayed"]),
                          "skipped": len(out["skipped"]),
                          "uris": out["replayed"],
                          "admission_open": bool(
                              queue.health().get("admission_open", True))}))
        return 0
    if args.action == "scale":
        # resize a running --replicas supervisor: write the desired count,
        # the supervisor's poll loop spawns/drains to match
        if args.value is None:
            print(json.dumps({"error": "scale needs a target count: "
                                       "manager scale N"}), file=sys.stderr)
            return 1
        n = int(args.value)
        pid = read_pid()
        if pid is None or not alive(pid):
            print(json.dumps({"error": "serving not running"}),
                  file=sys.stderr)
            return 1
        if not os.path.exists(_scale_path(args.pidfile)):
            print(json.dumps({"error": "not running as a replica "
                                       "supervisor (start with "
                                       "--replicas N)"}), file=sys.stderr)
            return 1
        with open(_scale_path(args.pidfile), "w") as f:
            f.write(str(n))
        print(json.dumps({"replicas": n}))
        return 0
    if args.action == "status":
        pid = read_pid()
        up = pid is not None and alive(pid)
        out = {"running": up, "pid": pid if up else None}
        if os.path.exists(_scale_path(args.pidfile)):
            # replica-supervisor deployment: per-replica liveness
            try:
                with open(_scale_path(args.pidfile)) as f:
                    desired = int(f.read().strip())
            except (OSError, ValueError):
                desired = 0
            replicas = {}
            warming = 0
            for i in range(desired):
                rp = _replica_pidfile(args.pidfile, i)
                try:
                    with open(rp) as f:
                        rpid = int(f.read().strip())
                except (OSError, ValueError):
                    rpid = None
                member = {"pid": rpid,
                          "alive": rpid is not None and alive(rpid)}
                # zero cold start (PR 11): per-replica warm-up state off
                # the health snapshot, so an operator can see WHY a fresh
                # replica is not taking traffic yet (warming k/n) without
                # curling its probe port
                try:
                    with open(_health_path(rp)) as f:
                        doc = json.load(f)
                except (OSError, ValueError):
                    doc = None
                if isinstance(doc, dict):
                    w = doc.get("warmup") or {}
                    if w.get("state") and w["state"] != "off":
                        member["warmup"] = {
                            k: w.get(k)
                            for k in ("state", "compiled", "total",
                                      "seconds")}
                        if w["state"] in ("pending", "warming"):
                            warming += 1
                    member["ready"] = bool(
                        (doc.get("ready") or {}).get("ready"))
                    if doc.get("cold_start_s") is not None:
                        member["cold_start_s"] = doc["cold_start_s"]
                    if doc.get("model_version") is not None:
                        # rollout (PR 16): which registry version this
                        # replica serves — mixed mid-rollout is normal
                        member["model_version"] = doc["model_version"]
                replicas[f"r{i}"] = member
            out["replicas"] = {"desired": desired, "warming": warming,
                               "members": replicas}
            from analytics_zoo_tpu.serving import rollout as _rollout
            if os.path.exists(_rollout.state_path(args.pidfile)):
                out["rollout"] = _rollout.load_state(args.pidfile)
        health = read_health()
        if health is not None:
            out["health"] = health
        print(json.dumps(out))
        return 0
    if args.action == "health":
        # worker-level health (supervision state, restart counts, dead-letter
        # and breaker status) written by the serving daemon each second.
        # Cross-checked against pid liveness: a SIGKILLed daemon leaves a
        # stale snapshot behind and must not report healthy forever.
        health = read_health()
        if health is None:
            print(json.dumps({"error": "no health snapshot (serving not "
                                       "running, or not yet written)"}),
                  file=sys.stderr)
            return 1
        pid = read_pid()
        if pid is None or not alive(pid):
            health["running"] = False
            health["stale"] = True
            print(json.dumps(health), file=sys.stderr)
            return 1
        print(json.dumps(health))
        # a live daemon whose workers are FAILED is not healthy: exit
        # nonzero so liveness probes that check the code catch it
        return 0 if health.get("running") else 1
    if args.action in ("stop", "restart"):
        pid = read_pid()
        if pid is not None and alive(pid):
            os.kill(pid, signal.SIGTERM)
            for _ in range(50):
                if not alive(pid):
                    break
                time.sleep(0.1)
        if args.action == "stop":
            print(json.dumps({"stopped": True}))
            return 0
        if pid is not None and alive(pid):
            print(json.dumps({"error": f"pid {pid} did not terminate"}),
                  file=sys.stderr)
            return 1
    # start / restart
    pid = read_pid()
    if pid is not None and alive(pid):
        print(json.dumps({"error": f"already running (pid {pid})"}),
              file=sys.stderr)
        return 1
    if args.replicas is not None and args.replicas >= 1:
        # replica-supervisor deployment (PR 5) — including --replicas 1, so
        # a single-replica start can still be resized later with `manager
        # scale N`.  The shared-queue contract needs a CROSS-PROCESS
        # backend: an inproc queue would give every replica its own
        # private stream
        src = str(load_config(args.config).get("data", {})
                  .get("src", "redis"))
        if src == "inproc":
            print(json.dumps({"error": "--replicas needs a cross-process "
                                       "queue (data.src: redis or "
                                       "file:<dir>), not inproc"}),
                  file=sys.stderr)
            return 1
        if args.foreground:
            _run_supervisor(args.config, args.pidfile, args.replicas,
                            autoscale=args.autoscale, lb_port=args.lb_port,
                            prewarm=not args.no_prewarm)
            return 0
        pid = os.fork()
        if pid == 0:                       # child: detach and supervise
            os.setsid()
            _run_supervisor(args.config, args.pidfile, args.replicas,
                            autoscale=args.autoscale, lb_port=args.lb_port,
                            prewarm=not args.no_prewarm)
            return 0
        print(json.dumps({"started": True, "pid": pid,
                          "replicas": args.replicas}))
        return 0
    if args.foreground:
        _run_foreground(args.config, args.pidfile)
        return 0
    pid = os.fork()
    if pid == 0:                           # child: detach and serve
        os.setsid()
        _run_foreground(args.config, args.pidfile)
        return 0
    print(json.dumps({"started": True, "pid": pid}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
