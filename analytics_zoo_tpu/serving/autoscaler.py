"""Closed-loop serving autoscaler (PR 10 tentpole).

Everything an elastic system needs already exists in the serving plane —
per-replica telemetry registries (PR 4), lease-based horizontal replicas
with ``manager scale N`` (PR 5), and tunable data-plane knobs
``max_batch`` / ``preprocess_workers`` / ``inflight_batches`` (PR 3) — but
until now nothing closed the loop: capacity was whatever the operator
typed.  This module is the feedback controller:

- **signals** — ``FleetSignals``: one tick's cross-replica observation
  (queue depth + pending, cumulative served/shed/quarantined/reclaimed
  counters, per-stage p99s, per-replica heartbeat ages, current knob and
  topology targets).  Collected from live engines (``EngineFleet``) or
  from the manager supervisor's per-replica health docs (``ManagerFleet``
  via ``serving/fleet.py``) — the SAME aggregation ``manager metrics
  --all-replicas`` prints.
- **policy** — ``AutoscalerPolicy.decide(signals, now)``: a PURE decision
  function (no sleeps, no wall clock of its own — ``now`` is a parameter,
  which is what makes the golden decision-table tests possible).  Two
  actuator tiers with hysteresis:

  * *fast* — in-replica knob nudges: ``max_batch`` doubles/halves within
    the pow-2 bucket ladder, ``inflight_batches`` and
    ``preprocess_workers`` step by one, each gated by ``knob_dwell_s``;
  * *slow* — topology: scale up after overload persists ``dwell_up_s``
    (bounded by ``max_step`` and ``max_replicas``), scale down only after
    ``dwell_down_s`` of underload AND ``scale_down_cooldown_s`` since the
    last scale event (never flap), floored at ``min_replicas``.

  Overload and underload are separated by a dead band (``p99_high`` /
  ``p99_low`` fractions of the SLO, ``backlog_high`` / ``backlog_low``
  micro-batches per replica): signals between the bands HOLD, so the
  controller cannot oscillate around a single threshold.  A replica whose
  heartbeat goes stale (``heartbeat_stale_s``) is REPLACED (per-replica
  ``replace_cooldown_s``) — the SIGKILL-recovery path.

- **runtime** — ``Autoscaler``: a thread ticking every ``interval_s``;
  every action lands in ``autoscaler_decisions_total{action=}``, moves the
  ``autoscaler_target_*`` gauges, appends to a bounded decision log, and
  emits one log line — observable through ``manager metrics`` (the
  supervisor snapshots the controller registry next to the pidfile).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from analytics_zoo_tpu.common.observability import (MetricsRegistry,
                                                    _percentile)

logger = logging.getLogger(__name__)


@dataclass
class FleetSignals:
    """One controller tick's cross-replica observation.  Counters are
    CUMULATIVE (the policy differentiates them into rates between ticks);
    ``replicas`` is the live member count while ``desired`` is the current
    topology target (they differ while a scale event is in flight)."""

    queue_depth: int = 0
    pending: int = 0
    replicas: int = 0
    desired: int = 0
    served_total: float = 0.0
    shed_total: float = 0.0
    quarantined_total: float = 0.0
    reclaimed_total: float = 0.0
    e2e_p99_ms: Optional[float] = None
    preprocess_p99_ms: Optional[float] = None
    predict_p99_ms: Optional[float] = None
    heartbeat_ages: Dict[str, float] = field(default_factory=dict)
    # zero cold start (PR 11): members still compiling their warm-up set
    # (alive but not yet taking routed traffic) and the fleet's slowest
    # measured spawn-to-first-result — together they tell the controller
    # how stale its own scale-up decisions run (actuation lag)
    replicas_warming: int = 0
    cold_start_s: Optional[float] = None
    # lag-aware prediction (PR 12): the MEASURED scale_up-decision ->
    # fleet-at-target-and-warm wall from the last actuation
    # (autoscaler_actuation_lag_seconds).  The Autoscaler runtime injects
    # it each tick; the policy projects the backlog this far forward, so
    # capacity lands when the projected load arrives instead of one
    # actuation lag late.  None = never measured -> no lead applied.
    actuation_lag_s: Optional[float] = None
    # current fast-tier targets + their ceilings (from the engines' knobs())
    max_batch: int = 4
    max_batch_ceiling: int = 1024
    inflight_batches: int = 2
    inflight_ceiling: int = 64
    preprocess_workers: int = 1
    # overload armor (PR 17): the fleet's worst brownout ladder stage
    # (serving/brownout.py; 0 = healthy).  A browned-out fleet is by
    # definition shedding work to protect its SLO — the policy treats
    # stage >= 2 as overload pressure alongside the p99/backlog signals.
    brownout_stage: int = 0


@dataclass
class AutoscalerParams:
    """Controller tuning.  The defaults are deliberately conservative:
    scale-up reacts within a couple of dwell ticks, scale-down waits out
    ``dwell_down_s`` AND ``scale_down_cooldown_s`` so a bursty workload is
    never starved by an eager downscale."""

    slo_p99_ms: float = 500.0          # the latency objective
    min_replicas: int = 1
    max_replicas: int = 8
    interval_s: float = 1.0            # controller tick period
    # hysteresis dead band: overload above the high marks, underload below
    # the low marks, HOLD in between
    p99_high: float = 0.8              # overload when p99 > high * slo
    p99_low: float = 0.3               # underload only when p99 < low * slo
    backlog_high: float = 2.0          # ... backlog > high * max_batch/replica
    backlog_low: float = 0.25
    dwell_up_s: float = 2.0            # overload must persist this long
    dwell_down_s: float = 10.0         # underload must persist this long
    scale_down_cooldown_s: float = 30.0  # after ANY scale event
    max_step: int = 2                  # replicas added/removed per decision
    knob_dwell_s: float = 1.0          # min gap between fast-tier nudges
    max_preprocess_workers: int = 8
    # lag-aware scale-up lead (PR 12): project the backlog forward by the
    # measured actuation lag (capped at max_lead_s so one pathological
    # measurement cannot make every gentle ramp read as overload).
    # predictive=False restores the PR 10 reactive-only controller.
    predictive: bool = True
    max_lead_s: float = 30.0
    heartbeat_stale_s: float = 10.0    # replica presumed dead past this
    replace_cooldown_s: float = 10.0   # per-replica, between replacements

    @classmethod
    def from_dict(cls, d: Dict) -> "AutoscalerParams":
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        return cls(**{k: v for k, v in (d or {}).items() if k in known})


@dataclass
class Action:
    """One controller decision.  ``kind`` is the metrics label
    (``autoscaler_decisions_total{action=kind}``); ``target`` is the new
    replica count (scale), the replica id (replace), or None; ``knobs``
    carries the fast-tier nudge for retune actions."""

    kind: str                          # scale_up | scale_down |
    #                                    replace_replica | retune_up |
    #                                    retune_down
    reason: str
    target: Optional[object] = None
    knobs: Optional[Dict] = None


class AutoscalerPolicy:
    """The pure decision core.  All state is explicit instance state
    mutated only inside ``decide(signals, now)``; time enters ONLY through
    the ``now`` parameter, so tests drive the whole hysteresis / dwell /
    cooldown machinery with a fake clock and synthetic signals."""

    def __init__(self, params: Optional[AutoscalerParams] = None):
        self.params = params or AutoscalerParams()
        self._prev: Optional[FleetSignals] = None
        self._prev_now: Optional[float] = None
        self._overload_since: Optional[float] = None
        self._underload_since: Optional[float] = None
        self._last_scale: float = float("-inf")
        self._last_knob: float = float("-inf")
        self._last_replace: Dict[str, float] = {}
        self._baseline_knobs: Optional[Dict] = None

    # -- derived quantities ---------------------------------------------------
    def _rates(self, s: FleetSignals, now: float) -> Dict[str, float]:
        prev, prev_now = self._prev, self._prev_now
        self._prev, self._prev_now = s, now
        if prev is None or prev_now is None or now <= prev_now:
            return {"shed": 0.0, "reclaim": 0.0, "quarantine": 0.0,
                    "backlog_rate": 0.0}
        dt = now - prev_now
        backlog = max(0, s.queue_depth) + max(0, s.pending)
        prev_backlog = max(0, prev.queue_depth) + max(0, prev.pending)
        # max(0, ...): a replaced external member's counters leaving the sum
        # reads as a negative delta — clamp rather than poison the rate
        return {
            "shed": max(0.0, s.shed_total - prev.shed_total) / dt,
            "reclaim": max(0.0, s.reclaimed_total - prev.reclaimed_total)
            / dt,
            "quarantine": max(0.0, s.quarantined_total
                              - prev.quarantined_total) / dt,
            # signed: the predictive term only uses growth (> 0), but the
            # sign is useful in reasons/logs
            "backlog_rate": (backlog - prev_backlog) / dt}

    # -- the decision function ------------------------------------------------
    def decide(self, s: FleetSignals, now: float) -> List[Action]:
        p = self.params
        if self._baseline_knobs is None and s.replicas > 0:
            # the knob relax tier returns toward the operator's initial
            # settings, never below — swings must not ratchet the knobs.
            # Captured only from a tick with REAL members: before the
            # first replica reports (manager replicas spend seconds in
            # model load), the signals carry placeholder knob defaults,
            # and baselining to those would relax a configured deployment
            # down to them
            self._baseline_knobs = {
                "max_batch": s.max_batch,
                "inflight_batches": s.inflight_batches,
                "preprocess_workers": s.preprocess_workers}
        actions: List[Action] = []

        # 0) dead-replica replacement — independent of the load bands: a
        # stale heartbeat means orphaned leases and lost capacity either way
        for rid, age in sorted(s.heartbeat_ages.items()):
            if age <= p.heartbeat_stale_s:
                continue
            if now - self._last_replace.get(rid, float("-inf")) \
                    < p.replace_cooldown_s:
                continue
            self._last_replace[rid] = now
            actions.append(Action(
                "replace_replica", target=rid,
                reason=f"heartbeat stale {age:.1f}s > "
                       f"{p.heartbeat_stale_s:g}s"))

        rates = self._rates(s, now)
        desired = max(1, s.desired or s.replicas or 1)
        backlog = max(0, s.queue_depth) + max(0, s.pending)
        batch_quantum = max(1, s.max_batch) * desired
        p99 = s.e2e_p99_ms
        # lag-aware lead (PR 12): new capacity arrives one MEASURED
        # actuation lag after the decision, so judge the backlog where it
        # will be when the replicas are actually warm — a growing backlog
        # crosses the overload band one lead earlier, a shrinking or flat
        # one is unaffected (and underload always judges the RAW backlog,
        # so prediction can never cause a scale-down)
        projected = backlog
        if p.predictive and s.actuation_lag_s \
                and rates["backlog_rate"] > 0:
            lead = min(float(s.actuation_lag_s), p.max_lead_s)
            projected = backlog + rates["backlog_rate"] * lead
        overload = ((p99 is not None and p99 > p.p99_high * p.slo_p99_ms)
                    or projected > p.backlog_high * batch_quantum
                    or rates["shed"] > 0
                    # brownout (PR 17): a replica deep in the degradation
                    # ladder is already sacrificing quality — treat it as
                    # overload so capacity arrives before stage 3 sheds
                    or s.brownout_stage >= 2)
        underload = (backlog < p.backlog_low * batch_quantum
                     and rates["shed"] == 0
                     and s.brownout_stage == 0
                     and (p99 is None or p99 < p.p99_low * p.slo_p99_ms))

        # hysteresis bookkeeping: the dead band resets BOTH dwell timers, so
        # a borderline workload never accumulates dwell credit
        if overload:
            if self._overload_since is None:   # not `or now`: a dwell that
                self._overload_since = now     # started at t=0.0 is falsy
            self._underload_since = None
        elif underload:
            if self._underload_since is None:
                self._underload_since = now
            self._overload_since = None
        else:
            self._overload_since = self._underload_since = None

        # 1) fast tier: in-replica knob nudges, one per knob_dwell_s
        if overload and now - self._last_knob >= p.knob_dwell_s:
            knob = self._knob_up(s, p)
            if knob is not None:
                self._last_knob = now
                actions.append(Action("retune_up", knobs=knob,
                                      reason=self._band_reason(
                                          s, rates, backlog, batch_quantum,
                                          projected)))
        elif underload and now - self._last_knob >= p.knob_dwell_s:
            knob = self._knob_down(s)
            if knob is not None:
                self._last_knob = now
                actions.append(Action(
                    "retune_down", knobs=knob,
                    reason="underload: relaxing toward baseline"))

        # 2) slow tier: topology.  Scale-up re-arms its own dwell so a
        # still-climbing backlog pays a fresh dwell per step (max_step
        # bounds each step; the re-armed dwell bounds the step RATE).
        if overload and self._overload_since is not None \
                and now - self._overload_since >= p.dwell_up_s \
                and desired < p.max_replicas:
            target = min(desired + p.max_step, p.max_replicas)
            self._last_scale = now
            self._overload_since = now
            actions.append(Action(
                "scale_up", target=target,
                reason=self._band_reason(s, rates, backlog, batch_quantum,
                                         projected)))
        elif underload and self._underload_since is not None \
                and now - self._underload_since >= p.dwell_down_s \
                and now - self._last_scale >= p.scale_down_cooldown_s \
                and desired > p.min_replicas:
            target = max(desired - p.max_step, p.min_replicas)
            self._last_scale = now
            self._underload_since = now
            actions.append(Action(
                "scale_down", target=target,
                reason=f"underload: backlog {backlog} < "
                       f"{p.backlog_low:g}x{batch_quantum}, p99 "
                       f"{'n/a' if p99 is None else f'{p99:.0f}ms'} < "
                       f"{p.p99_low * p.slo_p99_ms:.0f}ms"))
        return actions

    @staticmethod
    def _band_reason(s: FleetSignals, rates, backlog, quantum,
                     projected=None) -> str:
        bits = []
        if s.e2e_p99_ms is not None:
            bits.append(f"p99 {s.e2e_p99_ms:.0f}ms")
        bits.append(f"backlog {backlog}/{quantum}")
        if projected is not None and projected > backlog:
            bits.append(
                f"projected {projected:.0f} in {s.actuation_lag_s:.1f}s "
                f"lag ({rates['backlog_rate']:+.1f}/s)")
        if rates["shed"] > 0:
            bits.append(f"shedding {rates['shed']:.1f}/s")
        return "overload: " + ", ".join(bits)

    def _knob_up(self, s: FleetSignals, p: AutoscalerParams) \
            -> Optional[Dict]:
        """The fast-tier ladder: widen the micro-batch first (pow-2 double,
        the cheapest capacity), then deepen the device pipeline, then grow
        the decode pool — the last only when preprocess, not predict, is
        the measured long pole."""
        if s.max_batch < s.max_batch_ceiling:
            return {"max_batch": min(s.max_batch * 2, s.max_batch_ceiling)}
        if s.inflight_batches < s.inflight_ceiling:
            return {"inflight_batches": s.inflight_batches + 1}
        pre_dominant = (s.preprocess_p99_ms is not None
                        and (s.predict_p99_ms is None
                             or s.preprocess_p99_ms >= s.predict_p99_ms))
        if pre_dominant and s.preprocess_workers < p.max_preprocess_workers:
            return {"preprocess_workers": s.preprocess_workers + 1}
        return None

    def _knob_down(self, s: FleetSignals) -> Optional[Dict]:
        if self._baseline_knobs is None:
            return None                    # no real members seen yet
        base = self._baseline_knobs
        if s.max_batch > base.get("max_batch", s.max_batch):
            return {"max_batch": max(s.max_batch // 2,
                                     base["max_batch"])}
        if s.inflight_batches > base.get("inflight_batches",
                                         s.inflight_batches):
            return {"inflight_batches": s.inflight_batches - 1}
        if s.preprocess_workers > base.get("preprocess_workers",
                                           s.preprocess_workers):
            return {"preprocess_workers": s.preprocess_workers - 1}
        return None


class Autoscaler:
    """The controller runtime: tick -> collect signals -> decide -> actuate
    -> record.  ``fleet`` is any object with ``signals() -> FleetSignals``,
    ``scale_to(n)``, ``retune(**knobs)`` and ``replace(replica_id)`` —
    ``EngineFleet`` (in-process) and ``ManagerFleet`` (supervisor) below
    are the two shipped implementations."""

    DECISION_LOG = 256

    def __init__(self, fleet, params: Optional[AutoscalerParams] = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.fleet = fleet
        self.params = params or AutoscalerParams()
        self.policy = AutoscalerPolicy(self.params)
        self.registry = registry or MetricsRegistry()
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._decisions: deque = deque(maxlen=self.DECISION_LOG)
        reg = self.registry
        self._m_decisions = reg.counter(
            "autoscaler_decisions_total",
            "Controller actions taken, by kind", labels=("action",))
        for kind in ("scale_up", "scale_down", "replace_replica",
                     "retune_up", "retune_down"):
            self._m_decisions.labels(action=kind).inc(0)
        self._m_ticks = reg.counter(
            "autoscaler_ticks_total", "Controller evaluation ticks")
        self._g_replicas = reg.gauge(
            "autoscaler_target_replicas", "Current topology target")
        self._g_max_batch = reg.gauge(
            "autoscaler_target_max_batch", "Current max_batch knob target")
        self._g_inflight = reg.gauge(
            "autoscaler_target_inflight",
            "Current inflight_batches knob target")
        self._g_pre = reg.gauge(
            "autoscaler_target_preprocess_workers",
            "Current preprocess_workers knob target")
        self._g_p99 = reg.gauge(
            "autoscaler_observed_p99_ms",
            "Fleet e2e p99 at the last controller tick")
        # actuation lag (PR 11): scale_up decision -> every new member
        # alive AND warm.  The whole point of zero-cold-start replicas is
        # shrinking this number — with it measured, a predictive policy
        # term has something to be judged against.
        self._g_lag = reg.gauge(
            "autoscaler_actuation_lag_seconds",
            "Last scale_up decision to fleet-at-target-and-warm")
        self._g_warming = reg.gauge(
            "autoscaler_replicas_warming",
            "Members still compiling their warm-up set")
        self._pending_scale: Optional[tuple] = None  # (t_decided, target)
        # last measured actuation lag, fed back into the policy's
        # predictive term (PR 12): the controller learns its own latency
        self._last_lag: Optional[float] = None

    # -- one evaluation -------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> List[Action]:
        now = self._clock() if now is None else now
        try:
            signals = self.fleet.signals()
        except Exception as e:  # noqa: BLE001 — a dead collector must not
            logger.warning("autoscaler: signal collection failed (%s: %s)",
                           type(e).__name__, e)   # kill the control loop
            return []
        self._m_ticks.inc()
        self._g_p99.set(signals.e2e_p99_ms
                        if signals.e2e_p99_ms is not None else float("nan"))
        self._g_warming.set(float(signals.replicas_warming))
        if self._pending_scale is not None:
            t_req, target = self._pending_scale
            if signals.replicas >= target and signals.replicas_warming == 0:
                lag = max(0.0, now - t_req)
                self._g_lag.set(lag)
                self._last_lag = lag
                self._pending_scale = None
                logger.info(
                    "autoscaler: scale-up actuated — %d replica(s) alive "
                    "and warm %.2fs after the decision (fleet cold-start "
                    "%s)", target, lag,
                    f"{signals.cold_start_s:.2f}s"
                    if signals.cold_start_s is not None else "n/a")
        if signals.actuation_lag_s is None:
            # feed the measured closed-loop latency back into the policy's
            # predictive term; a fleet that reports its own lag (future
            # signal sources) wins over our local measurement
            signals.actuation_lag_s = self._last_lag
        actions = self.policy.decide(signals, now)
        for act in actions:
            self._apply(act, signals)
            if act.kind == "scale_up":
                self._pending_scale = (now, int(act.target))
            elif act.kind == "scale_down":
                # the fleet is shrinking: a pending lag measurement would
                # trivially "complete" at the lower target — drop it
                self._pending_scale = None
        # current targets AFTER this tick's actions
        self._g_replicas.set(getattr(self.fleet, "desired", signals.desired))
        self._g_max_batch.set(signals.max_batch)
        self._g_inflight.set(signals.inflight_batches)
        self._g_pre.set(signals.preprocess_workers)
        return actions

    def _apply(self, act: Action, signals: FleetSignals) -> None:
        self._m_decisions.labels(action=act.kind).inc()
        entry = {"ts": time.time(), "action": act.kind,
                 "target": act.target, "knobs": act.knobs,
                 "reason": act.reason}
        self._decisions.append(entry)
        # incident flight recorder (PR 15): every actuated decision lands
        # on the process timeline next to LB/lifecycle events, so an
        # incident bundle shows WHAT the controller did around the burn
        try:
            from analytics_zoo_tpu.common.observability import get_recorder
            get_recorder().record(
                "autoscale", action=act.kind,
                target=act.target, reason=str(act.reason)[:200])
        except Exception:  # noqa: BLE001 — diagnostics only
            pass
        logger.info(
            "autoscaler: %s target=%s knobs=%s (%s) [depth=%d pending=%d "
            "replicas=%d/%d]", act.kind, act.target, act.knobs, act.reason,
            signals.queue_depth, signals.pending, signals.replicas,
            signals.desired)
        try:
            if act.kind in ("scale_up", "scale_down"):
                self.fleet.scale_to(int(act.target))
            elif act.kind == "replace_replica":
                self.fleet.replace(act.target)
            elif act.kind in ("retune_up", "retune_down"):
                self.fleet.retune(**(act.knobs or {}))
        except Exception as e:  # noqa: BLE001 — an actuator failure is
            # logged and retried by a later tick, never fatal to the loop
            logger.warning("autoscaler: actuating %s failed (%s: %s)",
                           act.kind, type(e).__name__, e)

    def decisions(self) -> List[Dict]:
        return list(self._decisions)

    def snapshot(self) -> Dict:
        """Machine-readable controller state: registry snapshot + the
        decision log — what the manager supervisor persists next to the
        pidfile so ``manager metrics`` can show it."""
        return {"ts": time.time(),
                "params": dict(self.params.__dict__),
                "metrics": self.registry.snapshot(),
                "prom": self.registry.to_prometheus(),
                "decisions": self.decisions()}

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "Autoscaler":
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="serving-autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.params.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the control loop must live
                logger.exception("autoscaler: tick failed")

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None


# -- in-process fleet (bench, tests, embedded serving) --------------------------

class EngineFleet:
    """An in-process replica fleet over ONE shared queue: the Autoscaler's
    actuator and signal source when the replicas are ClusterServing engines
    in this process (the bench and the chaos tests; production uses
    ``ManagerFleet`` over supervisor-forked processes).

    ``factory(replica_id) -> started ClusterServing`` builds a member;
    engines share the queue object and (typically) one InferenceModel.
    External members — e.g. a subprocess replica the chaos bench will
    SIGKILL — join via ``add_external(replica_id, heartbeat_fn,
    stats_fn)`` and are counted in the fleet signals; replacing one swaps
    in an in-process engine."""

    def __init__(self, factory: Callable[[str], object], queue,
                 initial: int = 1, name_prefix: str = "as",
                 drain_s: float = 2.0):
        self._factory = factory
        self.queue = queue
        self._prefix = name_prefix
        self._drain_s = drain_s
        self._lock = threading.Lock()
        self._engines: Dict[str, object] = {}
        self._external: Dict[str, Dict] = {}   # rid -> {heartbeat, stats}
        self._seq = 0
        self.desired = 0
        self.scale_to(max(0, int(initial)))

    # -- membership -----------------------------------------------------------
    def engines(self) -> List[object]:
        with self._lock:
            return list(self._engines.values())

    def add_external(self, replica_id: str,
                     heartbeat_fn: Callable[[], Optional[float]],
                     stats_fn: Optional[Callable[[], Optional[Dict]]]
                     = None) -> None:
        """Adopt a member this process does not own (a subprocess replica).
        ``heartbeat_fn() -> age seconds`` (None = unknown/gone);
        ``stats_fn() -> health-doc-like dict`` contributes its counters."""
        with self._lock:
            self._external[replica_id] = {"heartbeat": heartbeat_fn,
                                          "stats": stats_fn}
            self.desired += 1

    def _spawn_locked(self) -> str:
        self._seq += 1
        rid = f"{self._prefix}-{self._seq}"
        self._engines[rid] = self._factory(rid)
        return rid

    def scale_to(self, n: int) -> None:
        n = max(0, int(n))
        to_stop: List[object] = []
        with self._lock:
            self.desired = n
            while len(self._engines) + len(self._external) < n:
                self._spawn_locked()
            # scale-down: newest engines first; externals are never stopped
            # from here (this process doesn't own them)
            while len(self._engines) + len(self._external) > n \
                    and self._engines:
                # newest first, by spawn sequence (lexicographic sorting
                # would retire as-9 before as-10)
                rid = max(self._engines,
                          key=lambda r: int(r.rsplit("-", 1)[-1])
                          if r.rsplit("-", 1)[-1].isdigit() else -1)
                to_stop.append(self._engines.pop(rid))
        for engine in to_stop:
            # scale-down drain: flush this replica's in-flight work but
            # leave the SHARED queue's admission open for the survivors
            engine.shutdown(drain_s=self._drain_s, close_admission=False)

    def retune(self, **knobs) -> None:
        for engine in self.engines():
            engine.retune(**knobs)

    def replace(self, replica_id: str) -> None:
        """Swap out a dead/wedged member: an engine is hard-stopped (no
        drain — it is presumed wedged; its unacked claims redeliver via the
        lease) and a fresh engine takes its slot; an external member is
        simply dropped and replaced by an in-process engine."""
        dead = None
        with self._lock:
            if replica_id in self._external:
                self._external.pop(replica_id)
                self._spawn_locked()
                return
            for rid, engine in list(self._engines.items()):
                if rid == replica_id \
                        or getattr(engine, "replica_id", None) == replica_id:
                    dead = self._engines.pop(rid)
                    break
            if dead is None:
                return
            self._spawn_locked()
        dead.shutdown(drain_s=0, close_admission=False)

    def shutdown(self) -> None:
        with self._lock:
            engines, self._engines = list(self._engines.values()), {}
            self._external.clear()
            self.desired = 0
        for engine in engines:
            engine.shutdown(drain_s=self._drain_s)

    # -- signal collection ----------------------------------------------------
    @staticmethod
    def _merged_p99_ms(children) -> Optional[float]:
        samples: List[float] = []
        for child in children:
            samples.extend(child.recent())
        if not samples:
            return None
        return _percentile(sorted(samples), 99) * 1e3

    def signals(self) -> FleetSignals:
        engines = self.engines()
        with self._lock:
            external = dict(self._external)
            desired = self.desired
        try:
            qh = self.queue.health()
        except Exception:  # noqa: BLE001 — backend down: zeros, the
            qh = {}        # heartbeats still drive replacement
        served = shed = quarantined = reclaimed = 0.0
        warming = 0
        cold_start = None
        brownout = 0
        hb: Dict[str, float] = {}
        for e in engines:
            served += e.total_records
            shed += e.shed
            quarantined += e.dead_lettered
            reclaimed += e.reclaimed
            brownout = max(brownout, int(getattr(e, "brownout_stage", 0)
                                         or 0))
            hb[e.replica_id] = e._heartbeat_age()
            w = getattr(e, "_warm_state", None) or {}
            if w.get("state") in ("pending", "warming"):
                warming += 1
            cs = getattr(e, "_cold_start_s", None)
            if cs is not None:
                cold_start = cs if cold_start is None \
                    else max(cold_start, cs)
        for rid, ext in external.items():
            age = None
            try:
                age = ext["heartbeat"]()
            except Exception:  # noqa: BLE001 — unreadable = unknown
                pass
            hb[rid] = float("inf") if age is None else float(age)
            stats = None
            if ext["stats"] is not None:
                try:
                    stats = ext["stats"]()
                except Exception:  # noqa: BLE001
                    stats = None
            if isinstance(stats, dict):
                served += stats.get("total_records", 0)
                shed += stats.get("shed", 0)
                quarantined += stats.get("dead_lettered", 0)
                reclaimed += stats.get("reclaimed", 0)
        sig = FleetSignals(
            queue_depth=int(qh.get("depth", 0) or 0),
            pending=max(0, int(qh.get("pending", 0) or 0)),
            replicas=len(engines) + len(external),
            desired=desired,
            served_total=served, shed_total=shed,
            quarantined_total=quarantined, reclaimed_total=reclaimed,
            e2e_p99_ms=self._merged_p99_ms(
                e._e2e._default() for e in engines),
            preprocess_p99_ms=self._merged_p99_ms(
                e._stages["preprocess"] for e in engines),
            predict_p99_ms=self._merged_p99_ms(
                e._stages["predict"] for e in engines),
            heartbeat_ages=hb,
            replicas_warming=warming,
            cold_start_s=cold_start,
            brownout_stage=brownout)
        if engines:
            k = engines[0].knobs()
            sig.max_batch = int(k["max_batch"])
            sig.max_batch_ceiling = int(k["max_batch_ceiling"])
            sig.inflight_batches = int(k["inflight_batches"])
            sig.inflight_ceiling = int(k["inflight_ceiling"])
            sig.preprocess_workers = int(k["preprocess_workers"])
        return sig


# -- manager-supervisor fleet (production topology) -----------------------------

class ManagerFleet:
    """Autoscaler adapter for a ``manager start --replicas N`` deployment:
    signals come from the per-replica health docs (HTTP probe scrape with
    ``<pidfile>.rN.health.json`` fallback — ``serving/fleet.py``), topology
    is actuated through the supervisor's ``<pidfile>.replicas`` scale file
    (exactly what ``manager scale N`` writes), knob nudges through
    ``<pidfile>.knobs.json`` which every replica polls once a second and
    applies via ``ClusterServing.retune()``, and a stale replica is
    replaced by SIGKILLing its pid — the supervisor's crash-respawn loop
    brings up the successor."""

    def __init__(self, pidfile: str, http_host: str = "127.0.0.1",
                 http_port: Optional[int] = None,
                 max_replicas: int = 8):
        self.pidfile = pidfile
        self.http_host = http_host
        self.http_port = http_port
        self.max_replicas = int(max_replicas)

    # the supervisor's files (mirrors serving/manager.py helpers; kept
    # string-level so this module never imports the manager's jax deps)
    @property
    def _scale_path(self) -> str:
        return self.pidfile + ".replicas"

    @property
    def knobs_path(self) -> str:
        return self.pidfile + ".knobs.json"

    @property
    def desired(self) -> int:
        from analytics_zoo_tpu.serving.fleet import read_scale
        return read_scale(self.pidfile)

    def signals(self) -> FleetSignals:
        from analytics_zoo_tpu.serving import fleet as _fleet
        docs = _fleet.replica_docs(self.pidfile, http_host=self.http_host,
                                   http_port=self.http_port,
                                   count=max(self.desired,
                                             self.max_replicas))
        agg = _fleet.aggregate_health(docs)
        knobs = agg.get("knobs") or {}
        return FleetSignals(
            queue_depth=int(agg.get("queue_depth", 0)),
            pending=max(0, int(agg.get("pending", 0))),
            replicas=int(agg.get("replicas_alive", 0)),
            desired=self.desired,
            served_total=float(agg.get("served", 0)),
            shed_total=float(agg.get("shed", 0)),
            quarantined_total=float(agg.get("quarantined", 0)),
            reclaimed_total=float(agg.get("reclaimed", 0)),
            e2e_p99_ms=agg.get("e2e_p99_ms"),
            preprocess_p99_ms=agg.get("preprocess_p99_ms"),
            predict_p99_ms=agg.get("predict_p99_ms"),
            heartbeat_ages=dict(agg.get("heartbeat_ages", {})),
            replicas_warming=int(agg.get("replicas_warming", 0) or 0),
            cold_start_s=agg.get("cold_start_s"),
            brownout_stage=int(agg.get("brownout_stage") or 0),
            max_batch=int(knobs.get("max_batch", 4)),
            max_batch_ceiling=int(knobs.get("max_batch_ceiling", 1024)),
            inflight_batches=int(knobs.get("inflight_batches", 2)),
            inflight_ceiling=int(knobs.get("inflight_ceiling", 64)),
            preprocess_workers=int(knobs.get("preprocess_workers", 1)))

    def scale_to(self, n: int) -> None:
        tmp = self._scale_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(max(0, int(n))))
        os.replace(tmp, self._scale_path)

    def retune(self, **knobs) -> None:
        current: Dict = {}
        try:
            with open(self.knobs_path) as f:
                current = json.load(f) or {}
        except (OSError, ValueError):
            pass
        current.update(knobs)
        tmp = self.knobs_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(current, f)
        os.replace(tmp, self.knobs_path)

    def replace(self, replica_id: str) -> None:
        """SIGKILL the stale replica (it is presumed wedged — a graceful
        SIGTERM could hang in its drain); the supervisor's respawn loop
        starts the replacement within its 1 s rate limit and the survivors
        reclaim the orphaned leases meanwhile."""
        import signal as _signal
        index = str(replica_id).rsplit("-", 1)[-1]
        if not index.isdigit():
            logger.warning("autoscaler: cannot map replica id %r to a "
                           "supervisor slot", replica_id)
            return
        try:
            with open(f"{self.pidfile}.r{index}") as f:
                pid = int(f.read().strip())
            os.kill(pid, _signal.SIGKILL)
            logger.warning("autoscaler: SIGKILLed stale replica %s "
                           "(pid %d); supervisor will respawn it",
                           replica_id, pid)
        except (OSError, ValueError) as e:
            logger.warning("autoscaler: replacing %s failed (%s: %s)",
                           replica_id, type(e).__name__, e)
